"""MFU ablation harness: where does the ResNet-50 step time go?

VERDICT r3 item 1 / r4 follow-up: the headline step is at MFU ~0.30 with
~1.5x headroom vs tuned TPU ResNet implementations. This script decomposes the
compiled step into its phases and sweeps the knobs that plausibly matter, each
measured as a SEPARATE jitted program on the live chip:

  fwd            forward + loss only
  fwd_bwd        value_and_grad (no optimizer update)
  full           value_and_grad + SGD-momentum update (the bench's step)

per batch in --batches (default "256,512"), NHWC layout, bf16 compute.

Usage:  python scripts/mfu_ablation.py [--batches 256,512,1024] [--iters 30]
Prints one JSON line per leg; exits 0 even on failure legs (error recorded).
"""
from __future__ import annotations

import argparse
import json
import time


def _time_compiled(fn, args, iters):
    out = fn(*args)  # compile
    jax_block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax_block(out)
    return (time.perf_counter() - t0) / iters


def jax_block(tree):
    import jax
    jax.block_until_ready(tree)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batches", default="256,512")
    p.add_argument("--iters", type=int, default=30)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.nn.layout import set_image_format
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init(compute_dtype=jnp.bfloat16)
    dev = Engine.devices()[0]
    set_image_format("NHWC")

    from bigdl_tpu.models.resnet import ResNet

    # analytic fwd FLOPs/img for ResNet-50 @224 and the per-generation peak
    # table — same constants the bench uses
    from bigdl_tpu.benchmark import _ANALYTIC_STEP_FLOPS_PER_UNIT, _peak_flops
    step_flops_per_img = _ANALYTIC_STEP_FLOPS_PER_UNIT["resnet50"]
    peak = _peak_flops(Engine.devices()[0].device_kind)  # None -> mfu: null

    for batch in [int(b) for b in args.batches.split(",")]:
        model = ResNet(1000, {"depth": 50, "dataSet": "ImageNet",
                              "conv1SpaceToDepth": True})
        criterion = nn.ClassNLLCriterion()
        params = model.get_params()
        mstate = model.get_state()
        rng = np.random.default_rng(0)
        x = jax.device_put(
            jnp.asarray(rng.normal(size=(batch, 224, 224, 3)), jnp.bfloat16), dev)
        y = jax.device_put(jnp.asarray(
            rng.integers(0, 1000, size=(batch,)), jnp.int32), dev)
        params = jax.device_put(params, dev)
        mstate = jax.device_put(mstate, dev)

        def loss_fn(p, s, xx, yy):
            # mirror the optimizer's mixed-precision policy: fp32 masters,
            # bf16 compute (cast inside the step so grads come back fp32)
            from bigdl_tpu.nn.precision import cast_floating
            pb = cast_floating(p, jnp.bfloat16)
            out, s2 = model.apply(pb, s, xx, training=True, rng=None)
            return criterion.apply(out, yy), s2

        fwd = jax.jit(lambda p, s, xx, yy: loss_fn(p, s, xx, yy)[0])
        grad = jax.jit(lambda p, s, xx, yy: jax.value_and_grad(
            lambda pp: loss_fn(pp, s, xx, yy)[0])(p))

        mom = jax.tree.map(jnp.zeros_like, params)

        @jax.jit
        def full(p, m, s, xx, yy):
            l, g = jax.value_and_grad(lambda pp: loss_fn(pp, s, xx, yy)[0])(p)
            m2 = jax.tree.map(lambda mi, gi: 0.9 * mi + gi, m, g)
            p2 = jax.tree.map(lambda pi, mi: pi - 0.01 * mi, p, m2)
            return l, p2, m2

        legs = {}
        try:
            legs["fwd"] = _time_compiled(fwd, (params, mstate, x, y), args.iters)
            legs["fwd_bwd"] = _time_compiled(grad, (params, mstate, x, y), args.iters)
            legs["full"] = _time_compiled(full, (params, mom, mstate, x, y), args.iters)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"batch": batch, "error": f"{type(e).__name__}: {e}"[:300]}))
            continue
        rec = {"batch": batch, "device": dev.device_kind}
        for k, v in legs.items():
            ips = batch / v
            rec[k + "_ms"] = round(v * 1e3, 2)
            rec[k + "_img_s"] = round(ips, 1)
        # MFU on the full step (the bench convention: fwd x3); null when the
        # device's peak is unknown — never computed against an assumed peak
        rec["full_mfu"] = (round(step_flops_per_img * rec["full_img_s"] / peak, 4)
                           if peak else None)
        # implied split: update cost = full - fwd_bwd; bwd cost = fwd_bwd - fwd
        rec["bwd_over_fwd"] = round(
            (legs["fwd_bwd"] - legs["fwd"]) / legs["fwd"], 2)
        rec["update_ms"] = round((legs["full"] - legs["fwd_bwd"]) * 1e3, 2)
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
