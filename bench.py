"""Benchmark harness. Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``

Primary metric (BASELINE.md): ResNet-50 ImageNet images/sec/chip. Until the ResNet-50
model lands, benches the best available flagship (LeNet training throughput). The
reference's published number is unavailable (BASELINE.json.published empty, mount empty),
so ``vs_baseline`` is null until a citable reference value exists.
"""

from __future__ import annotations

import json
import time

import numpy as np


def bench_train_throughput(model_name: str = "lenet", batch: int = 256,
                           iters: int = 30, warmup: int = 5):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    if model_name == "lenet":
        from bigdl_tpu.models.lenet import LeNet5
        model = LeNet5(10)
        x = np.random.default_rng(0).normal(size=(batch, 1, 28, 28)).astype(np.float32)
        y = np.random.default_rng(1).integers(0, 10, size=(batch,)).astype(np.int32)
    else:
        raise ValueError(f"unknown model {model_name}")

    criterion = nn.ClassNLLCriterion()
    method = SGD(learningrate=0.01, momentum=0.9, dampening=0.0)
    params, mstate = model.get_params(), model.get_state()
    ostate = method.init_state(params)

    def step(params, mstate, ostate, step_idx, inp, target):
        def loss_fn(p):
            out, new_ms = model.apply(p, mstate, inp, training=True, rng=None)
            return criterion.apply(out, target), new_ms
        (loss, new_ms), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_p, new_os = method.update(params, grads, ostate, step_idx)
        return new_p, new_ms, new_os, loss

    jit_step = jax.jit(step, donate_argnums=(0, 1, 2))
    inp, target = jax.device_put(x), jax.device_put(y)

    for i in range(warmup):
        params, mstate, ostate, loss = jit_step(
            params, mstate, ostate, jnp.asarray(i, jnp.int32), inp, target)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for i in range(iters):
        params, mstate, ostate, loss = jit_step(
            params, mstate, ostate, jnp.asarray(i, jnp.int32), inp, target)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return batch * iters / dt


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--model", default="lenet")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--iters", type=int, default=30)
    args = p.parse_args()

    imgs_per_sec = bench_train_throughput(args.model, args.batch, args.iters)
    print(json.dumps({
        "metric": f"{args.model}_train_images_per_sec_per_chip",
        "value": round(imgs_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": None,
    }))
