"""Driver-contract shim: the benchmark lives in ``bigdl_tpu.benchmark`` so the
installed wheel's ``bigdl-tpu bench`` works without a checkout. This file keeps
the contract entry point ``python bench.py`` at the repo root."""

import sys

from bigdl_tpu.benchmark import main

if __name__ == "__main__":
    sys.exit(main())
