"""Mixed-precision policy — bf16 compute with fp32 master state.

Reference parity (SURVEY.md §7.1, §7.3(5)): the reference's FP16 gradient *compression*
(ParameterProcessor halving wire traffic) has no TPU analog worth keeping — ICI is fast and
XLA owns the collectives. What matters on TPU is *compute* precision: the MXU runs bfloat16
matmuls/convs at ~2x the fp32 rate and always accumulates in fp32 internally, so the
numerically-sound policy is:

- **master params fp32** — the optimizer state and update run in fp32; params are cast to
  the compute dtype *inside* the jitted step (the cast's transpose makes gradients fp32);
- **activations bf16** — inputs cast once at the step boundary;
- **fp32 islands** — softmax/log-softmax (criterions see fp32 logits), batch-norm batch
  statistics, and attention's streaming-softmax accumulators stay fp32;
- **no loss scaling** — bfloat16 keeps fp32's exponent range, so the fp16-style scaled-loss
  dance is unnecessary (and is deliberately not implemented).

Enable via ``Engine.init(compute_dtype=jnp.bfloat16)`` or ``BIGDL_COMPUTE_DTYPE=bf16``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cast_floating(tree, dtype):
    """Cast every floating leaf of a pytree to ``dtype``; integer/bool leaves pass
    through untouched (targets, masks, valid counts)."""
    def _cast(x):
        x = jnp.asarray(x)
        return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x
    return jax.tree_util.tree_map(_cast, tree)
