"""Cosine-similarity layers.

Reference parity (SURVEY.md §2.1 layer zoo, expected ``<dl>/nn/Cosine.scala`` /
``CosineDistance.scala`` — unverified, mount empty): ``Cosine`` scores the input
against learnable class prototypes by cosine similarity; ``CosineDistance``
computes the rowwise cosine similarity of a pair of tensors.

TPU-native: one normalised matmul on the MXU (Cosine) / one fused reduction on
the VPU (CosineDistance).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import AbstractModule, TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform
from bigdl_tpu.utils.table import Table


def cosine_similarity(x, y, axis: int = -1, eps: float = 1e-12):
    """Shared clipped cosine similarity (layers + criterions use this one
    definition so epsilon/broadcasting fixes land everywhere at once)."""
    return jnp.sum(x * y, axis) / jnp.clip(
        jnp.linalg.norm(x, axis=axis) * jnp.linalg.norm(y, axis=axis), eps)


class Cosine(TensorModule):
    """``out[b, o] = cos(x[b], w[o])`` with learnable prototypes
    ``w: (output_size, input_size)``."""

    def __init__(self, input_size: int, output_size: int,
                 w_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.w_init = w_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.asarray(
            self.w_init.init((self.output_size, self.input_size),
                             fan_in=self.input_size, fan_out=self.output_size))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        if input.ndim > 2:
            raise ValueError(
                f"Cosine expects (N, {self.input_size}) or ({self.input_size},), "
                f"got {input.shape}; wrap with Bottle for higher-rank inputs")
        x = input if input.ndim == 2 else input[None]
        w = params["weight"]
        xn = x / jnp.clip(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
        wn = w / jnp.clip(jnp.linalg.norm(w, axis=-1, keepdims=True), 1e-12)
        out = xn @ wn.T
        if input.ndim == 1:
            out = out[0]
        return out, state

    def __repr__(self):
        return f"Cosine({self.input_size} -> {self.output_size})"


class CosineDistance(AbstractModule):
    """Rowwise cosine similarity of a Table/tuple pair (x1, x2) → (N,)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        x1, x2 = (input[1], input[2]) if isinstance(input, Table) \
            else (input[0], input[1])
        return cosine_similarity(x1, x2), state
