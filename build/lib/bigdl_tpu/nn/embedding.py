"""Embedding layers.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/LookupTable.scala`` — unverified):
``LookupTable(nIndex, nOutput)`` maps 1-based integer indices to rows of a learnable
(nIndex, nOutput) weight; options paddingValue / maxNorm / normType.

TPU-native: the lookup is one gather (``weight[idx]``); its VJP is a scatter-add that XLA
emits natively — no sparse-gradient special-casing like Torch's. max-norm renorm is applied
functionally in the forward pass (matching Torch semantics of renorm-before-lookup).

Out-of-range behaviour differs from the reference: the reference raises on bad indices, but
a jitted gather cannot — JAX *clamps* out-of-bounds indices and wraps negative ones, so an
off-by-one in user data silently reads a wrong row. Callers can assert ranges host-side;
``zero_based=True`` is the safest choice for new code.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomNormal


class LookupTable(TensorModule):
    def __init__(self, n_index: int, n_output: int, padding_value: float = 0.0,
                 max_norm: float = float("inf"), norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False,
                 w_init: Optional[InitializationMethod] = None,
                 zero_based: bool = False):
        super().__init__()
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.w_init = w_init or RandomNormal(0.0, 1.0)
        self.zero_based = zero_based
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.asarray(
            self.w_init.init((self.n_index, self.n_output),
                             fan_in=self.n_index, fan_out=self.n_output))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        idx = input.astype(jnp.int32)
        if not self.zero_based:
            idx = idx - 1  # reference/Torch indices are 1-based
        w = params["weight"]
        if self.max_norm != float("inf"):
            norms = jnp.power(
                jnp.sum(jnp.power(jnp.abs(w), self.norm_type), axis=1, keepdims=True),
                1.0 / self.norm_type)
            scale = jnp.minimum(1.0, self.max_norm / (norms + 1e-7))
            w = w * scale
        out = w[idx]
        if self.padding_value != 0.0:
            pad_idx = int(self.padding_value) - (0 if self.zero_based else 1)
            out = jnp.where((idx == pad_idx)[..., None], 0.0, out)
        return out, state

    def __repr__(self):
        return f"LookupTable({self.n_index} -> {self.n_output})"


class HashBucketEmbedding(LookupTable):
    """Embedding over hashed ids: arbitrary (possibly unbounded) non-negative
    integer ids are mixed with a Fibonacci multiplicative hash and mapped into
    ``n_buckets`` rows. The analog of the reference recommendation examples'
    hashing trick for out-of-vocabulary users/items (SURVEY.md §2.5 Examples:
    NCF / Wide&Deep), without the host-side feature dictionary.

    Always zero-based (ids are raw hashes, not Torch 1-based vocab indices).
    """

    def __init__(self, n_buckets: int, n_output: int,
                 w_init: Optional[InitializationMethod] = None):
        super().__init__(n_buckets, n_output, w_init=w_init, zero_based=True)

    def apply(self, params, state, input, *, training=False, rng=None):
        h = input.astype(jnp.uint32)
        # murmur3-style 32-bit finalizer: full avalanche, so every bucket in
        # [0, n_buckets) is reachable for any n_buckets up to 2^32 — a handful
        # of fused integer ops on the VPU
        h = h ^ (h >> jnp.uint32(16))
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> jnp.uint32(13))
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> jnp.uint32(16))
        bucket = (h % jnp.uint32(self.n_index)).astype(jnp.int32)
        return super().apply(params, state, bucket, training=training, rng=rng)

    def __repr__(self):
        return f"HashBucketEmbedding({self.n_index} buckets -> {self.n_output})"
