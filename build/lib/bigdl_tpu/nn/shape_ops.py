"""Shape-manipulation layers.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/Reshape.scala``, ``View.scala``,
``Squeeze.scala``, ``Unsqueeze.scala``, ``Transpose.scala``, ``Padding.scala``,
``Narrow.scala``, ``Select.scala``, ``SplitTable.scala``, ``Contiguous.scala`` — unverified).
All are metadata-only ops under XLA (free at runtime when fused).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import AbstractModule, TensorModule
from bigdl_tpu.utils.table import T, Table


class Reshape(TensorModule):
    """Reshape non-batch dims to ``size``; ``batch_mode=None`` auto-detects a batch dim:
    input is treated as batched when its non-batch dims hold exactly ``prod(size)``
    elements (``ndim >= 2 and prod(shape[1:]) == prod(size)``)."""

    def __init__(self, size: Sequence[int], batch_mode: bool | None = None):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def apply(self, params, state, input, *, training=False, rng=None):
        batched = self.batch_mode
        if batched is None:
            import numpy as np
            # batch dim preserved whenever the non-batch dims hold exactly the target
            # element count (robust for batch size 1, unlike ndim heuristics)
            batched = (input.ndim >= 2 and
                       int(np.prod(input.shape[1:])) == int(np.prod(self.size)))
        if batched:
            return input.reshape((input.shape[0],) + self.size), state
        return input.reshape(self.size), state

    def __repr__(self):
        return f"Reshape({'x'.join(map(str, self.size))})"


class View(Reshape):
    """Alias of Reshape with batch handling (reference ``View`` with num_input_dims)."""


class Flatten(TensorModule):
    def apply(self, params, state, input, *, training=False, rng=None):
        return input.reshape(input.shape[0], -1), state


class Squeeze(TensorModule):
    def __init__(self, dim: int | None = None, num_input_dims: int | None = None):
        super().__init__()
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        if self.dim is None:
            return jnp.squeeze(input), state
        return jnp.squeeze(input, axis=self.dim - 1), state


class Unsqueeze(TensorModule):
    def __init__(self, pos: int, num_input_dims: int | None = None):
        super().__init__()
        self.pos = pos

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.expand_dims(input, axis=self.pos - 1), state


class Transpose(TensorModule):
    """Swap listed (1-based) dim pairs in order (reference semantics)."""

    def __init__(self, permutations: Sequence[tuple[int, int]]):
        super().__init__()
        self.permutations = [(a - 1, b - 1) for a, b in permutations]

    def apply(self, params, state, input, *, training=False, rng=None):
        perm = list(range(input.ndim))
        for a, b in self.permutations:
            perm[a], perm[b] = perm[b], perm[a]
        return jnp.transpose(input, perm), state


class Select(TensorModule):
    """Select index ``index`` (1-based; negative from end) along dim (1-based)."""

    def __init__(self, dim: int, index: int):
        super().__init__()
        self.dim, self.index = dim, index

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        idx = self.index - 1 if self.index > 0 else input.shape[axis] + self.index
        return jnp.take(input, idx, axis=axis), state


class Narrow(TensorModule):
    """Slice ``length`` elements starting at ``offset`` (1-based) along dim."""

    def __init__(self, dim: int, offset: int, length: int = 1):
        super().__init__()
        self.dim, self.offset, self.length = dim, offset, length

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        start = self.offset - 1
        length = self.length
        if length < 0:
            length = input.shape[axis] - start + length + 1
        return jnp.take(input, jnp.arange(start, start + length), axis=axis), state


class SplitTable(AbstractModule):
    """Split a tensor along dim (1-based) into a Table of slices."""

    def __init__(self, dim: int, num_input_dims: int = -1):
        super().__init__()
        self.dim = dim
        self.num_input_dims = num_input_dims

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        if self.num_input_dims > 0 and input.ndim == self.num_input_dims + 1:
            axis += 1
        parts = [jnp.squeeze(p, axis=axis)
                 for p in jnp.split(input, input.shape[axis], axis=axis)]
        return T(*parts), state


class Padding(TensorModule):
    """Pad ``pad`` entries (negative → before, positive → after) along dim with value."""

    def __init__(self, dim: int, pad: int, num_input_dims: int = 0,
                 value: float = 0.0, n_index: int = 1):
        super().__init__()
        self.dim, self.pad, self.value = dim, pad, value
        self.num_input_dims = num_input_dims

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self.dim - 1
        if self.num_input_dims > 0 and input.ndim == self.num_input_dims + 1:
            axis += 1
        widths = [(0, 0)] * input.ndim
        widths[axis] = (-self.pad, 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(input, widths, constant_values=self.value), state


class SpatialZeroPadding(TensorModule):
    def __init__(self, pad_left: int, pad_right: int, pad_top: int, pad_bottom: int):
        super().__init__()
        self.pads = (pad_left, pad_right, pad_top, pad_bottom)

    def apply(self, params, state, input, *, training=False, rng=None):
        l, r, t, b = self.pads
        widths = [(0, 0)] * (input.ndim - 2) + [(t, b), (l, r)]
        return jnp.pad(input, widths), state


class Contiguous(TensorModule):
    """No-op under XLA (arrays are always logically contiguous)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state


class Replicate(TensorModule):
    """Replicate input ``n_features`` times along a new dim (1-based)."""

    def __init__(self, n_features: int, dim: int = 1, n_input_dims: int = -1):
        super().__init__()
        self.n_features, self.dim, self.n_input_dims = n_features, dim, n_input_dims

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self.dim - 1
        if self.n_input_dims > 0 and input.ndim == self.n_input_dims + 1:
            axis += 1
        return jnp.repeat(jnp.expand_dims(input, axis), self.n_features, axis=axis), state


class Tile(TensorModule):
    """Repeat input ``copies`` times along dim (1-based; reference ``Tile``)."""

    def __init__(self, dim: int = 1, copies: int = 2):
        super().__init__()
        self.dim, self.copies = dim, copies

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self.dim - 1 if self.dim > 0 else input.ndim + self.dim
        reps = [1] * input.ndim
        reps[axis] = self.copies
        return jnp.tile(input, reps), state


class Reverse(TensorModule):
    """Flip along dim (1-based; reference ``Reverse``)."""

    def __init__(self, dimension: int = 1, is_inplace: bool = False):
        super().__init__()
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self.dimension - 1 if self.dimension > 0 else input.ndim + self.dimension
        return jnp.flip(input, axis=axis), state


class Index(AbstractModule):
    """Index select: input Table = (source, indices); gathers along dim
    (1-based; reference ``Index``). Indices are 0-based here, consistent with
    this framework's labels."""

    def __init__(self, dimension: int = 1):
        super().__init__()
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        src, idx = xs[0], xs[1]
        axis = self.dimension - 1 if self.dimension > 0 else src.ndim + self.dimension
        return jnp.take(src, idx.astype(jnp.int32), axis=axis), state


class InferReshape(TensorModule):
    """Reshape where one target dim may be -1 (inferred) and 0 copies the
    corresponding input dim (reference ``InferReshape``)."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.batch_mode = batch_mode

    def apply(self, params, state, input, *, training=False, rng=None):
        in_shape = input.shape[1:] if self.batch_mode else input.shape
        target = [in_shape[i] if s == 0 else s for i, s in enumerate(self.size)]
        if self.batch_mode:
            target = [input.shape[0]] + target
        return input.reshape(tuple(target)), state
