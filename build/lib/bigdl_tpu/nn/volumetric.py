"""Volumetric (3-D) layers — video/voxel workloads.

Reference parity (SURVEY.md §2.1 layer zoo, expected ``<dl>/nn/
VolumetricConvolution.scala`` / ``VolumetricMaxPooling.scala`` /
``VolumetricAveragePooling.scala`` — unverified, mount empty): Torch-style
NCDHW 3-D conv and pooling. One ``conv_general_dilated`` / ``reduce_window``
each — XLA tiles the contraction onto the MXU like any other conv.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform


class VolumetricConvolution(TensorModule):
    """Input (N, C, T, H, W) → (N, O, T', H', W'). Weight OIDHW."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t, self.d_w, self.d_h = d_t, d_w, d_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.with_bias = with_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        fan_in = self.n_input_plane * self.k_t * self.k_h * self.k_w
        w = self.w_init.init(
            (self.n_output_plane, self.n_input_plane, self.k_t, self.k_h,
             self.k_w), fan_in=fan_in, fan_out=self.n_output_plane)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(
                self.b_init.init((self.n_output_plane,), fan_in=fan_in,
                                 fan_out=self.n_output_plane))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 4
        if squeeze:
            x = x[None]
        out = lax.conv_general_dilated(
            x, params["weight"],
            window_strides=(self.d_t, self.d_h, self.d_w),
            padding=[(self.pad_t, self.pad_t), (self.pad_h, self.pad_h),
                     (self.pad_w, self.pad_w)],
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        if self.with_bias:
            out = out + params["bias"][None, :, None, None, None]
        if squeeze:
            out = out[0]
        return out, state

    def __repr__(self):
        return (f"VolumetricConvolution({self.n_input_plane} -> "
                f"{self.n_output_plane}, {self.k_t}x{self.k_h}x{self.k_w})")


class _VolumetricPool(TensorModule):
    def __init__(self, k_t: int, k_w: int, k_h: int,
                 d_t: int | None = None, d_w: int | None = None,
                 d_h: int | None = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0):
        super().__init__()
        self.k_t, self.k_w, self.k_h = k_t, k_w, k_h
        self.d_t = d_t if d_t is not None else k_t
        self.d_w = d_w if d_w is not None else k_w
        self.d_h = d_h if d_h is not None else k_h
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h

    def _window(self):
        return ((1, 1, self.k_t, self.k_h, self.k_w),
                (1, 1, self.d_t, self.d_h, self.d_w),
                ((0, 0), (0, 0), (self.pad_t, self.pad_t),
                 (self.pad_h, self.pad_h), (self.pad_w, self.pad_w)))


class VolumetricMaxPooling(_VolumetricPool):
    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 4
        if squeeze:
            x = x[None]
        win, strides, pad = self._window()
        out = lax.reduce_window(x, -jnp.inf, lax.max, win, strides, pad)
        out = out.astype(x.dtype)
        if squeeze:
            out = out[0]
        return out, state


class VolumetricAveragePooling(_VolumetricPool):
    """count_include_pad=True average (Torch default for AvgPool3d)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 4
        if squeeze:
            x = x[None]
        win, strides, pad = self._window()
        sums = lax.reduce_window(x, 0.0, lax.add, win, strides, pad)
        out = sums / (self.k_t * self.k_h * self.k_w)
        out = out.astype(x.dtype)
        if squeeze:
            out = out[0]
        return out, state


class VolumetricFullConvolution(TensorModule):
    """3-D transposed convolution (reference ``VolumetricFullConvolution``):
    the NCDHW mirror of SpatialFullConvolution — one lhs-dilated conv, which
    XLA lowers to the same MXU contractions as the forward conv."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kt: int, kw: int, kh: int, dt: int = 1, dw: int = 1,
                 dh: int = 1, pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 adj_t: int = 0, adj_w: int = 0, adj_h: int = 0,
                 n_group: int = 1, no_bias: bool = False,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kt, self.kw, self.kh = kt, kw, kh
        self.dt, self.dw, self.dh = dt, dw, dh
        self.pad_t, self.pad_w, self.pad_h = pad_t, pad_w, pad_h
        self.adj_t, self.adj_w, self.adj_h = adj_t, adj_w, adj_h
        self.n_group = n_group
        self.with_bias = not no_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        fan_in = self.n_input_plane * self.kt * self.kh * self.kw
        fan_out = self.n_output_plane * self.kt * self.kh * self.kw
        w = self.w_init.init(
            (self.n_input_plane, self.n_output_plane // self.n_group,
             self.kt, self.kh, self.kw),
            fan_in=fan_in, fan_out=fan_out)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(self.b_init.init(
                (self.n_output_plane,), fan_in=fan_in, fan_out=fan_out))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 4
        if squeeze:
            x = x[None]
        pad = [(self.kt - 1 - self.pad_t, self.kt - 1 - self.pad_t + self.adj_t),
               (self.kh - 1 - self.pad_h, self.kh - 1 - self.pad_h + self.adj_h),
               (self.kw - 1 - self.pad_w, self.kw - 1 - self.pad_w + self.adj_w)]
        # correlation-transpose needs the spatially flipped kernel (torch/Caffe
        # deconv semantics — same fix as SpatialFullConvolution)
        w = jnp.flip(params["weight"], (-3, -2, -1))
        if self.n_group > 1:
            # grouped deconv rearrange (I, O/g) → (I/g, O); see SpatialFullConvolution
            g = self.n_group
            i, og = w.shape[0], w.shape[1]
            w = w.reshape(g, i // g, og, self.kt, self.kh, self.kw) \
                 .transpose(1, 0, 2, 3, 4, 5) \
                 .reshape(i // g, g * og, self.kt, self.kh, self.kw)
        out = lax.conv_general_dilated(
            x, w,
            window_strides=(1, 1, 1),
            padding=pad,
            lhs_dilation=(self.dt, self.dh, self.dw),
            dimension_numbers=("NCDHW", "IODHW", "NCDHW"),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None, None]
        if squeeze:
            out = out[0]
        return out, state
