"""Static graph container — Torch-style ``inputs()`` node wiring over a functional core.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/Graph.scala``, ``StaticGraph.scala``,
``<dl>/utils/Node.scala`` — unverified, mount empty): the reference builds a DAG of modules
by calling ``layer.inputs(node1, node2, ...)`` which returns a ``Node`` wrapping the layer;
``Graph(input=..., output=...)`` topologically sorts the DAG and executes it in order on
``forward``, replaying reversed for ``backward`` with gradOutput routing.

TPU-native design: the topological order is computed once at construction; ``apply`` is a
pure function that walks the sorted nodes, feeding each module the (Table-packed, if n>1)
outputs of its predecessor nodes. The whole graph is ONE traced program under ``jit`` —
backward is ``jax.vjp`` of the composite, so no reverse-graph construction is needed and
XLA fuses across node boundaries (what the reference's mkldnn ``Fusion`` pass hand-did).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

from bigdl_tpu.nn.abstractnn import AbstractModule, Container, split_rng
from bigdl_tpu.utils.table import Table, T


class ModuleNode:
    """A node in the module DAG: wraps a module plus its predecessor nodes."""

    _counter = 0

    def __init__(self, module: Optional[AbstractModule],
                 prev_nodes: Sequence["ModuleNode"] = ()):
        ModuleNode._counter += 1
        self.id = ModuleNode._counter
        self.module = module
        self.prev_nodes: list[ModuleNode] = list(prev_nodes)

    def __repr__(self):
        return f"Node({self.module!r})"


def Input() -> ModuleNode:
    """Create a graph input placeholder node (reference ``Input()``)."""
    return ModuleNode(None, ())


def make_node(module: AbstractModule, nodes: Sequence) -> ModuleNode:
    """``layer.inputs(nodeA, nodeB)`` → new node wiring nodeA/nodeB into this layer."""
    flat: list[ModuleNode] = []
    for n in nodes:
        if isinstance(n, (list, tuple)):
            flat.extend(n)
        else:
            flat.append(n)
    return ModuleNode(module, flat)


class Graph(Container):
    """DAG of modules executed in topological order as one pure function.

    ``Graph(input_nodes, output_nodes)`` — either may be a single node or a list. Multiple
    graph inputs consume a ``Table`` input activity (element i → input node i); multiple
    outputs produce a ``Table``.
    """

    def __init__(self,
                 input: Union[ModuleNode, Sequence[ModuleNode]],
                 output: Union[ModuleNode, Sequence[ModuleNode]]):
        super().__init__()
        self.input_nodes = list(input) if isinstance(input, (list, tuple)) else [input]
        self.output_nodes = list(output) if isinstance(output, (list, tuple)) else [output]
        self.sorted_nodes = self._topo_sort()
        # children (for params/state nesting) = executable nodes in topo order
        self.exec_nodes = [n for n in self.sorted_nodes if n.module is not None]
        self.modules = [n.module for n in self.exec_nodes]
        self._node_child_name = {n.id: str(i) for i, n in enumerate(self.exec_nodes)}

    # ------------------------------------------------------------------ build
    def _topo_sort(self) -> list[ModuleNode]:
        """Kahn's algorithm from output nodes back through prev edges."""
        # collect reachable nodes
        seen: dict[int, ModuleNode] = {}
        stack = list(self.output_nodes)
        while stack:
            n = stack.pop()
            if n.id in seen:
                continue
            seen[n.id] = n
            stack.extend(n.prev_nodes)
        for inp in self.input_nodes:
            if inp.id not in seen:
                raise ValueError("Graph input node is not connected to any output")
        # in-degree over reachable subgraph
        indeg = {nid: 0 for nid in seen}
        succs: dict[int, list[ModuleNode]] = {nid: [] for nid in seen}
        for n in seen.values():
            for p in n.prev_nodes:
                indeg[n.id] += 1
                succs[p.id].append(n)
        ready = sorted([n for n in seen.values() if indeg[n.id] == 0], key=lambda n: n.id)
        order: list[ModuleNode] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for s in succs[n.id]:
                indeg[s.id] -= 1
                if indeg[s.id] == 0:
                    ready.append(s)
        if len(order) != len(seen):
            raise ValueError("Graph contains a cycle")
        return order

    # ------------------------------------------------------------------ run
    def apply(self, params, state, input, *, training=False, rng=None):
        # map graph inputs
        values: dict[int, object] = {}
        if len(self.input_nodes) == 1:
            values[self.input_nodes[0].id] = input
        else:
            xs = input.values() if isinstance(input, Table) else list(input)
            if len(xs) != len(self.input_nodes):
                raise ValueError(
                    f"graph expects {len(self.input_nodes)} inputs, got {len(xs)}")
            for node, x in zip(self.input_nodes, xs):
                values[node.id] = x

        new_state = {}
        rngs = split_rng(rng, len(self.exec_nodes))
        ri = 0
        for node in self.sorted_nodes:
            if node.module is None:
                if node.id not in values:
                    raise ValueError("unbound Input() node in graph")
                continue
            if node.prev_nodes:
                preds = [values[p.id] for p in node.prev_nodes]
                x = preds[0] if len(preds) == 1 else T(*preds)
            elif node.id in values:
                # module node used directly as a graph input (reference allows
                # `layer.inputs()` with no predecessors as an input node)
                x = values[node.id]
            else:
                raise ValueError(f"{node} has no predecessors and is not a graph input")
            cname = self._node_child_name[node.id]
            out, s = node.module.apply(params[cname], state[cname], x,
                                       training=training, rng=rngs[ri])
            ri += 1
            values[node.id] = out
            new_state[cname] = s

        outs = [values[n.id] for n in self.output_nodes]
        out = outs[0] if len(outs) == 1 else T(*outs)
        return out, new_state

    def node(self, name: str) -> Optional[ModuleNode]:
        for n in self.exec_nodes:
            if n.module is not None and n.module.name == name:
                return n
        return None

    def __repr__(self):
        return (f"Graph(inputs={len(self.input_nodes)}, outputs={len(self.output_nodes)}, "
                f"nodes={len(self.exec_nodes)})")


# Reference alias: StaticGraph is the concrete eager-plan graph class.
StaticGraph = Graph
