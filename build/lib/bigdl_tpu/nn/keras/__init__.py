"""Keras-1.2-style user API (reference ``<dl>/nn/keras/`` + python
``bigdl.nn.keras`` — SURVEY.md §2.1, unverified)."""

from bigdl_tpu.nn.keras.layers import (
    Activation, AveragePooling2D, BatchNormalization, Convolution2D, Dense,
    Dropout, Embedding, Flatten, GRU, GlobalAveragePooling2D, KerasLayer, LSTM,
    MaxPooling2D, Reshape, SimpleRNN, ZeroPadding2D,
)
from bigdl_tpu.nn.keras.topology import (
    Input, KerasModel, KerasNode, Model, Sequential, merge,
)

# Keras-2 style aliases
Conv2D = Convolution2D

__all__ = [
    "Activation", "AveragePooling2D", "BatchNormalization", "Conv2D",
    "Convolution2D", "Dense", "Dropout", "Embedding", "Flatten", "GRU",
    "GlobalAveragePooling2D", "Input", "KerasLayer", "KerasModel", "KerasNode",
    "LSTM", "MaxPooling2D", "Model", "Reshape", "Sequential", "SimpleRNN",
    "ZeroPadding2D", "merge",
]
