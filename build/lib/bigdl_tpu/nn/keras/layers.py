"""Keras-1.2-style shape-inferring layers over the nn module zoo.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/keras/`` — unverified): the
reference wraps its Torch-style layers in Keras layers that infer weight shapes from
the incoming activation shape; models are wired with ``Sequential.add`` or the
functional ``layer(node)`` API and trained via ``compile/fit``.

Design: a ``KerasLayer`` is a *builder* — ``build(input_shape)`` (batch dim excluded)
returns the concrete nn module, ``compute_output_shape`` propagates shapes. Data layout
is channels-first (NCHW), the framework-wide convention (TPU/XLA handles layout
assignment internally, so no 'tf' dim-ordering variant is needed).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

from bigdl_tpu import nn as N


def _act(name: Optional[str]):
    if name is None or name == "linear":
        return None
    table = {
        "relu": N.ReLU, "tanh": N.Tanh, "sigmoid": N.Sigmoid,
        "hard_sigmoid": N.HardSigmoid, "softmax": N.SoftMax,
        "softplus": N.SoftPlus, "softsign": N.SoftSign, "elu": N.ELU,
        "gelu": N.GELU, "swish": N.Swish, "log_softmax": N.LogSoftMax,
    }
    if name not in table:
        raise ValueError(f"unknown activation {name!r}")
    return table[name]()


def _pair(v) -> tuple:
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


class KerasLayer:
    """Shape-inferring builder for one nn module."""

    def __init__(self, input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        self.input_shape = tuple(input_shape) if input_shape is not None else None
        self.name = name or f"{type(self).__name__.lower()}_{id(self) % 100000}"

    def build(self, input_shape: tuple) -> "N.AbstractModule":
        raise NotImplementedError

    def compute_output_shape(self, input_shape: tuple) -> tuple:
        raise NotImplementedError

    def _with_activation(self, module, activation: Optional[str]):
        act = _act(activation)
        if act is None:
            return module
        return N.Sequential().add(module).add(act)

    # functional API: layer(node) → new node with propagated shape
    def __call__(self, node):
        from bigdl_tpu.nn.keras.topology import KerasNode, merge_nodes
        if isinstance(node, (list, tuple)):
            node = merge_nodes(node)
        if not isinstance(node, KerasNode):
            raise TypeError("functional call expects Input()/layer output node(s)")
        module = self.build(node.shape)
        from bigdl_tpu.nn.graph import make_node
        return KerasNode(make_node(module, [node.node]),
                         self.compute_output_shape(node.shape))


class Dense(KerasLayer):
    def __init__(self, output_dim: int, activation: Optional[str] = None,
                 bias: bool = True, init=None, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias
        self.init = init

    def build(self, input_shape):
        if len(input_shape) != 1:
            raise ValueError(
                f"Dense expects 1-D (features,) input shape, got {input_shape}; "
                "add Flatten() first")
        lin = N.Linear(input_shape[0], self.output_dim, with_bias=self.bias,
                       w_init=self.init)
        return self._with_activation(lin, self.activation)

    def compute_output_shape(self, input_shape):
        return (self.output_dim,)


class Activation(KerasLayer):
    def __init__(self, activation: str, **kw):
        super().__init__(**kw)
        self.activation = activation

    def build(self, input_shape):
        act = _act(self.activation)
        return act if act is not None else N.Identity()

    def compute_output_shape(self, input_shape):
        return input_shape


class Dropout(KerasLayer):
    def __init__(self, p: float, **kw):
        super().__init__(**kw)
        self.p = p

    def build(self, input_shape):
        return N.Dropout(self.p)

    def compute_output_shape(self, input_shape):
        return input_shape


class Flatten(KerasLayer):
    def build(self, input_shape):
        return N.Reshape([int(math.prod(input_shape))])

    def compute_output_shape(self, input_shape):
        return (int(math.prod(input_shape)),)


class Reshape(KerasLayer):
    def __init__(self, target_shape: Sequence[int], **kw):
        super().__init__(**kw)
        self.target_shape = tuple(target_shape)

    def build(self, input_shape):
        return N.Reshape(list(self.target_shape))

    def compute_output_shape(self, input_shape):
        if math.prod(self.target_shape) != math.prod(input_shape):
            raise ValueError(
                f"cannot reshape {input_shape} into {self.target_shape}")
        return self.target_shape


class Convolution2D(KerasLayer):
    """2-D conv on (channels, h, w). ``border_mode``: 'valid' or 'same'."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None, border_mode: str = "valid",
                 subsample=(1, 1), bias: bool = True, init=None, **kw):
        super().__init__(**kw)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode!r}")
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.bias = bias
        self.init = init

    def build(self, input_shape):
        c = input_shape[0]
        kh, kw = self.nb_row, self.nb_col
        pre_pad = None
        pw = ph = 0
        if self.border_mode == "same":
            if kh % 2 == 1 and kw % 2 == 1:
                pw, ph = (kw - 1) // 2, (kh - 1) // 2  # symmetric pad suffices
            else:
                # even kernel: SAME needs asymmetric (k-1)//2 / k//2 padding,
                # which the conv's symmetric pad can't express — pad explicitly.
                # Total pad k-1 yields out = ceil(in/stride) for every stride.
                pre_pad = N.SpatialZeroPadding((kw - 1) // 2, kw // 2,
                                               (kh - 1) // 2, kh // 2)
        conv = N.SpatialConvolution(
            c, self.nb_filter, kw, kh,
            self.subsample[1], self.subsample[0], pw, ph,
            with_bias=self.bias, w_init=self.init)
        if pre_pad is not None:
            conv = N.Sequential().add(pre_pad).add(conv)
        return self._with_activation(conv, self.activation)

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        sh, sw = self.subsample
        if self.border_mode == "same":
            oh = (h + sh - 1) // sh
            ow = (w + sw - 1) // sw
        else:
            oh = (h - self.nb_row) // sh + 1
            ow = (w - self.nb_col) // sw + 1
        return (self.nb_filter, oh, ow)


class _Pooling2D(KerasLayer):
    _op = None

    def __init__(self, pool_size=(2, 2), strides=None, border_mode: str = "valid",
                 **kw):
        super().__init__(**kw)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode!r}")
        self.border_mode = border_mode

    def build(self, input_shape):
        if self.border_mode == "same":
            # SAME = ceil(h/s) per dimension; the pooling primitive computes the exact
            # asymmetric lo/hi padding itself (pad_mode="same"), which is correct for
            # odd, even, and mixed pool sizes alike — no ceil-mode double counting.
            return self._op(self.pool_size[1], self.pool_size[0],
                            self.strides[1], self.strides[0], pad_mode="same")
        return self._op(self.pool_size[1], self.pool_size[0],
                        self.strides[1], self.strides[0], 0, 0)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        sh, sw = self.strides
        if self.border_mode == "same":
            return (c, (h + sh - 1) // sh, (w + sw - 1) // sw)
        return (c, (h - self.pool_size[0]) // sh + 1,
                (w - self.pool_size[1]) // sw + 1)


class MaxPooling2D(_Pooling2D):
    @property
    def _op(self):
        return N.SpatialMaxPooling


class AveragePooling2D(_Pooling2D):
    @property
    def _op(self):
        return N.SpatialAveragePooling


class GlobalAveragePooling2D(KerasLayer):
    def build(self, input_shape):
        c, h, w = input_shape
        return N.Sequential().add(N.SpatialAveragePooling(w, h)) \
                             .add(N.Reshape([c]))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), **kw):
        super().__init__(**kw)
        self.padding = _pair(padding)

    def build(self, input_shape):
        ph, pw = self.padding
        return N.SpatialZeroPadding(pw, pw, ph, ph)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h + 2 * self.padding[0], w + 2 * self.padding[1])


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99, **kw):
        super().__init__(**kw)
        self.epsilon = epsilon
        self.momentum = momentum

    def build(self, input_shape):
        # our BatchNormalization momentum is the update fraction (Torch style),
        # Keras momentum is the retain fraction
        mom = 1.0 - self.momentum
        if len(input_shape) == 3:
            return N.SpatialBatchNormalization(input_shape[0], eps=self.epsilon,
                                               momentum=mom)
        return N.BatchNormalization(input_shape[0], eps=self.epsilon, momentum=mom)

    def compute_output_shape(self, input_shape):
        return input_shape


class Embedding(KerasLayer):
    """(batch, seq) int indices → (batch, seq, output_dim). 0-based indices."""

    def __init__(self, input_dim: int, output_dim: int, init=None, **kw):
        super().__init__(**kw)
        self.input_dim, self.output_dim = input_dim, output_dim
        self.init = init

    def build(self, input_shape):
        return N.LookupTable(self.input_dim, self.output_dim, w_init=self.init,
                             zero_based=True)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class _RecurrentLayer(KerasLayer):
    _cell = None

    def __init__(self, output_dim: int, return_sequences: bool = False,
                 go_backwards: bool = False, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    def _make_cell(self, input_size):
        return self._cell(input_size, self.output_dim)

    def build(self, input_shape):
        if len(input_shape) != 2:
            raise ValueError(
                f"recurrent layers expect (time, features) input, got {input_shape}")
        seq = N.Sequential()
        if self.go_backwards:
            seq.add(_ReverseTime())
        seq.add(N.Recurrent(self._make_cell(input_shape[1])))
        if not self.return_sequences:
            seq.add(N.Select(2, -1))  # last timestep (1-based dims)
        return seq

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], self.output_dim)
        return (self.output_dim,)


class _ReverseTime(N.TensorModule):
    """Flip the time axis of (batch, time, feature)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[:, ::-1], state


class LSTM(_RecurrentLayer):
    @property
    def _cell(self):
        return N.LSTM


class GRU(_RecurrentLayer):
    @property
    def _cell(self):
        return N.GRU


class SimpleRNN(_RecurrentLayer):
    @property
    def _cell(self):
        return N.RnnCell


class Convolution1D(KerasLayer):
    """1-D conv on (steps, features) — keras-1.2 ``Convolution1D``. Maps onto
    the native NWC TemporalConvolution (one MXU contraction)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None, border_mode: str = "valid",
                 subsample_length: int = 1, bias: bool = True, init=None, **kw):
        super().__init__(**kw)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode!r}")
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.border_mode = border_mode
        self.subsample_length = subsample_length
        self.bias = bias
        self.init = init

    def build(self, input_shape):
        steps, features = input_shape
        conv = N.TemporalConvolution(features, self.nb_filter,
                                     self.filter_length,
                                     self.subsample_length,
                                     with_bias=self.bias, w_init=self.init)
        if self.border_mode == "same":
            # exact TF/keras SAME split (shared helper — pooling.py)
            from bigdl_tpu.nn.pooling import _same_pad
            k, s = self.filter_length, self.subsample_length
            left, right = _same_pad(steps, k, s)
            needed = left + right
            seq = N.Sequential()
            if left:
                seq.add(N.Padding(1, -left, num_input_dims=2))
            if needed - left:
                seq.add(N.Padding(1, needed - left, num_input_dims=2))
            conv = seq.add(conv)
        return self._with_activation(conv, self.activation)

    def compute_output_shape(self, input_shape):
        steps, _ = input_shape
        k, s = self.filter_length, self.subsample_length
        if self.border_mode == "same":
            return ((steps + s - 1) // s, self.nb_filter)
        return ((steps - k) // s + 1, self.nb_filter)


class _Pooling1D(KerasLayer):
    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 **kw):
        super().__init__(**kw)
        self.pool_length = pool_length
        self.stride = stride if stride is not None else pool_length

    def compute_output_shape(self, input_shape):
        steps, f = input_shape
        return ((steps - self.pool_length) // self.stride + 1, f)


class MaxPooling1D(_Pooling1D):
    def build(self, input_shape):
        return N.TemporalMaxPooling(self.pool_length, self.stride)


class GlobalMaxPooling1D(KerasLayer):
    def build(self, input_shape):
        return N.Sequential().add(N.TemporalMaxPooling(-1)).add(
            N.Reshape([input_shape[1]]))

    def compute_output_shape(self, input_shape):
        return (input_shape[1],)


class GlobalMaxPooling2D(KerasLayer):
    def build(self, input_shape):
        c, h, w = input_shape
        return N.Sequential().add(N.SpatialMaxPooling(w, h)) \
                             .add(N.Reshape([c]))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class LayerNormalization(KerasLayer):
    """LayerNorm over the trailing feature axis (served by the Pallas kernel
    on TPU)."""

    def __init__(self, epsilon: float = 1e-5, **kw):
        super().__init__(**kw)
        self.epsilon = epsilon

    def build(self, input_shape):
        return N.LayerNorm(input_shape[-1], eps=self.epsilon)

    def compute_output_shape(self, input_shape):
        return input_shape
