"""Tree-structured recurrence — BinaryTreeLSTM.

Reference parity (SURVEY.md §2.5 Examples, expected upstream
``<dl>/example/treeLSTM`` + ``<dl>/nn/BinaryTreeLSTM.scala`` — unverified,
mount empty): the constituency TreeLSTM of Tai et al. used by the sentiment
example, with per-child forget gates.

TPU-native design: the reference walks each tree with recursive Scala calls —
data-dependent control flow that cannot compile. Here every tree is encoded as
a STATIC array program: nodes are indexed with the ROOT AT 0 and children at
strictly larger indices; ``lax.scan`` sweeps indices from high to low, each step
gathering its two children's (h, c) from the carried state arrays and writing
its own — one compiled program for the whole batch of trees, padding nodes
(children = -1) costing only masked lanes. Trees of any shape batch together as
long as they share the padded node count.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.abstractnn import AbstractModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform, Zeros
from bigdl_tpu.utils.table import Table


class BinaryTreeLSTM(AbstractModule):
    """Input: Table ``(x (N, nodes, D), children (N, nodes, 2) int32)`` where
    ``children[b, i] = (left, right)`` node indices (> i) or -1 for a leaf slot.
    Output: per-node hidden states ``(N, nodes, H)`` — the root's state is
    ``out[:, 0]``. Gate layout: [i, o, u, f_l, f_r]."""

    def __init__(self, input_size: int, hidden_size: int,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or Zeros()
        self.reset()

    def reset(self) -> None:
        d, h = self.input_size, self.hidden_size

        def mk(shape, fan_in):
            return jnp.asarray(self.w_init.init(shape, fan_in=fan_in,
                                                fan_out=shape[-1]))

        self._params = {
            "w_x": mk((d, 5 * h), d),
            "u_l": mk((h, 5 * h), h),
            "u_r": mk((h, 5 * h), h),
            "bias": jnp.asarray(self.b_init.init((5 * h,), fan_in=d,
                                                 fan_out=5 * h)),
        }
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        x, children = xs[0], xs[1].astype(jnp.int32)
        n, nodes, _ = x.shape
        h_dim = self.hidden_size

        def gather_child(arr, idx):
            # arr (N, nodes, H); idx (N,) node index per sample, -1 → zeros
            safe = jnp.clip(idx, 0, nodes - 1)
            picked = jnp.take_along_axis(arr, safe[:, None, None].repeat(
                h_dim, axis=2), axis=1)[:, 0]
            return jnp.where((idx >= 0)[:, None], picked, 0.0)

        def step(carry, i):
            h_all, c_all = carry
            idx = nodes - 1 - i  # sweep high → low so children are ready
            xi = lax.dynamic_index_in_dim(x, idx, axis=1, keepdims=False)
            ch = lax.dynamic_index_in_dim(children, idx, axis=1, keepdims=False)
            h_l, h_r = gather_child(h_all, ch[:, 0]), gather_child(h_all, ch[:, 1])
            c_l, c_r = gather_child(c_all, ch[:, 0]), gather_child(c_all, ch[:, 1])
            gates = (xi @ params["w_x"] + h_l @ params["u_l"]
                     + h_r @ params["u_r"] + params["bias"])
            i_g, o_g, u_g, fl_g, fr_g = jnp.split(gates, 5, axis=-1)
            c_new = (jax.nn.sigmoid(i_g) * jnp.tanh(u_g)
                     + jax.nn.sigmoid(fl_g) * c_l + jax.nn.sigmoid(fr_g) * c_r)
            h_new = jax.nn.sigmoid(o_g) * jnp.tanh(c_new)
            h_all = lax.dynamic_update_index_in_dim(h_all, h_new, idx, axis=1)
            c_all = lax.dynamic_update_index_in_dim(c_all, c_new, idx, axis=1)
            return (h_all, c_all), None

        init = (jnp.zeros((n, nodes, h_dim), x.dtype),
                jnp.zeros((n, nodes, h_dim), x.dtype))
        (h_all, _), _ = lax.scan(step, init, jnp.arange(nodes))
        return h_all, state

    def __repr__(self):
        return f"BinaryTreeLSTM({self.input_size} -> {self.hidden_size})"
