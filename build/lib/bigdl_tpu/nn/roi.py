"""Region-of-interest pooling.

Reference parity (SURVEY.md §2.1 layer zoo, expected ``<dl>/nn/RoiPooling.scala``
— unverified, mount empty): the reference implements Fast-R-CNN max RoiPooling
with data-dependent bin extents — control flow a TPU program cannot trace.

TPU-native redesign: RoiAlign semantics (Mask R-CNN) with a FIXED number of
bilinear sample points per bin — every ROI becomes the same static gather
pattern, so one ``vmap`` over ROIs compiles to batched gathers with no dynamic
shapes. ``mode='avg'`` is standard RoiAlign; ``mode='max'`` maxes the sample
points, approximating the reference's max pooling on a static budget.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import AbstractModule
from bigdl_tpu.utils.table import Table


class RoiPooling(AbstractModule):
    """Input: Table ``(features (N, C, H, W), rois (R, 5))`` with rows
    ``[batch_idx, x1, y1, x2, y2]`` in feature-map coordinates (apply
    ``spatial_scale`` to image-space boxes). Output ``(R, C, pooled_h,
    pooled_w)``."""

    def __init__(self, pooled_h: int, pooled_w: int,
                 spatial_scale: float = 1.0, sampling_ratio: int = 2,
                 mode: str = "avg"):
        super().__init__()
        if mode not in ("avg", "max"):
            raise ValueError("mode must be 'avg' or 'max'")
        self.pooled_h, self.pooled_w = pooled_h, pooled_w
        self.spatial_scale = spatial_scale
        self.sampling_ratio = sampling_ratio
        self.mode = mode

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        feats, rois = xs[0], xs[1]
        n, c, h, w = feats.shape
        ph, pw, ns = self.pooled_h, self.pooled_w, self.sampling_ratio

        def one_roi(roi):
            b = roi[0].astype(jnp.int32)
            x1, y1, x2, y2 = roi[1:] * self.spatial_scale
            bw = jnp.maximum(x2 - x1, 1e-6) / pw
            bh = jnp.maximum(y2 - y1, 1e-6) / ph
            # sample grid: (ph*ns) x (pw*ns) bilinear points
            iy = jnp.arange(ph * ns)
            ix = jnp.arange(pw * ns)
            ys = y1 + (iy // ns) * bh + ((iy % ns) + 0.5) / ns * bh
            xs_ = x1 + (ix // ns) * bw + ((ix % ns) + 0.5) / ns * bw
            ys = jnp.clip(ys, 0.0, h - 1.0)
            xs_ = jnp.clip(xs_, 0.0, w - 1.0)
            y0 = jnp.floor(ys).astype(jnp.int32)
            x0 = jnp.floor(xs_).astype(jnp.int32)
            y1i = jnp.minimum(y0 + 1, h - 1)
            x1i = jnp.minimum(x0 + 1, w - 1)
            wy = (ys - y0)[:, None]
            wx = (xs_ - x0)[None, :]
            fmap = feats[b]  # (C, H, W)

            def g(yy, xx):
                return fmap[:, yy, :][:, :, xx]  # (C, ph*ns, pw*ns)

            samp = ((1 - wy) * (1 - wx) * g(y0, x0)
                    + (1 - wy) * wx * g(y0, x1i)
                    + wy * (1 - wx) * g(y1i, x0)
                    + wy * wx * g(y1i, x1i))
            samp = samp.reshape(c, ph, ns, pw, ns)
            if self.mode == "avg":
                return samp.mean(axis=(2, 4))
            return samp.max(axis=(2, 4))

        return jax.vmap(one_roi)(rois.astype(jnp.float32)), state

    def __repr__(self):
        return (f"RoiPooling({self.pooled_h}x{self.pooled_w}, "
                f"scale={self.spatial_scale}, {self.mode})")
