"""Sparse-feature layers — the SparseTensor/SparseLinear redesign.

Reference parity (SURVEY.md §2.1, expected ``<dl>/tensor/SparseTensor.scala`` +
``<dl>/nn/SparseLinear.scala``/``SparseJoinTable`` — unverified, mount empty):
the reference carries a COO ``SparseTensor`` through the data pipeline so
Wide&Deep's very wide one-hot/cross features avoid dense materialization.

TPU-native redesign: XLA wants static shapes, so the sparse representation is a
**padded id/value list** per row — ``ids (N, K) int32`` (pad = -1) and optional
``values (N, K) float`` — instead of a dynamic-length COO tensor. The contraction
``out[b] = Σ_k values[b,k] * W[ids[b,k]]`` is one gather + masked reduction:
exactly what a CSR matvec does, but in the form the MXU/VPU pipeline and SPMD
partitioner handle natively (dense gathers over a sharded table). K is the max
active features per row — Wide&Deep-style workloads have small fixed K, so the
padding cost is bounded and shapes never change between steps.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import AbstractModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform, Zeros
from bigdl_tpu.utils.table import Table

PAD_ID = -1


def _split_ids_values(input):
    if isinstance(input, Table):
        xs = input.values()
    elif isinstance(input, (tuple, list)):
        xs = list(input)
    else:
        xs = [input]
    ids = xs[0]
    values = xs[1] if len(xs) > 1 else None
    return ids, values


class SparseLinear(AbstractModule):
    """Linear layer over padded sparse ids: input ``ids (N, K)`` [+ optional
    ``values (N, K)``] → ``(N, output_size)``. Pad entries (id == -1) contribute
    nothing. The reference's SparseLinear consumed a COO SparseTensor; the
    padded-gather form is the shape-static equivalent."""

    def __init__(self, n_features: int, output_size: int, with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.n_features = n_features
        self.output_size = output_size
        self.with_bias = with_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or Zeros()
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.asarray(
            self.w_init.init((self.n_features, self.output_size),
                             fan_in=self.n_features, fan_out=self.output_size))}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(
                self.b_init.init((self.output_size,), fan_in=self.n_features,
                                 fan_out=self.output_size))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        ids, values = _split_ids_values(input)
        mask = (ids != PAD_ID)
        safe = jnp.where(mask, ids, 0).astype(jnp.int32)
        rows = params["weight"][safe]                      # (N, K, out)
        w = mask.astype(rows.dtype)
        if values is not None:
            w = w * values
        out = jnp.sum(rows * w[..., None], axis=1)
        if self.with_bias:
            out = out + params["bias"]
        return out, state

    def __repr__(self):
        return f"SparseLinear({self.n_features} -> {self.output_size})"


class SparseEmbeddingSum(AbstractModule):
    """Bag-of-ids embedding: mean/sum of embedding rows over the padded id list
    (the reference reached this via LookupTable + sparse input; here it is the
    direct masked-gather reduction)."""

    def __init__(self, n_index: int, n_output: int, combiner: str = "mean",
                 w_init: Optional[InitializationMethod] = None):
        super().__init__()
        if combiner not in ("mean", "sum"):
            raise ValueError("combiner must be 'mean' or 'sum'")
        self.n_index = n_index
        self.n_output = n_output
        self.combiner = combiner
        self.w_init = w_init or RandomUniform(-0.05, 0.05)
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.asarray(
            self.w_init.init((self.n_index, self.n_output),
                             fan_in=self.n_index, fan_out=self.n_output))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        ids, values = _split_ids_values(input)
        mask = (ids != PAD_ID)
        safe = jnp.where(mask, ids, 0).astype(jnp.int32)
        rows = params["weight"][safe]                      # (N, K, dim)
        w = mask.astype(rows.dtype)
        if values is not None:
            w = w * values
        out = jnp.sum(rows * w[..., None], axis=1)
        if self.combiner == "mean":
            out = out / jnp.clip(jnp.sum(w, axis=1, keepdims=True), 1e-12)
        return out, state

    def __repr__(self):
        return (f"SparseEmbeddingSum({self.n_index} -> {self.n_output}, "
                f"{self.combiner})")
