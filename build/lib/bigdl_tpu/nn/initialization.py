"""Weight initialisation strategies.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/InitializationMethod.scala`` —
unverified): ``Xavier``, ``MsraFiller``, ``RandomUniform``, ``RandomNormal``, ``Zeros``,
``Ones``, ``ConstInitMethod``, ``BilinearFiller``. Init is eager, host-side, driven by the
global deterministic ``RandomGenerator`` (Torch semantics); arrays are then pushed to device.

Fan-in/fan-out convention matches Torch/BigDL: for a Linear weight of shape (out, in),
fan_in = in, fan_out = out; for conv weight (nOut, nIn, kH, kW), fan_in = nIn*kH*kW.
"""

from __future__ import annotations

import numpy as np

from bigdl_tpu.utils.random_generator import RandomGenerator


from bigdl_tpu.nn.abstractnn import RecordsInit


class InitializationMethod(metaclass=RecordsInit):
    def init(self, shape, fan_in: int, fan_out: int) -> np.ndarray:
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class Xavier(InitializationMethod):
    """Glorot uniform: U(-sqrt(6/(fan_in+fan_out)), +)."""

    def init(self, shape, fan_in, fan_out):
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        return RandomGenerator.uniform(-limit, limit, shape)


class MsraFiller(InitializationMethod):
    """He/MSRA normal: N(0, sqrt(2/fan)) — the reference uses it for ResNet convs."""

    def __init__(self, variance_norm_average: bool = False):
        self.variance_norm_average = variance_norm_average

    def init(self, shape, fan_in, fan_out):
        n = (fan_in + fan_out) / 2.0 if self.variance_norm_average else fan_out
        std = float(np.sqrt(2.0 / n))
        return RandomGenerator.normal(0.0, std, shape)


class RandomUniform(InitializationMethod):
    def __init__(self, lower: float | None = None, upper: float | None = None):
        self.lower, self.upper = lower, upper

    def init(self, shape, fan_in, fan_out):
        if self.lower is None:
            # Torch default: U(-1/sqrt(fan_in), 1/sqrt(fan_in))
            stdv = 1.0 / float(np.sqrt(fan_in)) if fan_in > 0 else 1.0
            return RandomGenerator.uniform(-stdv, stdv, shape)
        return RandomGenerator.uniform(self.lower, self.upper, shape)


class RandomNormal(InitializationMethod):
    def __init__(self, mean: float = 0.0, stdv: float = 1.0):
        self.mean, self.stdv = mean, stdv

    def init(self, shape, fan_in, fan_out):
        return RandomGenerator.normal(self.mean, self.stdv, shape)


class Zeros(InitializationMethod):
    def init(self, shape, fan_in, fan_out):
        return np.zeros(shape, np.float32)


class Ones(InitializationMethod):
    def init(self, shape, fan_in, fan_out):
        return np.ones(shape, np.float32)


class ConstInitMethod(InitializationMethod):
    def __init__(self, value: float):
        self.value = value

    def init(self, shape, fan_in, fan_out):
        return np.full(shape, self.value, np.float32)


class BilinearFiller(InitializationMethod):
    """Bilinear upsampling kernel init (for deconvolution layers)."""

    def init(self, shape, fan_in, fan_out):
        # shape: (nOut, nIn, kH, kW)
        if len(shape) != 4:
            raise ValueError("BilinearFiller expects a 4-D conv weight shape")
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        yy, xx = np.meshgrid(np.arange(kh), np.arange(kw), indexing="ij")
        filt = (1 - np.abs(yy / f_h - c_h)) * (1 - np.abs(xx / f_w - c_w))
        out = np.zeros(shape, np.float32)
        out[...] = filt
        return out
