"""Triggers — composable stop/fire conditions.

Reference parity (SURVEY.md §2.3, expected ``<dl>/optim/Trigger.scala`` — unverified):
``everyEpoch``, ``severalIteration(n)``, ``maxEpoch(n)``, ``maxIteration(n)``, ``minLoss``,
``maxScore``, ``and``/``or``. A trigger is evaluated against the trainer's state table
(keys: "epoch" 1-based, "neval" 1-based iteration counter, "loss", "score",
"epoch_finished" bool set at epoch boundaries).
"""

from __future__ import annotations

from typing import Callable


class Trigger:
    """``scope`` controls when side-effect triggers are evaluated by the trainer:
    'iteration' (inside the batch loop), 'epoch' (at epoch boundaries), or 'any'."""

    def __init__(self, fn: Callable[[dict], bool], name: str = "trigger",
                 scope: str = "any"):
        self._fn = fn
        self._name = name
        self.scope = scope

    def __call__(self, state: dict) -> bool:
        return bool(self._fn(state))

    def __repr__(self):
        return f"Trigger({self._name})"

    # factories ------------------------------------------------------------
    @staticmethod
    def every_epoch() -> "Trigger":
        return Trigger(lambda s: s.get("epoch_finished", False), "everyEpoch",
                       scope="epoch")

    @staticmethod
    def several_iteration(interval: int) -> "Trigger":
        return Trigger(lambda s: s.get("neval", 0) % interval == 0,
                       f"severalIteration({interval})", scope="iteration")

    @staticmethod
    def max_epoch(n: int) -> "Trigger":
        return Trigger(lambda s: s.get("epoch", 1) > n, f"maxEpoch({n})")

    @staticmethod
    def max_iteration(n: int) -> "Trigger":
        # checked at loop top with neval starting at 1 → runs exactly n iterations
        return Trigger(lambda s: s.get("neval", 0) > n, f"maxIteration({n})")

    @staticmethod
    def min_loss(value: float) -> "Trigger":
        return Trigger(lambda s: s.get("loss", float("inf")) < value, f"minLoss({value})")

    @staticmethod
    def max_score(value: float) -> "Trigger":
        return Trigger(lambda s: s.get("score", float("-inf")) > value,
                       f"maxScore({value})")

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in triggers), "and")

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in triggers), "or")
