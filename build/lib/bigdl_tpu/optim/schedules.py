"""Learning-rate schedules for :class:`~bigdl_tpu.optim.SGD`.

Reference parity (SURVEY.md §2.3, expected ``<dl>/optim/SGD.scala`` inner objects —
unverified): ``Default``, ``Step``, ``MultiStep``, ``Poly``, ``Exponential``,
``NaturalExp``, ``Plateau``, ``Warmup``, ``SequentialSchedule``.

TPU-native: a schedule is a pure callable ``(base_lr, step) -> lr`` traced into the jitted
train step (``step`` is a traced f32 scalar), so changing iteration never recompiles.
``Plateau`` is the one *stateful* schedule (it reacts to validation metrics on the host);
it is marked ``stateful = True`` and the trainer carries its current LR as a leaf of the
optimizer state pytree, updated between jitted steps without retriggering compilation.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp


class LearningRateSchedule:
    """Pure schedule: maps (base_lr, iteration) -> learning rate, jit-traceable."""

    stateful = False

    def __call__(self, base_lr, step):
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class Default(LearningRateSchedule):
    """``clr = lr / (1 + step * decay)`` — the reference SGD default."""

    def __init__(self, learningrate_decay: float = 0.0):
        self.learningrate_decay = learningrate_decay

    def __call__(self, base_lr, step):
        return base_lr / (1.0 + step * self.learningrate_decay)


class Step(LearningRateSchedule):
    """``clr = lr * gamma ^ floor(step / step_size)``."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def __call__(self, base_lr, step):
        return base_lr * jnp.power(self.gamma, jnp.floor(step / self.step_size))


class MultiStep(LearningRateSchedule):
    """``clr = lr * gamma ^ (number of milestones passed)``."""

    def __init__(self, step_sizes: Sequence[int], gamma: float):
        self.step_sizes = tuple(step_sizes)
        self.gamma = gamma

    def __call__(self, base_lr, step):
        milestones = jnp.asarray(self.step_sizes, jnp.float32)
        n_passed = jnp.sum(step >= milestones)
        return base_lr * jnp.power(self.gamma, n_passed.astype(jnp.float32))


class Poly(LearningRateSchedule):
    """``clr = lr * (1 - step/max_iteration) ^ power``; 0 beyond ``max_iteration``."""

    def __init__(self, power: float, max_iteration: int):
        self.power = power
        self.max_iteration = max_iteration

    def __call__(self, base_lr, step):
        frac = jnp.clip(step / self.max_iteration, 0.0, 1.0)
        return base_lr * jnp.power(1.0 - frac, self.power)


class Exponential(LearningRateSchedule):
    """``clr = lr * decay_rate ^ (step / decay_step)`` (floored when ``stair_case``)."""

    def __init__(self, decay_step: int, decay_rate: float, stair_case: bool = False):
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.stair_case = stair_case

    def __call__(self, base_lr, step):
        exponent = step / self.decay_step
        if self.stair_case:
            exponent = jnp.floor(exponent)
        return base_lr * jnp.power(self.decay_rate, exponent)


class NaturalExp(LearningRateSchedule):
    """``clr = lr * exp(-decay_rate * floor-or-frac(step / decay_step))``."""

    def __init__(self, decay_step: int, decay_rate: float, stair_case: bool = False):
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.stair_case = stair_case

    def __call__(self, base_lr, step):
        exponent = step / self.decay_step
        if self.stair_case:
            exponent = jnp.floor(exponent)
        return base_lr * jnp.exp(-self.decay_rate * exponent)


class Warmup(LearningRateSchedule):
    """``clr = lr + delta * step`` — linear ramp, used inside SequentialSchedule."""

    def __init__(self, delta: float):
        self.delta = delta

    def __call__(self, base_lr, step):
        return base_lr + self.delta * step


class SequentialSchedule(LearningRateSchedule):
    """Chain of ``(schedule, duration_iterations)`` stages.

    Each stage sees a stage-local step counter starting at 0; the final stage runs
    forever. Mirrors the reference's ``SequentialSchedule.add(schedule, maxIteration)``.
    """

    def __init__(self):
        self.stages: list = []

    def add(self, schedule: LearningRateSchedule, max_iteration: int) -> "SequentialSchedule":
        self.stages.append((schedule, int(max_iteration)))
        return self

    def __call__(self, base_lr, step):
        if not self.stages:
            return base_lr
        lr = None
        offset = 0.0
        for i, (sched, dur) in enumerate(self.stages):
            local = step - offset
            stage_lr = sched(base_lr, jnp.maximum(local, 0.0))
            lr = stage_lr if lr is None else jnp.where(local >= 0, stage_lr, lr)
            offset += dur
        return lr


class Plateau(LearningRateSchedule):
    """Reduce LR when a monitored metric stops improving (host-side, stateful).

    Mirrors the reference's ``SGD.Plateau(monitor, factor, patience, mode, epsilon,
    cooldown, minLr)``. The trainer calls :meth:`on_metric` after each validation
    round with the monitored value; the returned LR is written into the optimizer
    state pytree (no recompilation — LR is a traced leaf).
    """

    stateful = True

    def __init__(self, monitor: str = "score", factor: float = 0.1,
                 patience: int = 10, mode: str = "min", epsilon: float = 1e-4,
                 cooldown: int = 0, min_lr: float = 0.0):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        if factor >= 1.0:
            raise ValueError("Plateau factor must be < 1.0")
        # monitor: "score" (first configured validation metric), "loss"/"Loss"
        # (training loss), or the NAME of a validation method (e.g.
        # "Top1Accuracy") — naming one decouples the monitored metric from the
        # order methods were listed in set_validation.
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.current_lr: float = None  # set by the trainer from SGD.learningrate
        self._best: float = None
        self._wait = 0
        self._cooldown_left = 0

    def reset(self, base_lr: float) -> None:
        self.current_lr = base_lr
        self._best = None
        self._wait = 0
        self._cooldown_left = 0

    # Host state travels with trainer checkpoints so retry-from-checkpoint
    # resumes the patience window instead of the pre-crash LR.
    def state_dict(self) -> dict:
        return {"current_lr": self.current_lr, "best": self._best,
                "wait": self._wait, "cooldown_left": self._cooldown_left}

    def load_state_dict(self, d: dict) -> None:
        self.current_lr = d["current_lr"]
        self._best = d["best"]
        self._wait = d["wait"]
        self._cooldown_left = d["cooldown_left"]

    def _improved(self, value: float) -> bool:
        if self._best is None:
            return True
        if self.mode == "min":
            return value < self._best - self.epsilon
        return value > self._best + self.epsilon

    def on_metric(self, value: float) -> float:
        """Record a monitored value; return the (possibly reduced) current LR."""
        if self.current_lr is None:
            raise RuntimeError("Plateau.reset(base_lr) must be called before on_metric")
        # Keras-exact cooldown semantics (ReduceLROnPlateau): the counter is
        # decremented first and the patience guard reads the *decremented* value,
        # so the round on which cooldown expires DOES count toward patience.
        # (A round-1 advisor note suggested snapshotting pre-decrement; that
        # mis-stated Keras and was declined — see tests/test_advice_fixes.py.)
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self._wait = 0
        if self._improved(value):
            self._best = value
            self._wait = 0
        elif self._cooldown_left <= 0:
            self._wait += 1
            if self._wait > self.patience:
                self.current_lr = max(self.current_lr * self.factor, self.min_lr)
                self._cooldown_left = self.cooldown
                self._wait = 0
        return self.current_lr

    def __call__(self, base_lr, step):
        # Pure path unused: the trainer reads LR from optimizer state for stateful
        # schedules. Return the host-tracked value for completeness.
        return self.current_lr if self.current_lr is not None else base_lr
