from bigdl_tpu.models.inception.inception import (
    Inception_Layer_v1, Inception_Layer_v2, Inception_v1,
    Inception_v1_NoAuxClassifier, Inception_v2, Inception_v2_NoAuxClassifier,
)
