"""Inception-v1 (GoogLeNet).

Reference parity (SURVEY.md §2.5, expected ``<dl>/models/inception/Inception_v1.scala`` —
unverified, mount empty): ``Inception_Layer_v1(inputSize, T(T(c1), T(r3, c3), T(r5, c5),
T(pp)), prefix)`` builds a ``Concat`` of four branches (1x1 | 1x1→3x3 | 1x1→5x5 |
maxpool→1x1); ``Inception_v1_NoAuxClassifier`` is the plain Sequential stack;
``Inception_v1`` adds the two auxiliary classifier heads after inception 4a and 4d and
outputs a 3-element Table trained with ``ParallelCriterion`` (main loss weight 1.0, aux
0.3). Baseline config #3 (BASELINE.md).

TPU-native notes: the heavy ``Concat`` branch blocks are pure functional fan-out/concat —
XLA schedules the four branches as independent fusions; LRN is a windowed reduce
(``SpatialCrossMapLRN``). The aux-head split uses the Graph container's multi-output
support rather than the reference's nested-ConcatTable trick.
"""

from __future__ import annotations

from bigdl_tpu import nn
from bigdl_tpu.utils.table import Table


def _cfg(v):
    return list(v.values()) if isinstance(v, Table) else list(v)


def Inception_Layer_v1(input_size: int, config, name_prefix: str = "") -> nn.Concat:
    """The 4-branch inception block."""
    cfg = _cfg(config)
    c1 = _cfg(cfg[0])[0]
    r3, c3 = _cfg(cfg[1])
    r5, c5 = _cfg(cfg[2])
    pp = _cfg(cfg[3])[0]
    concat = nn.Concat(2)
    concat.add(nn.Sequential()
               .add(nn.SpatialConvolution(input_size, c1, 1, 1)
                    .set_name(name_prefix + "1x1"))
               .add(nn.ReLU()))
    concat.add(nn.Sequential()
               .add(nn.SpatialConvolution(input_size, r3, 1, 1)
                    .set_name(name_prefix + "3x3_reduce"))
               .add(nn.ReLU())
               .add(nn.SpatialConvolution(r3, c3, 3, 3, 1, 1, 1, 1)
                    .set_name(name_prefix + "3x3"))
               .add(nn.ReLU()))
    concat.add(nn.Sequential()
               .add(nn.SpatialConvolution(input_size, r5, 1, 1)
                    .set_name(name_prefix + "5x5_reduce"))
               .add(nn.ReLU())
               .add(nn.SpatialConvolution(r5, c5, 5, 5, 1, 1, 2, 2)
                    .set_name(name_prefix + "5x5"))
               .add(nn.ReLU()))
    concat.add(nn.Sequential()
               .add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil())
               .add(nn.SpatialConvolution(input_size, pp, 1, 1)
                    .set_name(name_prefix + "pool_proj"))
               .add(nn.ReLU()))
    return concat


def _stem() -> nn.Sequential:
    return (nn.Sequential()
            .add(nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3)
                 .set_name("conv1/7x7_s2"))
            .add(nn.ReLU())
            .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
            .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
            .add(nn.SpatialConvolution(64, 64, 1, 1).set_name("conv2/3x3_reduce"))
            .add(nn.ReLU())
            .add(nn.SpatialConvolution(64, 192, 3, 3, 1, 1, 1, 1).set_name("conv2/3x3"))
            .add(nn.ReLU())
            .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
            .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
            .add(Inception_Layer_v1(192, [[64], [96, 128], [16, 32], [32]],
                                    "inception_3a/"))
            .add(Inception_Layer_v1(256, [[128], [128, 192], [32, 96], [64]],
                                    "inception_3b/"))
            .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
            .add(Inception_Layer_v1(480, [[192], [96, 208], [16, 48], [64]],
                                    "inception_4a/")))


def _mid() -> nn.Sequential:
    return (nn.Sequential()
            .add(Inception_Layer_v1(512, [[160], [112, 224], [24, 64], [64]],
                                    "inception_4b/"))
            .add(Inception_Layer_v1(512, [[128], [128, 256], [24, 64], [64]],
                                    "inception_4c/"))
            .add(Inception_Layer_v1(512, [[112], [144, 288], [32, 64], [64]],
                                    "inception_4d/")))


def _tail(class_num: int, has_dropout: bool) -> nn.Sequential:
    seq = (nn.Sequential()
           .add(Inception_Layer_v1(528, [[256], [160, 320], [32, 128], [128]],
                                   "inception_4e/"))
           .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
           .add(Inception_Layer_v1(832, [[256], [160, 320], [32, 128], [128]],
                                   "inception_5a/"))
           .add(Inception_Layer_v1(832, [[384], [192, 384], [48, 128], [128]],
                                   "inception_5b/"))
           .add(nn.SpatialAveragePooling(7, 7, 1, 1)))
    if has_dropout:
        seq.add(nn.Dropout(0.4))
    return (seq
            .add(nn.View([1024]))
            .add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
            .add(nn.LogSoftMax()))


def _aux_head(n_in: int, class_num: int, prefix: str,
              use_bn: bool = False) -> nn.Sequential:
    """Aux classifier head; ``use_bn`` swaps the conv+ReLU for conv+BN+ReLU
    (the v2 variant)."""
    seq = nn.Sequential().add(nn.SpatialAveragePooling(5, 5, 3, 3).ceil())
    if use_bn:
        seq.add(_conv_bn(n_in, 128, 1, 1, name=prefix + "conv"))
    else:
        seq.add(nn.SpatialConvolution(n_in, 128, 1, 1).set_name(prefix + "conv"))
        seq.add(nn.ReLU())
    return (seq
            .add(nn.View([128 * 4 * 4]))
            .add(nn.Linear(128 * 4 * 4, 1024).set_name(prefix + "fc"))
            .add(nn.ReLU())
            .add(nn.Linear(1024, class_num).set_name(prefix + "classifier"))
            .add(nn.LogSoftMax()))


def _flatten(*blocks: nn.Sequential) -> nn.Sequential:
    model = nn.Sequential()
    for block in blocks:
        for m in block.modules:
            model.add(m)
    return model


def Inception_v1_NoAuxClassifier(class_num: int = 1000,
                                 has_dropout: bool = True) -> nn.Sequential:
    return _flatten(_stem(), _mid(), _tail(class_num, has_dropout))


def Inception_v1(class_num: int = 1000, has_dropout: bool = True) -> nn.Graph:
    """Full GoogLeNet with the two aux heads; outputs T(main, aux1, aux2)."""
    inp = nn.Input()
    feat4a = _stem().inputs(inp)
    aux1 = _aux_head(512, class_num, "loss1/").inputs(feat4a)
    feat4d = _mid().inputs(feat4a)
    aux2 = _aux_head(528, class_num, "loss2/").inputs(feat4d)
    main = _tail(class_num, has_dropout).inputs(feat4d)
    return nn.Graph(inp, [main, aux1, aux2])


# --------------------------------------------------------------------- v2
def _conv_bn(in_p: int, out_p: int, kw: int, kh: int, sw: int = 1, sh: int = 1,
             pw: int = 0, ph: int = 0, name: str = "") -> nn.Sequential:
    """conv (no bias) + BN + ReLU — the BN-Inception building block."""
    return (nn.Sequential()
            .add(nn.SpatialConvolution(in_p, out_p, kw, kh, sw, sh, pw, ph,
                                       with_bias=False).set_name(name))
            .add(nn.SpatialBatchNormalization(out_p).set_name(name + "/bn"))
            .add(nn.ReLU()))


def Inception_Layer_v2(input_size: int, config, name_prefix: str = "") -> nn.Concat:
    """The BN-Inception block (reference ``Inception_Layer_v2`` — SURVEY.md
    §2.5 Inception_v2, unverified): branches 1x1 | 1x1→3x3 | 1x1→3x3→3x3 |
    pool(+proj), every conv followed by BatchNorm. ``config`` =
    [[c1], [r3, c3], [rd, cd], [pool_kind, pp]]; c1 == 0 marks a stride-2
    reduction block (no 1x1 branch, pass-through pool, stride on the 3x3s)."""
    cfg = _cfg(config)
    c1 = _cfg(cfg[0])[0]
    r3, c3 = _cfg(cfg[1])
    rd, cd = _cfg(cfg[2])
    pool_kind, pp = _cfg(cfg[3])
    reduction = c1 == 0
    stride = 2 if reduction else 1

    concat = nn.Concat(2)
    if not reduction:
        concat.add(_conv_bn(input_size, c1, 1, 1, name=name_prefix + "1x1"))
    concat.add(nn.Sequential()
               .add(_conv_bn(input_size, r3, 1, 1,
                             name=name_prefix + "3x3_reduce"))
               .add(_conv_bn(r3, c3, 3, 3, stride, stride, 1, 1,
                             name=name_prefix + "3x3")))
    concat.add(nn.Sequential()
               .add(_conv_bn(input_size, rd, 1, 1,
                             name=name_prefix + "double3x3_reduce"))
               .add(_conv_bn(rd, cd, 3, 3, 1, 1, 1, 1,
                             name=name_prefix + "double3x3a"))
               .add(_conv_bn(cd, cd, 3, 3, stride, stride, 1, 1,
                             name=name_prefix + "double3x3b")))
    pool_seq = nn.Sequential()
    if pool_kind == "max" or reduction:
        pool_seq.add(nn.SpatialMaxPooling(3, 3, stride, stride,
                                          0 if reduction else 1,
                                          0 if reduction else 1).ceil())
    else:
        pool_seq.add(nn.SpatialAveragePooling(3, 3, stride, stride, 1, 1)
                     .ceil())
    if pp > 0:
        pool_seq.add(_conv_bn(input_size, pp, 1, 1,
                              name=name_prefix + "pool_proj"))
    concat.add(pool_seq)
    return concat


def _v2_stem() -> nn.Sequential:
    return (nn.Sequential()
            .add(_conv_bn(3, 64, 7, 7, 2, 2, 3, 3, "conv1/7x7_s2"))
            .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
            .add(_conv_bn(64, 64, 1, 1, name="conv2/3x3_reduce"))
            .add(_conv_bn(64, 192, 3, 3, 1, 1, 1, 1, "conv2/3x3"))
            .add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
            .add(Inception_Layer_v2(192, [[64], [64, 64], [64, 96],
                                          ["avg", 32]], "inception_3a/"))
            .add(Inception_Layer_v2(256, [[64], [64, 96], [64, 96],
                                          ["avg", 64]], "inception_3b/"))
            .add(Inception_Layer_v2(320, [[0], [128, 160], [64, 96],
                                          ["max", 0]], "inception_3c/"))
            .add(Inception_Layer_v2(576, [[224], [64, 96], [96, 128],
                                          ["avg", 128]], "inception_4a/")))


def _v2_mid() -> nn.Sequential:
    return (nn.Sequential()
            .add(Inception_Layer_v2(576, [[192], [96, 128], [96, 128],
                                          ["avg", 128]], "inception_4b/"))
            .add(Inception_Layer_v2(576, [[160], [128, 160], [128, 160],
                                          ["avg", 96]], "inception_4c/"))
            .add(Inception_Layer_v2(576, [[96], [128, 192], [160, 192],
                                          ["avg", 96]], "inception_4d/")))


def _v2_tail(class_num: int) -> nn.Sequential:
    return (nn.Sequential()
            .add(Inception_Layer_v2(576, [[0], [128, 192], [192, 256],
                                          ["max", 0]], "inception_4e/"))
            .add(Inception_Layer_v2(1024, [[352], [192, 320], [160, 224],
                                           ["avg", 128]], "inception_5a/"))
            .add(Inception_Layer_v2(1024, [[352], [192, 320], [192, 224],
                                           ["max", 128]], "inception_5b/"))
            .add(nn.SpatialAveragePooling(7, 7, 1, 1))
            .add(nn.View([1024]))
            .add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
            .add(nn.LogSoftMax()))


def Inception_v2_NoAuxClassifier(class_num: int = 1000) -> nn.Sequential:
    return _flatten(_v2_stem(), _v2_mid(), _v2_tail(class_num))


def Inception_v2(class_num: int = 1000) -> nn.Graph:
    """BN-Inception with two aux heads (after 4a and 4d, mirroring the v1
    head placement); outputs T(main, aux1, aux2) for ParallelCriterion."""
    inp = nn.Input()
    feat4a = _v2_stem().inputs(inp)
    aux1 = _aux_head(576, class_num, "loss1/", use_bn=True).inputs(feat4a)
    feat4d = _v2_mid().inputs(feat4a)
    aux2 = _aux_head(576, class_num, "loss2/", use_bn=True).inputs(feat4d)
    main = _v2_tail(class_num).inputs(feat4d)
    return nn.Graph(inp, [main, aux1, aux2])
