"""Inception-v1/v2 ImageNet training main (reference parity: ``<dl>/models/inception/
TrainInceptionV1.scala`` — unverified, SURVEY.md §2.5; baseline config #3). With aux heads
the loss is ``ParallelCriterion`` (main ×1.0, aux ×0.3) with the target repeated, matching
the reference. No ImageNet on disk here → synthetic fallback keeps the main runnable.
``python -m bigdl_tpu.models.inception.train``.
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Inception-v1/v2 training")
    p.add_argument("-f", "--folder", default=None)
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--no-aux", action="store_true", help="NoAuxClassifier variant")
    p.add_argument("--v2", action="store_true", help="BN-Inception (Inception_v2)")
    p.add_argument("--max-iteration", type=int, default=4)
    p.add_argument("--learning-rate", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--synthetic-size", type=int, default=64)
    p.add_argument("--image-size", type=int, default=224)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.inception import (
        Inception_v1, Inception_v1_NoAuxClassifier, Inception_v2,
        Inception_v2_NoAuxClassifier,
    )
    from bigdl_tpu.optim import DistriOptimizer, LocalOptimizer, SGD, Trigger
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    if args.folder is not None:
        # on-disk ImageNet-layout folder through the streaming pipeline
        from bigdl_tpu.models.imagenet_data import imagenet_sets
        train_set, _ = imagenet_sets(
            args.folder, args.batch_size, crop=args.image_size,
            distributed=args.distributed)
    else:
        # fast in-memory synthetic set (clustered blobs so loss visibly drops)
        rng = np.random.default_rng(0)
        n_cls = min(args.classes, 10)
        protos = np.random.default_rng(7).normal(
            0, 1, size=(n_cls, 3, args.image_size, args.image_size)).astype(np.float32)
        labels = rng.integers(0, n_cls, size=args.synthetic_size)
        imgs = (protos[labels]
                + rng.normal(0, 0.5, size=(args.synthetic_size, 3, args.image_size,
                                           args.image_size)).astype(np.float32))
        samples = [Sample(x, y) for x, y in zip(imgs, labels.astype(np.int32))]
        train_set = (DataSet.array(samples, distributed=args.distributed)
                     >> SampleToMiniBatch(args.batch_size))

    if args.no_aux:
        model = (Inception_v2_NoAuxClassifier(args.classes) if args.v2
                 else Inception_v1_NoAuxClassifier(args.classes))
        criterion = nn.ClassNLLCriterion()
    else:
        model = (Inception_v2(args.classes) if args.v2
                 else Inception_v1(args.classes))
        criterion = (nn.ParallelCriterion(repeat_target=True)
                     .add(nn.ClassNLLCriterion(), 1.0)
                     .add(nn.ClassNLLCriterion(), 0.3)
                     .add(nn.ClassNLLCriterion(), 0.3))
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    optimizer = (cls(model, train_set, criterion)
                 .set_optim_method(SGD(learningrate=args.learning_rate,
                                       momentum=args.momentum,
                                       weightdecay=args.weight_decay, dampening=0.0))
                 .set_end_when(Trigger.max_iteration(args.max_iteration)))
    trained = optimizer.optimize()
    print(f"final loss: {optimizer.state['loss']:.4f}")
    return trained


if __name__ == "__main__":
    main()
