from bigdl_tpu.models.textclassifier.textclassifier import TextClassifier

__all__ = ["TextClassifier"]
