"""Text-classification example main (reference parity: upstream
``example/textclassification`` — unverified, SURVEY.md §2.5).

``python -m bigdl_tpu.models.textclassifier.train`` — with no corpus on disk
(no network), generates a synthetic topic-classification task: each class has
its own keyword vocabulary mixed with shared filler words; sentences are
tokenized through the text pipeline (SentenceTokenizer + Dictionary), padded to
a fixed length, and classified by the temporal-CNN model.
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Temporal-CNN text classification")
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--max-epoch", type=int, default=4)
    p.add_argument("--sentences", type=int, default=2048)
    p.add_argument("--classes", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--vocab-size", type=int, default=2000)
    p.add_argument("--distributed", action="store_true")
    return p


def synthetic_corpus(n: int, classes: int, seed=0):
    """Sentences of filler words + class-specific keywords (learnable topic)."""
    rng = np.random.default_rng(seed)
    filler = [f"word{i}" for i in range(200)]
    keywords = [[f"topic{c}kw{i}" for i in range(20)] for c in range(classes)]
    texts, labels = [], []
    for _ in range(n):
        c = int(rng.integers(0, classes))
        length = int(rng.integers(8, 24))
        words = [filler[rng.integers(0, len(filler))] for _ in range(length)]
        for _ in range(max(2, length // 5)):
            pos = int(rng.integers(0, len(words)))
            words[pos] = keywords[c][rng.integers(0, 20)]
        texts.append(" ".join(words))
        labels.append(c)
    return texts, np.asarray(labels, np.int32)


def texts_to_samples(texts, labels, dictionary, seq_len):
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.text import SentenceTokenizer

    tok = SentenceTokenizer()
    samples = []
    for text, y in zip(texts, labels):
        ids = [dictionary.get_index(t) for t in next(tok(iter([text])))]
        ids = ids[:seq_len] + [0] * max(0, seq_len - len(ids))
        samples.append(Sample(np.asarray(ids, np.int32), np.int32(y)))
    return samples


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.text import Dictionary, SentenceTokenizer
    from bigdl_tpu.models.textclassifier import TextClassifier
    from bigdl_tpu.optim import (
        Adam, DistriOptimizer, LocalOptimizer, Top1Accuracy, Trigger,
    )
    from bigdl_tpu.utils.engine import Engine

    Engine.init()
    texts, labels = synthetic_corpus(args.sentences, args.classes)
    tok = SentenceTokenizer()
    dictionary = Dictionary(
        (t for text in texts for t in next(tok(iter([text])))),
        vocab_size=args.vocab_size)
    samples = texts_to_samples(texts, labels, dictionary, args.seq_len)
    split = int(0.9 * len(samples))
    train = DataSet.array(samples[:split], distributed=args.distributed) \
        >> SampleToMiniBatch(args.batch_size)
    test = DataSet.array(samples[split:]) >> SampleToMiniBatch(args.batch_size)

    model = TextClassifier(dictionary.vocab_size(), args.classes,
                           seq_len=args.seq_len)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    opt = (cls(model, train, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learningrate=args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_validation(Trigger.every_epoch(), test, [Top1Accuracy()]))
    opt.log_every = 10
    opt.optimize()
    acc = opt.state["scores"]["Top1Accuracy"]
    print(f"TextClassifier held-out Top1Accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
