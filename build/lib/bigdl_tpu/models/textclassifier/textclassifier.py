"""Text-classification CNN — the textclassification example's model.

Reference parity (SURVEY.md §2.5 Examples, expected upstream
``<dl>/example/textclassification/TextClassifier.scala`` — unverified, mount
empty): embedding (GloVe upstream; learned here) → temporal CNN blocks →
global max over time → dense classifier.

TPU-native: embedding gather + NWC temporal convs + reduce_window max compile
into one XLA program; sequences are padded/truncated to a fixed length so jit
sees one shape.
"""

from __future__ import annotations

from bigdl_tpu import nn


def TextClassifier(vocab_size: int, class_num: int, embed_dim: int = 64,
                   seq_len: int = 64, conv_channels: int = 128,
                   kernel_w: int = 5) -> nn.Sequential:
    """Input: (N, seq_len) int32 token ids (0 = unk/pad, Dictionary convention,
    hence zero-based lookup). Output: (N, class_num) log-probabilities."""
    return (nn.Sequential()
            .add(nn.LookupTable(vocab_size, embed_dim, zero_based=True))
            .add(nn.TemporalConvolution(embed_dim, conv_channels, kernel_w))
            .add(nn.ReLU())
            .add(nn.TemporalMaxPooling(2))
            .add(nn.TemporalConvolution(conv_channels, conv_channels, kernel_w))
            .add(nn.ReLU())
            .add(nn.TemporalMaxPooling(-1))   # global max over remaining time
            .add(nn.Squeeze(2))
            .add(nn.Linear(conv_channels, class_num))
            .add(nn.LogSoftMax()))
