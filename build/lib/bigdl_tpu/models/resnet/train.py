"""ResNet training main — CIFAR-10 (depth 20/32/...) or ImageNet (50/...) variants.

Reference parity: ``<dl>/models/resnet/Train*.scala`` scopt options (depth, shortcutType,
batchSize, nEpochs, learningRate, momentum, weightDecay, dataset, optnet — unverified,
SURVEY.md §2.5). ``python -m bigdl_tpu.models.resnet.train``.
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="ResNet training")
    p.add_argument("-f", "--folder", default=None, help="dataset dir")
    p.add_argument("--dataset", default="CIFAR-10", choices=["CIFAR-10", "ImageNet"])
    p.add_argument("--depth", type=int, default=20)
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--shortcut-type", default=None, choices=[None, "A", "B", "C"])
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("--max-epoch", type=int, default=1)
    p.add_argument("--learning-rate", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--nesterov", action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--summary-dir", default=None)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--synthetic-size", type=int, default=1024)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import cifar
    from bigdl_tpu.models.resnet import ResNet
    from bigdl_tpu.optim import (
        DistriOptimizer, LocalOptimizer, SGD, Top1Accuracy, Trigger,
    )
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    if args.dataset == "ImageNet":
        from bigdl_tpu.models.imagenet_data import imagenet_sets
        train_set, test_set = imagenet_sets(
            args.folder, args.batch_size, distributed=args.distributed,
            synthetic_per_class=max(args.synthetic_size // 4, 8))
    else:
        train_set, test_set = cifar.train_val_sets(
            args.folder, args.batch_size, distributed=args.distributed,
            synthetic_size=args.synthetic_size)

    opt = {"depth": args.depth, "dataSet": args.dataset}
    if args.shortcut_type:
        opt["shortcutType"] = args.shortcut_type
    model = ResNet(args.classes, opt)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    optimizer = (cls(model, train_set, nn.ClassNLLCriterion())
                 .set_optim_method(SGD(learningrate=args.learning_rate,
                                       momentum=args.momentum,
                                       weightdecay=args.weight_decay,
                                       nesterov=args.nesterov, dampening=0.0))
                 .set_end_when(Trigger.max_epoch(args.max_epoch))
                 .set_validation(Trigger.every_epoch(), test_set, [Top1Accuracy()]))
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, Trigger.every_epoch())
    if args.summary_dir:
        from bigdl_tpu.visualization import TrainSummary, ValidationSummary
        optimizer.set_train_summary(TrainSummary(args.summary_dir, "resnet"))
        optimizer.set_val_summary(ValidationSummary(args.summary_dir, "resnet"))
    trained = optimizer.optimize()
    print(f"final loss: {optimizer.state['loss']:.4f}")
    return trained


if __name__ == "__main__":
    main()
