from bigdl_tpu.models.resnet.resnet import (
    ResNet, ResNet50, basic_block, bottleneck, conv_bn,
)
