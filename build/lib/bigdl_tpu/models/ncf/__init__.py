from bigdl_tpu.models.ncf.ncf import NeuralCF

__all__ = ["NeuralCF"]
