from bigdl_tpu.models.autoencoder.autoencoder import Autoencoder
