"""Autoencoder MNIST training main (reference parity: ``<dl>/models/autoencoder/Train.scala``
— unverified, SURVEY.md §2.5). Reconstruction target = input; MSE loss.
``python -m bigdl_tpu.models.autoencoder.train``.
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="MNIST autoencoder training")
    p.add_argument("-f", "--folder", default=None)
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("--bottleneck", type=int, default=32)
    p.add_argument("--max-epoch", type=int, default=1)
    p.add_argument("--learning-rate", type=float, default=0.01)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--synthetic-size", type=int, default=2048)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.mnist import load_mnist
    from bigdl_tpu.models.autoencoder import Autoencoder
    from bigdl_tpu.optim import Adam, DistriOptimizer, LocalOptimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    imgs, _ = load_mnist(args.folder, "train", synthetic_size=args.synthetic_size)
    flat = (imgs.astype(np.float32) / 255.0).reshape(len(imgs), -1)
    samples = [Sample(x, x) for x in flat]
    train_set = (DataSet.array(samples, distributed=args.distributed)
                 >> SampleToMiniBatch(args.batch_size))

    model = Autoencoder(args.bottleneck)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    optimizer = (cls(model, train_set, nn.MSECriterion())
                 .set_optim_method(Adam(learningrate=args.learning_rate))
                 .set_end_when(Trigger.max_epoch(args.max_epoch)))
    trained = optimizer.optimize()
    print(f"final loss: {optimizer.state['loss']:.6f}")
    return trained


if __name__ == "__main__":
    main()
