"""MNIST autoencoder.

Reference parity (SURVEY.md §2.5, expected ``<dl>/models/autoencoder/Autoencoder.scala`` —
unverified, mount empty): 784 → Linear(784, classNum) → ReLU → Linear(classNum, 784) →
Sigmoid, trained with MSECriterion reconstructing the input.
"""

from __future__ import annotations

from bigdl_tpu import nn


def Autoencoder(class_num: int = 32) -> nn.Sequential:
    """``class_num`` is the bottleneck width (reference naming)."""
    return (nn.Sequential()
            .add(nn.Reshape([28 * 28]))
            .add(nn.Linear(28 * 28, class_num))
            .add(nn.ReLU())
            .add(nn.Linear(class_num, 28 * 28))
            .add(nn.Sigmoid()))
