"""VGG model family.

Reference parity (SURVEY.md §2.5, expected ``<dl>/models/vgg/`` with ``VggForCifar10``,
``Vgg_16``, ``Vgg_19`` — unverified, mount empty): ``VggForCifar10`` is the BN-augmented
CIFAR VGG (conv3x3+BN+ReLU stacks, 5 maxpools, 512-wide classifier head with dropout);
``Vgg_16``/``Vgg_19`` are the classic ImageNet configs D/E (no BN, 4096-wide FC head).
"""

from __future__ import annotations

from bigdl_tpu import nn


def _conv_bn_relu(n_in: int, n_out: int) -> list:
    return [nn.SpatialConvolution(n_in, n_out, 3, 3, 1, 1, 1, 1),
            nn.SpatialBatchNormalization(n_out, eps=1e-3),
            nn.ReLU()]


def VggForCifar10(class_num: int = 10, has_dropout: bool = True) -> nn.Sequential:
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]
    model = nn.Sequential()
    n_in = 3
    for v in cfg:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
        else:
            for layer in _conv_bn_relu(n_in, v):
                model.add(layer)
            n_in = v
    model.add(nn.View([512]))
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(512, 512))
    model.add(nn.BatchNormalization(512))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(512, class_num))
    model.add(nn.LogSoftMax())
    return model


_VGG_CFG = {
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
         512, 512, 512, "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_imagenet(depth: int, class_num: int, has_dropout: bool) -> nn.Sequential:
    model = nn.Sequential()
    n_in = 3
    for v in _VGG_CFG[depth]:
        if v == "M":
            model.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
        else:
            model.add(nn.SpatialConvolution(n_in, v, 3, 3, 1, 1, 1, 1))
            model.add(nn.ReLU())
            n_in = v
    model.add(nn.View([512 * 7 * 7]))
    model.add(nn.Linear(512 * 7 * 7, 4096))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, 4096))
    model.add(nn.ReLU())
    if has_dropout:
        model.add(nn.Dropout(0.5))
    model.add(nn.Linear(4096, class_num))
    model.add(nn.LogSoftMax())
    return model


def Vgg_16(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    return _vgg_imagenet(16, class_num, has_dropout)


def Vgg_19(class_num: int = 1000, has_dropout: bool = True) -> nn.Sequential:
    return _vgg_imagenet(19, class_num, has_dropout)
