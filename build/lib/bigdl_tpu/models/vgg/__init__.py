from bigdl_tpu.models.vgg.vgg import Vgg_16, Vgg_19, VggForCifar10
