"""Model-import example main (reference parity: upstream ``example/loadmodel``
— unverified, SURVEY.md §2.5): load a TF frozen graph (``--tf model.pb``), a
Caffe pair (``--caffe deploy.prototxt weights.caffemodel``), or a native
portable file (``--bigdl model.bigdl``), then run inference on synthetic (or
``.npy``) input and print the top predictions.

``python -m bigdl_tpu.models.loadmodel.main --tf model.pb --input-shape 1,3,224,224``
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Load an external model and predict")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--tf", help="TF frozen GraphDef (.pb)")
    src.add_argument("--caffe", nargs=2,
                     metavar=("PROTOTXT", "CAFFEMODEL"),
                     help="Caffe structure + weights")
    src.add_argument("--bigdl", help="portable native model (.bigdl)")
    p.add_argument("--tf-output", default="output",
                   help="TF output node name")
    p.add_argument("--tf-input", default=None, help="TF input node name")
    p.add_argument("--input-shape", required=True,
                   help="comma-separated input shape incl. batch "
                        "(NHWC for TF models, NCHW for Caffe/native)")
    p.add_argument("--input-npy", default=None,
                   help=".npy file to feed instead of synthetic data")
    p.add_argument("--top", type=int, default=5)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.utils.engine import Engine

    Engine.init()
    if args.tf:
        from bigdl_tpu.utils.tf import load_frozen_graph
        model = load_frozen_graph(
            args.tf, outputs=[args.tf_output],
            inputs=[args.tf_input] if args.tf_input else None)
    elif args.caffe:
        from bigdl_tpu.utils.caffe import load_caffe
        model = load_caffe(args.caffe[0], args.caffe[1])
    else:
        model = nn.AbstractModule.load(args.bigdl)

    shape = tuple(int(s) for s in args.input_shape.split(","))
    if args.input_npy:
        x = np.load(args.input_npy).astype(np.float32).reshape(shape)
    else:
        x = np.random.default_rng(0).normal(size=shape).astype(np.float32)

    out = np.asarray(model.evaluate().forward(jnp.asarray(x)))
    scores = out.reshape(out.shape[0], -1)
    top = np.argsort(-scores, axis=1)[:, : args.top]
    for i, row in enumerate(top):
        pretty = ", ".join(f"{c}:{scores[i, c]:.4f}" for c in row)
        print(f"sample {i}: top{args.top} -> {pretty}")
    return scores


if __name__ == "__main__":
    main()
