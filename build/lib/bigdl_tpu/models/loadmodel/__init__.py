"""Model-import example; see main.py."""
