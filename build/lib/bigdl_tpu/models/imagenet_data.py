"""Shared ImageNet-style data pipelines for the vision model mains.

Reference parity (SURVEY.md §2.5): the reference's ImageNet mains read Spark sequence
files and apply BGRImg* transformers. Here the source is the on-disk image folder
(``dataset/image_folder.py``) streaming through the vision transformer pipeline; with
no ``--folder`` a small synthetic ImageNet-layout directory is materialised so every
main runs end-to-end out of the box.

Train: aspect-scale → random crop → random hflip → channel normalize → CHW.
Val:   aspect-scale → center crop → channel normalize → CHW.
Normalisation uses the standard ImageNet RGB statistics on the 0-255 scale.
"""

from __future__ import annotations

import os
import tempfile

from bigdl_tpu.dataset.dataset import AbstractDataSet, DataSet
from bigdl_tpu.dataset.sample import SampleToMiniBatch
from bigdl_tpu.transform.vision.image import (
    AspectScale, CenterCrop, ChannelNormalize, ImageFrameToSample, MatToTensor,
    RandomCrop, RandomHFlip,
)

IMAGENET_RGB_MEANS = (123.68, 116.779, 103.939)
IMAGENET_RGB_STDS = (58.393, 57.12, 57.375)


def _split_dir(folder: str, split: str) -> str:
    sub = os.path.join(folder, split)
    return sub if os.path.isdir(sub) else folder


def imagenet_sets(folder: str | None, batch_size: int, crop: int = 224,
                  distributed: bool = False, num_workers: int = 8,
                  synthetic_classes: int = 4, synthetic_per_class: int = 32,
                  ) -> tuple[AbstractDataSet, AbstractDataSet]:
    """(train_set, val_set) of MiniBatches from ``folder`` (``train/``/``val/``
    subdirs honored when present), or from a synthetic fallback directory."""
    if folder is None:
        from bigdl_tpu.dataset.image_folder import write_synthetic_image_folder
        folder = tempfile.mkdtemp(prefix="bigdl_synth_imagenet_")
        write_synthetic_image_folder(
            folder, n_classes=synthetic_classes, n_per_class=synthetic_per_class,
            size=crop + crop // 4)

    scale = crop * 256 // 224
    train = (DataSet.image_folder(_split_dir(folder, "train"),
                                  num_workers=num_workers, distributed=distributed)
             >> AspectScale(scale)
             >> RandomCrop(crop, crop)
             >> RandomHFlip()
             >> ChannelNormalize(IMAGENET_RGB_MEANS, IMAGENET_RGB_STDS)
             >> MatToTensor()
             >> ImageFrameToSample()
             >> SampleToMiniBatch(batch_size))
    val = (DataSet.image_folder(_split_dir(folder, "val"),
                                num_workers=num_workers, distributed=distributed)
           >> AspectScale(scale)
           >> CenterCrop(crop, crop)
           >> ChannelNormalize(IMAGENET_RGB_MEANS, IMAGENET_RGB_STDS)
           >> MatToTensor()
           >> ImageFrameToSample()
           >> SampleToMiniBatch(batch_size))
    return train, val
