"""TreeLSTM sentiment example main (reference parity: upstream
``example/treeLSTM`` sentiment training — unverified, SURVEY.md §2.5).

``python -m bigdl_tpu.models.treelstm.train`` — synthetic sentiment task over
random binary parse trees: leaf tokens carry positive/negative/neutral valence
and the root label is the majority valence, so the tree recurrence has a real
compositional signal. Evaluated with TreeNNAccuracy (root-node accuracy).
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="BinaryTreeLSTM sentiment")
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--learning-rate", type=float, default=2e-3)
    p.add_argument("--max-epoch", type=int, default=6)
    p.add_argument("--trees", type=int, default=2048)
    p.add_argument("--leaves", type=int, default=8, help="leaves per tree")
    p.add_argument("--vocab-size", type=int, default=60)
    p.add_argument("--embed-dim", type=int, default=32)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--distributed", action="store_true")
    return p


def random_tree(n_leaves: int, rng):
    """Random binary tree; returns (children list root-first, leaf slots).
    Node 0 is the root; children indices are strictly larger (the encoding
    BinaryTreeLSTM scans)."""
    # build bottom-up: start with leaf fragments, merge random pairs
    nodes = []          # (left, right) per internal node, indices into `nodes`/leaves
    frags = [("leaf", i) for i in range(n_leaves)]
    while len(frags) > 1:
        i = rng.integers(0, len(frags) - 1)
        a, b = frags[i], frags[i + 1]
        nodes.append((a, b))
        frags[i: i + 2] = [("node", len(nodes) - 1)]
    total = 2 * n_leaves - 1
    children = np.full((total, 2), -1, np.int32)
    leaf_slot = np.full(n_leaves, -1, np.int32)
    counter = [0]
    order: dict = {}

    def assign(ref):  # root-first DFS numbering
        kind, idx = ref
        my = counter[0]
        counter[0] += 1
        if kind == "leaf":
            leaf_slot[idx] = my
        else:
            l, r = nodes[idx]
            children[my] = (assign(l), assign(r))
        return my

    assign(frags[0])
    return children, leaf_slot


def synthetic_trees(n, n_leaves, vocab_size, seed=0):
    """Tokens 1..v/3 positive, v/3..2v/3 negative, rest neutral; root label =
    sign of (positives - negatives)."""
    from bigdl_tpu.dataset.sample import Sample
    rng = np.random.default_rng(seed)
    third = vocab_size // 3
    samples = []
    total = 2 * n_leaves - 1
    for _ in range(n):
        children, leaf_slot = random_tree(n_leaves, rng)
        tokens = rng.integers(0, vocab_size, size=n_leaves)
        ids = np.zeros(total, np.int32)  # internal nodes embed token 0 (pad)
        ids[leaf_slot] = tokens + 1      # reserve 0 for internal/pad
        score = int((tokens < third).sum()) - int(((tokens >= third)
                                                   & (tokens < 2 * third)).sum())
        label = np.int32(1 if score > 0 else 0)
        samples.append(Sample((ids, children), label))
    return samples


def build_model(vocab_size: int, embed_dim: int, hidden: int,
                class_num: int = 2):
    from bigdl_tpu import nn
    from bigdl_tpu.nn.tree import BinaryTreeLSTM

    inp = nn.Input()
    ids = nn.SelectTable(1).inputs(inp)
    children = nn.SelectTable(2).inputs(inp)
    emb = nn.LookupTable(vocab_size + 1, embed_dim, zero_based=True).inputs(ids)
    h = BinaryTreeLSTM(embed_dim, hidden).inputs(emb, children)
    root = nn.Select(2, 1).inputs(h)        # node 0 = root
    out = nn.Linear(hidden, class_num).inputs(root)
    out = nn.LogSoftMax().inputs(out)
    return nn.Graph(inp, out)


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim import (
        Adam, DistriOptimizer, LocalOptimizer, TreeNNAccuracy, Trigger,
    )
    from bigdl_tpu.utils.engine import Engine

    Engine.init()
    samples = synthetic_trees(args.trees, args.leaves, args.vocab_size)
    split = int(0.9 * len(samples))
    train = DataSet.array(samples[:split], distributed=args.distributed) \
        >> SampleToMiniBatch(args.batch_size)
    test = DataSet.array(samples[split:]) >> SampleToMiniBatch(args.batch_size)

    model = build_model(args.vocab_size, args.embed_dim, args.hidden)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    opt = (cls(model, train, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learningrate=args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_validation(Trigger.every_epoch(), test, [TreeNNAccuracy()]))
    opt.log_every = 10
    opt.optimize()
    acc = opt.state["scores"]["TreeNNAccuracy"]
    print(f"TreeLSTM held-out TreeNNAccuracy (root): {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
