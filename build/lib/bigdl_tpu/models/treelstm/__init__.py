"""TreeLSTM sentiment example package; see train.py for the main and model."""
