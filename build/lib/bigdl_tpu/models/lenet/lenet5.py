"""LeNet-5 (reference parity: ``<dl>/models/lenet/LeNet5.scala`` — unverified, SURVEY.md
§2.5): conv(1→6,5x5) → tanh → maxpool → conv(6→12,5x5) → tanh → maxpool → fc(100) → tanh
→ fc(classNum) → logsoftmax. Baseline config #1 (BASELINE.md)."""

from bigdl_tpu import nn


def LeNet5(class_num: int = 10) -> nn.Sequential:
    return (nn.Sequential()
            .add(nn.Reshape([1, 28, 28]))
            .add(nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"))
            .add(nn.Tanh())
            .add(nn.SpatialMaxPooling(2, 2, 2, 2))
            .add(nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"))
            .add(nn.Tanh())
            .add(nn.SpatialMaxPooling(2, 2, 2, 2))
            .add(nn.Reshape([12 * 4 * 4]))
            .add(nn.Linear(12 * 4 * 4, 100).set_name("fc_1"))
            .add(nn.Tanh())
            .add(nn.Linear(100, class_num).set_name("fc_2"))
            .add(nn.LogSoftMax()))
