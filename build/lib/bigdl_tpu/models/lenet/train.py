"""LeNet-5 MNIST training main (reference parity: ``<dl>/models/lenet/Train.scala`` with
its scopt options — unverified, SURVEY.md §2.5). ``python -m bigdl_tpu.models.lenet.train``.
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="LeNet-5 on MNIST")
    p.add_argument("-f", "--folder", default=None, help="MNIST data dir (idx files)")
    p.add_argument("-b", "--batch-size", type=int, default=128)
    p.add_argument("--learning-rate", type=float, default=0.05)
    p.add_argument("--learning-rate-decay", type=float, default=0.0)
    p.add_argument("--max-epoch", type=int, default=1)
    p.add_argument("--checkpoint", default=None, help="checkpoint dir")
    p.add_argument("--overwrite-checkpoint", action="store_true")
    p.add_argument("--model-snapshot", default=None, help="resume model snapshot")
    p.add_argument("--state-snapshot", default=None, help="resume optim state snapshot")
    p.add_argument("--summary-dir", default=None, help="TensorBoard summary dir")
    p.add_argument("--distributed", action="store_true",
                   help="train with DistriOptimizer over the device mesh")
    p.add_argument("--synthetic-size", type=int, default=2048,
                   help="synthetic fallback dataset size when no data folder")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.mnist import load_mnist, to_samples
    from bigdl_tpu.models.lenet import LeNet5
    from bigdl_tpu.optim import (
        DistriOptimizer, LocalOptimizer, SGD, Top1Accuracy, Trigger,
    )
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    train = to_samples(*load_mnist(args.folder, "train",
                                   synthetic_size=args.synthetic_size))
    test = to_samples(*load_mnist(args.folder, "test",
                                  synthetic_size=max(args.synthetic_size // 4, 256)))
    train_set = (DataSet.array(train, distributed=args.distributed)
                 >> SampleToMiniBatch(args.batch_size))
    test_set = (DataSet.array(test, distributed=args.distributed)
                >> SampleToMiniBatch(args.batch_size))

    if args.model_snapshot:
        model = nn.AbstractModule.load(args.model_snapshot)
    else:
        model = LeNet5(10)
    if args.state_snapshot:
        from bigdl_tpu.utils import file as _file
        method = _file.load(args.state_snapshot)
    else:
        method = SGD(learningrate=args.learning_rate,
                     learningrate_decay=args.learning_rate_decay)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    optimizer = (cls(model, train_set, nn.ClassNLLCriterion())
                 .set_optim_method(method)
                 .set_end_when(Trigger.max_epoch(args.max_epoch))
                 .set_validation(Trigger.every_epoch(), test_set, [Top1Accuracy()]))
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, Trigger.every_epoch())
        optimizer.over_write_checkpoint(args.overwrite_checkpoint)
    if args.summary_dir:
        from bigdl_tpu.visualization import TrainSummary, ValidationSummary
        optimizer.set_train_summary(TrainSummary(args.summary_dir, "lenet"))
        optimizer.set_val_summary(ValidationSummary(args.summary_dir, "lenet"))
    trained = optimizer.optimize()
    print(f"final loss: {optimizer.state['loss']:.4f}")
    return trained


if __name__ == "__main__":
    main()
