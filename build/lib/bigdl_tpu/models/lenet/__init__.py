from bigdl_tpu.models.lenet.lenet5 import LeNet5
