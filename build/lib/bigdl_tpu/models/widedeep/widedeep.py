"""Wide & Deep — the second recommendation-example model.

Reference parity (SURVEY.md §2.5 Examples, expected upstream
``<dl>/example/recommendation/WideAndDeep*`` — unverified, mount empty): a wide
linear model over sparse one-hot/cross features joined with a deep MLP over
embeddings + dense columns, summed into the output logits.

TPU-native: the wide branch is :class:`SparseLinear` over padded id lists (the
SparseTensor redesign — nn/sparse.py), the deep branch is bag-of-ids embeddings
concatenated with dense features through a ReLU tower, and the whole model is
one ``nn.Graph`` compiled into a single XLA program.

Input: Table/tuple ``(wide_ids (N, Kw) int32 pad=-1, deep_ids (N, Kd) int32
pad=-1, dense (N, D) float32)`` → (N, class_num) log-probabilities.
"""

from __future__ import annotations

from bigdl_tpu import nn
from bigdl_tpu.nn.sparse import SparseEmbeddingSum, SparseLinear


def WideAndDeep(wide_features: int, deep_vocab: int, dense_dim: int,
                class_num: int = 2, embed_dim: int = 16,
                hidden_layers: tuple[int, ...] = (64, 32)) -> nn.Graph:
    inp = nn.Input()
    wide_ids = nn.SelectTable(1).inputs(inp)
    deep_ids = nn.SelectTable(2).inputs(inp)
    dense = nn.SelectTable(3).inputs(inp)

    # wide: sparse linear straight to the logits
    wide_out = SparseLinear(wide_features, class_num).inputs(wide_ids)

    # deep: embedding bag + dense → MLP → logits
    emb = SparseEmbeddingSum(deep_vocab, embed_dim, combiner="mean").inputs(deep_ids)
    x = nn.JoinTable(2).inputs(emb, dense)
    in_dim = embed_dim + dense_dim
    for width in hidden_layers:
        x = nn.Linear(in_dim, width).inputs(x)
        x = nn.ReLU().inputs(x)
        in_dim = width
    deep_out = nn.Linear(in_dim, class_num).inputs(x)

    out = nn.CAddTable().inputs(wide_out, deep_out)
    out = nn.LogSoftMax().inputs(out)
    return nn.Graph(inp, out)
