"""Wide&Deep recommendation example main (reference parity: upstream
``example/recommendation/WideAndDeepExample.scala`` — unverified, SURVEY.md §2.5).

``python -m bigdl_tpu.models.widedeep.train`` — synthetic tabular CTR-style
task: each example has sparse "wide" ids (memorization features — one id is a
direct label leak with some noise), sparse "deep" category ids, and dense
numeric columns (generalization features). Trains and reports Top1 accuracy,
which must beat the class prior.
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Wide&Deep on synthetic tabular data")
    p.add_argument("-b", "--batch-size", type=int, default=256)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--max-epoch", type=int, default=5)
    p.add_argument("--examples", type=int, default=8192)
    p.add_argument("--wide-features", type=int, default=500)
    p.add_argument("--deep-vocab", type=int, default=200)
    p.add_argument("--dense-dim", type=int, default=8)
    p.add_argument("--wide-k", type=int, default=4)
    p.add_argument("--deep-k", type=int, default=6)
    p.add_argument("--distributed", action="store_true")
    return p


def synthetic_tabular(n, wide_features, deep_vocab, dense_dim, wide_k, deep_k,
                      seed=0):
    """Binary label from (a) a memorizable wide id and (b) a dense linear rule —
    so the model needs BOTH branches to do well."""
    from bigdl_tpu.dataset.sample import Sample
    rng = np.random.default_rng(seed)
    wide_signal = rng.integers(0, 2, size=wide_features)   # id → label bias
    w_dense = rng.normal(size=dense_dim)
    samples = []
    for _ in range(n):
        wide_ids = rng.choice(wide_features, size=wide_k, replace=False)
        deep_ids = rng.choice(deep_vocab, size=deep_k, replace=False)
        dense = rng.normal(size=dense_dim).astype(np.float32)
        logit = (2.0 * wide_signal[wide_ids[0]] - 1.0) + dense @ w_dense
        y = np.int32(1 if logit + 0.3 * rng.normal() > 0 else 0)
        samples.append(Sample((wide_ids.astype(np.int32),
                               deep_ids.astype(np.int32), dense), y))
    return samples


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.models.widedeep import WideAndDeep
    from bigdl_tpu.optim import (
        Adam, DistriOptimizer, LocalOptimizer, Top1Accuracy, Trigger,
    )
    from bigdl_tpu.utils.engine import Engine

    Engine.init()
    samples = synthetic_tabular(args.examples, args.wide_features,
                                args.deep_vocab, args.dense_dim,
                                args.wide_k, args.deep_k)
    split = int(0.9 * len(samples))
    train = DataSet.array(samples[:split], distributed=args.distributed) \
        >> SampleToMiniBatch(args.batch_size)
    test = DataSet.array(samples[split:]) >> SampleToMiniBatch(args.batch_size)

    model = WideAndDeep(args.wide_features, args.deep_vocab, args.dense_dim)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    opt = (cls(model, train, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learningrate=args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch))
           .set_validation(Trigger.every_epoch(), test, [Top1Accuracy()]))
    opt.log_every = 20
    opt.optimize()
    acc = opt.state["scores"]["Top1Accuracy"]
    print(f"Wide&Deep held-out Top1Accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
