from bigdl_tpu.models.widedeep.widedeep import WideAndDeep

__all__ = ["WideAndDeep"]
