from bigdl_tpu.models.rnn.rnn import PTBModel, SimpleRNN
