"""RNN language models (PTB word-level LM + SimpleRNN).

Reference parity (SURVEY.md §2.5, expected ``<dl>/models/rnn/`` and
``<dl>/example/languagemodel/PTBModel.scala`` — unverified, mount empty): ``PTBModel`` is
LookupTable(vocab→hidden) → numLayers stacked LSTMs → TimeDistributed(Linear(hidden→vocab))
→ TimeDistributed(LogSoftMax), trained with ``TimeDistributedCriterion(ClassNLLCriterion)``
on bptt-length windows; ``SimpleRNN`` is the small tanh-RnnCell variant used by the text
generation example. Baseline config #4 (BASELINE.md).

TPU-native notes: each LSTM layer is a ``Recurrent`` container whose time loop is ONE
``lax.scan`` (SURVEY.md §5.7 — the reference re-ran a Scala loop per step); stacking layers
keeps everything inside a single jit so XLA pipelines the per-step 4H-gate matmuls on the
MXU.
"""

from __future__ import annotations

from bigdl_tpu import nn


def PTBModel(input_size: int, hidden_size: int = 650, output_size: int | None = None,
             num_layers: int = 2, dropout: float = 0.0,
             key_proj: bool = False) -> nn.Sequential:
    """Word-level PTB LSTM LM. ``input_size``/``output_size`` = vocabulary size."""
    output_size = output_size if output_size is not None else input_size
    model = (nn.Sequential()
             .add(nn.LookupTable(input_size, hidden_size, zero_based=True)
                  .set_name("embedding")))
    for i in range(num_layers):
        if dropout > 0:
            model.add(nn.Dropout(dropout))
        model.add(nn.Recurrent(nn.LSTM(hidden_size, hidden_size))
                  .set_name(f"lstm{i + 1}"))
    if dropout > 0:
        model.add(nn.Dropout(dropout))
    model.add(nn.TimeDistributed(nn.Linear(hidden_size, output_size))
              .set_name("decoder"))
    model.add(nn.TimeDistributed(nn.LogSoftMax()))
    return model


def SimpleRNN(input_size: int, hidden_size: int, output_size: int) -> nn.Sequential:
    """Tanh-cell RNN LM (reference ``models/rnn/SimpleRNN``)."""
    return (nn.Sequential()
            .add(nn.LookupTable(input_size, hidden_size))
            .add(nn.Recurrent(nn.RnnCell(hidden_size, hidden_size)))
            .add(nn.TimeDistributed(nn.Linear(hidden_size, output_size)))
            .add(nn.TimeDistributed(nn.LogSoftMax())))
