"""bigdl_tpu — a TPU-native deep-learning framework with the capabilities of BigDL classic.

This is a ground-up re-design of the reference framework (skamble91/BigDL, a fork of
intel-analytics/BigDL "classic") for TPU hardware:

- the reference's ``DenseTensor`` + Intel-MKL JNI math becomes ``jax.numpy`` lowered by XLA
  onto the MXU/VPU (the JNI seam is deleted, not bridged);
- its Torch-style mutable module system (``AbstractModule.forward/backward``) keeps its API
  shape but is backed by a pure functional core (pytree params, ``jax.vjp``) so whole training
  steps compile to one XLA program;
- its Spark ``DistriOptimizer`` + BlockManager partitioned all-reduce becomes data-parallel
  ``jit`` over a ``jax.sharding.Mesh`` with ICI collectives (reduce-scatter → sharded optimizer
  update → all-gather, the exact ZeRO-1 structure the reference's ``AllReduceParameter``
  pioneered on Spark);
- ``Engine.init`` selects a device mesh instead of a CPU thread topology.

Reference provenance: the survey of the reference lives in SURVEY.md. NOTE: the reference
mount was empty in rounds 0-1, so reference citations in docstrings give the *expected
upstream path* (e.g. ``<dl>/nn/Linear.scala``) per SURVEY.md §2 and are marked unverified.
"""

__version__ = "0.1.0"

from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.table import Table, T

__all__ = ["Engine", "Table", "T", "__version__"]
