"""sklearn-compatible estimator wrappers — the Spark-ML pipeline analog.

Reference parity (SURVEY.md §2.5, expected ``<dl>/dlframes/`` ``DLEstimator`` /
``DLClassifier`` / ``DLModel`` — unverified, mount empty): the reference wraps a
BigDL module + criterion as a ``spark.ml`` Estimator so deep models slot into ML
pipelines over DataFrames.

TPU-native: the ecosystem pipeline API here is scikit-learn — ``DLEstimator``
implements the sklearn estimator contract (``fit(X, y)`` / ``predict`` /
``get_params``/``set_params`` via ``BaseEstimator``), so BigDL-TPU models
compose with ``sklearn.pipeline.Pipeline``, ``GridSearchCV``, and
``cross_val_score``. Training runs through the framework's own compiled-step
trainer (LocalOptimizer), not a reimplementation.
"""

from __future__ import annotations

import numpy as np
from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin

from bigdl_tpu.nn.abstractnn import AbstractModule


class DLEstimator(BaseEstimator):
    """Fit an arbitrary module + criterion on (X, y) numpy data.

    ``model_fn``: zero-arg factory returning a fresh AbstractModule — a factory
    (not an instance) so sklearn ``clone()`` / ``GridSearchCV`` re-fits start
    from fresh parameters. ``criterion_fn`` likewise.
    """

    _estimator_type = "regressor"

    def __init__(self, model_fn=None, criterion_fn=None, batch_size: int = 32,
                 max_epoch: int = 10, learning_rate: float = 1e-3,
                 optim_method: str = "adam"):
        self.model_fn = model_fn
        self.criterion_fn = criterion_fn
        self.batch_size = batch_size
        self.max_epoch = max_epoch
        self.learning_rate = learning_rate
        self.optim_method = optim_method

    # ------------------------------------------------------------------ fit
    def _build_optim(self):
        from bigdl_tpu.optim import Adam, SGD
        if self.optim_method == "adam":
            return Adam(learningrate=self.learning_rate)
        if self.optim_method == "sgd":
            return SGD(learningrate=self.learning_rate, momentum=0.9,
                       dampening=0.0)
        raise ValueError(f"optim_method must be 'adam' or 'sgd', "
                         f"got {self.optim_method!r}")

    def _label_dtype(self):
        return np.float32

    def fit(self, X, y):
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.optim import LocalOptimizer, Trigger
        from bigdl_tpu.utils.engine import Engine

        Engine._require_init()
        if self.model_fn is None or self.criterion_fn is None:
            raise ValueError("model_fn and criterion_fn are required")
        X = np.asarray(X, np.float32)
        y = np.asarray(y, self._label_dtype())
        if len(X) != len(y):
            raise ValueError(
                f"inconsistent sample counts: X has {len(X)}, y has {len(y)}")
        if y.ndim == 1 and np.issubdtype(y.dtype, np.floating):
            # regression targets must match the model's (N, 1) output — a bare
            # (N,) target would silently broadcast the loss to (N, N)
            y = y[:, None]
        samples = [Sample(x, t) for x, t in zip(X, y)]
        data = DataSet.array(samples) >> SampleToMiniBatch(self.batch_size)
        self.model_ = self.model_fn()
        if not isinstance(self.model_, AbstractModule):
            raise TypeError("model_fn must return an AbstractModule")
        opt = (LocalOptimizer(self.model_, data, self.criterion_fn())
               .set_optim_method(self._build_optim())
               .set_end_when(Trigger.max_epoch(self.max_epoch)))
        opt.log_every = 10 ** 9  # silent inside pipelines
        opt.optimize()
        self.n_features_in_ = X.shape[1] if X.ndim > 1 else 1
        return self

    # -------------------------------------------------------------- predict
    def _forward(self, X):
        self._check_fitted()
        return np.asarray(self.model_.predict(np.asarray(X, np.float32),
                                              batch_size=self.batch_size))

    def _check_fitted(self):
        if not hasattr(self, "model_"):
            raise RuntimeError("estimator is not fitted; call fit(X, y) first")

    def predict(self, X):
        return self._forward(X)


class DLClassifier(ClassifierMixin, DLEstimator):
    """Classification variant: integer labels, ``predict`` returns class ids,
    ``predict_proba`` / ``predict_log_proba`` expose the model's distribution
    (model output is expected to be log-probabilities, the zoo convention)."""

    _estimator_type = "classifier"

    def _label_dtype(self):
        return np.int32

    def fit(self, X, y):
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        idx = {c: i for i, c in enumerate(self.classes_)}
        return super().fit(X, np.asarray([idx[c] for c in y]))

    def predict(self, X):
        self._check_fitted()
        return self.classes_[np.argmax(self._forward(X), axis=-1)]

    def predict_log_proba(self, X):
        return self._forward(X)

    def predict_proba(self, X):
        return np.exp(self._forward(X))


class DLRegressor(RegressorMixin, DLEstimator):
    """Regression variant (squeezes trailing singleton output dims)."""

    def predict(self, X):
        out = self._forward(X)
        return out[:, 0] if out.ndim == 2 and out.shape[1] == 1 else out
