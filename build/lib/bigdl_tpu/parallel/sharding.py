"""Sharding helpers — the TPU-native replacement for the reference's parameter-partition
machinery.

Reference parity (SURVEY.md §2.3/§5.8, expected ``<dl>/parameters/AllReduceParameter.scala``
— unverified): the reference flattens all parameters into one vector, splits it into
``partitionNum`` slices, and moves gradient/weight slices through the Spark BlockManager —
structurally reduce-scatter → per-slice optimizer update → all-gather (ZeRO-1).

TPU-native: no flattening, no explicit messaging. Pytrees get ``NamedSharding`` annotations
over the Engine mesh and XLA's SPMD partitioner emits the ICI collectives:

- replicated params + batch sharded on ``data`` → XLA inserts the gradient all-reduce;
- ``zero1_state_sharding`` shards optimizer slots over ``data`` → the (elementwise) update
  computes sharded and XLA all-gathers the new params — the exact slice-owned update the
  reference ran over BlockManager, minus the seam.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def shard_leading_axis(mesh: Mesh, x_shape, axis: str = "data") -> NamedSharding:
    """Shard dim 0 over ``axis`` when divisible, else replicate (per-leaf decision)."""
    n = int(dict(mesh.shape)[axis])
    if len(x_shape) > 0 and x_shape[0] % n == 0 and x_shape[0] >= n:
        return NamedSharding(mesh, P(axis))
    return NamedSharding(mesh, P())


def zero1_state_sharding(mesh: Mesh, state_tree, axis: str = "data"):
    """A sharding pytree for optimizer slots: leading-axis sharded where divisible.

    Matches the reference's slice-owned optimizer state (each partition updates 1/N of the
    parameter vector); here the slicing is per-leaf along dim 0 and XLA handles the
    reduce-scatter/all-gather placement.
    """
    import jax

    return jax.tree_util.tree_map(
        lambda x: shard_leading_axis(mesh, np.shape(x), axis), state_tree)
