"""Tensor parallelism — parameter-sharding rules over the mesh's ``model`` axis.

No reference counterpart (SURVEY.md §2.3 parallelism checklist: TP absent upstream);
required capability of the TPU build. TPU-native design: TP is *declarative* — params
get ``NamedSharding`` annotations and XLA's SPMD partitioner splits the matmuls and
inserts the activation collectives (all-gather/reduce-scatter over ICI). No manual
collective calls, no module rewrites: the same model runs 1-chip or TP=8 by changing
only the rules.

Rules are ``(path_substring_or_regex, PartitionSpec)`` pairs matched against the
pytree path of each parameter leaf (e.g. ``("classifier/weight", P("model", None))``
for a column-parallel Linear). Helpers provide the two Megatron-style Linear
shardings; pair a column-parallel layer with a following row-parallel layer so the
intermediate activation stays sharded and only one all-reduce happens per pair.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.tree_util import keystr, tree_map_with_path


def _normalize_path(path) -> str:
    # keystr gives e.g. "['1']['weight']" — normalize to "1/weight"
    return keystr(path).replace("']['", "/").strip("[]'\"")


def column_parallel(model_axis: str = "model") -> P:
    """Linear weight (out, in) split on the output dim; bias splits with it."""
    return P(model_axis, None)


def row_parallel(model_axis: str = "model") -> P:
    """Linear weight (out, in) split on the input dim; bias replicated."""
    return P(None, model_axis)


class TPRules:
    """Ordered parameter-path → PartitionSpec rules (first match wins)."""

    def __init__(self, rules: Sequence[Tuple[str, P]] = (),
                 default: P = P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def add(self, pattern: str, spec: P) -> "TPRules":
        self.rules.append((re.compile(pattern), spec))
        return self

    def match(self, path: str, shape) -> Optional[P]:
        """The first matching rule's spec, or None (no rule matched)."""
        for pat, spec in self.rules:
            if pat.search(path):
                self._check(path, spec, shape)
                return spec
        return None

    def spec_for(self, path: str, shape) -> P:
        spec = self.match(path, shape)
        return self.default if spec is None else spec

    @staticmethod
    def _check(path: str, spec: P, shape) -> None:
        if len(spec) > len(shape):
            raise ValueError(
                f"TP rule for {path!r}: spec {spec} has more axes than the "
                f"parameter shape {tuple(shape)}")

    def param_shardings(self, params, mesh: Mesh):
        """NamedSharding pytree for a parameter tree. Divisibility is validated
        eagerly so a bad rule fails at compile time with the path named."""
        axes = dict(mesh.shape)

        def one(path, leaf):
            p = _normalize_path(path)
            shape = np.shape(leaf)
            spec = self.spec_for(p, shape)
            for dim, ax in enumerate(spec):
                if ax is None:
                    continue
                size = axes.get(ax)
                if size is None:
                    raise ValueError(
                        f"TP rule for {p!r} uses mesh axis {ax!r}, not in mesh "
                        f"{tuple(axes)}")
                if shape[dim] % size != 0:
                    raise ValueError(
                        f"TP rule for {p!r}: dim {dim} of shape {shape} not "
                        f"divisible by {ax!r} axis size {size}")
            return NamedSharding(mesh, spec)

        return tree_map_with_path(one, params)

    def slot_shardings(self, state_shapes, mesh: Mesh,
                       dp_axis: Optional[str] = None):
        """Shardings for optimizer slot trees. Slot trees mirror the param tree
        one level down (e.g. ``state["v"][...]``), so rule paths match them too:
        slots of a TP-sharded param follow the param's sharding; the rest are
        replicated, or — when ``dp_axis`` is given (ZeRO-1) — sharded on their
        leading dim over the data axis."""
        from bigdl_tpu.parallel.sharding import shard_leading_axis

        def one(path, leaf):
            p = _normalize_path(path)
            shape = np.shape(leaf)
            spec = self.match(p, shape)
            if spec is not None:
                return NamedSharding(mesh, spec)
            if dp_axis is not None:
                return shard_leading_axis(mesh, shape, dp_axis)
            return NamedSharding(mesh, P())

        return tree_map_with_path(one, state_shapes)


def megatron_mlp_rules(up_pattern: str, down_pattern: str,
                       model_axis: str = "model") -> TPRules:
    """The canonical pair: up-projection column-parallel, down-projection
    row-parallel → one all-reduce per MLP block instead of two.

    Patterns are boundary-anchored so layer index "1" cannot match "11"."""
    return TPRules([
        (rf"(^|/){up_pattern}/weight$", column_parallel(model_axis)),
        (rf"(^|/){up_pattern}/bias$", P(model_axis)),
        (rf"(^|/){down_pattern}/weight$", row_parallel(model_axis)),
        (rf"(^|/){down_pattern}/bias$", P()),
    ])
