"""Pipeline parallelism — GPipe-style stage sharding over the ``pipe`` axis.

No reference counterpart (SURVEY.md §2.3 checklist: PP absent upstream —
design headroom for the TPU build, like ring attention and MoE). Homogeneous
stages (identical pytree structure, input shape = output shape) are stacked on
a leading stage dim sharded over the mesh's ``pipe`` axis; under ``shard_map``
each device holds one stage and the classic GPipe schedule runs: at tick ``t``
a device applies its stage to the activation it received, then ``ppermute``\\ s
the result to its right neighbor. After ``M + S - 1`` ticks every microbatch
has crossed all ``S`` stages. The backward schedule needs no hand-written code:
jax reverse-mode differentiates through the ``lax.scan`` + ``ppermute`` chain,
producing the reversed-communication backward pipeline automatically — the
whole train step stays ONE jitted program.

Off-mesh (no ``pipe`` axis) the same microbatch loop runs without
communication, so tests and single-chip runs get identical math.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from bigdl_tpu.nn.abstractnn import AbstractModule, Container


class GPipe(Container):
    """Pipeline container: ``n_stages`` clones of ``stage`` composed
    sequentially, executed as a pipeline over the ``pipe`` mesh axis when
    present. Stages must be stateless (no BatchNorm running stats) and
    shape-preserving (output shape == input shape)."""

    def __init__(self, stage: Optional[AbstractModule] = None,
                 n_stages: int = 1, n_microbatches: int = 2,
                 axis_name: str = "pipe"):
        mods = []
        if stage is not None:
            if jax.tree_util.tree_leaves(stage.get_state()):
                raise ValueError("GPipe stages must be stateless")
            mods = [stage]
            for _ in range(n_stages - 1):
                c = stage.clone()
                c.reset()  # independent parameters per stage
                mods.append(c)
        super().__init__(*mods)
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.axis_name = axis_name

    # ------------------------------------------------------------------ run
    def _stage_apply(self, params, x, training):
        # stages are stateless, but containers still want the structured
        # (empty) state tree
        out, _ = self.modules[0].apply(params, self.modules[0].get_state(), x,
                                       training=training, rng=None)
        return out

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.utils.engine import Engine

        s, m = self.n_stages, self.n_microbatches
        b = input.shape[0]
        if b % m != 0:
            raise ValueError(f"batch {b} not divisible by n_microbatches {m}")

        mesh = Engine.mesh() if Engine.is_initialized() else None
        axes = dict(mesh.shape) if mesh is not None else {}
        if axes.get(self.axis_name, 1) == s and s > 1:
            # under dp x pp the batch stays sharded over `data` inside the
            # shard_map (replicating it would all-gather and nullify dp)
            data_axis = Engine.DATA_AXIS if Engine.DATA_AXIS in axes else None
            d = axes.get(data_axis, 1) if data_axis else 1
            if d > 1 and (b % d != 0 or (b // d) % m != 0):
                raise ValueError(
                    f"batch {b} must divide by data size {d} and the local "
                    f"batch by n_microbatches {m}")
            return self._apply_sharded(params, input, training, mesh,
                                       data_axis if d > 1 else None), state

        # sequential fallback: same stage composition, no communication
        y = input
        for i in range(s):
            y = self._stage_apply(params[str(i)], y, training)
        return y, state

    def _apply_sharded(self, params, x, training, mesh, data_axis=None):
        s, m = self.n_stages, self.n_microbatches
        axis = self.axis_name
        x_spec = P(data_axis) if data_axis else P()
        # stack per-stage params on a leading stage dim (sharded over `pipe`)
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *[params[str(i)] for i in range(s)])

        def body(p_stk, xs):
            rank = lax.axis_index(axis)
            p = jax.tree_util.tree_map(lambda l: l[0], p_stk)  # my stage
            micro = xs.reshape((m, xs.shape[0] // m) + xs.shape[1:])
            # carries become device-varying after the first ppermute; mark the
            # (invariant) zeros accordingly or scan rejects the carry typing
            zero = lax.pcast(jnp.zeros_like(micro[0]), (axis,), to="varying")
            out_acc = lax.pcast(jnp.zeros_like(micro), (axis,), to="varying")
            perm = [(i, i + 1) for i in range(s - 1)]

            def tick(carry, t):
                recv, out_acc = carry
                feed = micro[jnp.minimum(t, m - 1)]
                inp = jnp.where(jnp.logical_and(rank == 0, t < m), feed, recv)
                out = self._stage_apply(p, inp, training)
                # last stage banks microbatch t-(s-1) when it emerges
                slot = jnp.clip(t - (s - 1), 0, m - 1)
                bank = jnp.logical_and(rank == s - 1, t >= s - 1)
                prev = lax.dynamic_index_in_dim(out_acc, slot, 0,
                                                keepdims=False)
                out_acc = lax.dynamic_update_index_in_dim(
                    out_acc, jnp.where(bank, out, prev), slot, axis=0)
                recv = lax.ppermute(out, axis, perm)
                return (recv, out_acc), None

            (recv, out_acc), _ = lax.scan(tick, (zero, out_acc),
                                          jnp.arange(m + s - 1))
            # results live on the last stage only → broadcast over the axis
            out_acc = jnp.where(lax.axis_index(axis) == s - 1, out_acc, 0.0)
            out_acc = lax.psum(out_acc, axis)
            return out_acc.reshape(xs.shape)

        spec_p = jax.tree_util.tree_map(lambda _: P(axis), stacked)
        fn = jax.shard_map(body, mesh=mesh,
                           in_specs=(spec_p, x_spec), out_specs=x_spec)
        return fn(stacked, x)

    def __repr__(self):
        return (f"GPipe(stages={self.n_stages}, "
                f"microbatches={self.n_microbatches})")
