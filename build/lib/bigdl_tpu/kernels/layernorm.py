"""Pallas TPU kernels — fused LayerNorm.

This is the framework's Pallas layer (SURVEY.md §7.1: "Pallas reserved for true
gaps"): XLA fuses most elementwise chains into adjacent matmuls on its own, but
row-normalisation is a 3-pass pattern (mean, variance, scale) the compiler
sometimes leaves as separate HBM round trips on large rows. The kernel below
does all three passes in one VMEM residency per row-block: a (block_rows, H)
tile is loaded once, reduced on the VPU, normalised, scaled, and written once.

Semantics: forward is the Pallas kernel on TPU (interpreter elsewhere/on CPU
tests); the backward pass is the standard recompute-form VJP in plain jnp —
rematerialisation is the TPU-idiomatic trade (one extra fused forward instead
of stashing normalised activations in HBM).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _reference_layer_norm(x, gamma, beta, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    return (x - mean) * inv * gamma + beta


def _pallas_layer_norm(x2d, gamma, beta, eps, block_rows, interpret):
    from jax.experimental import pallas as pl

    n, h = x2d.shape

    def kernel(x_ref, g_ref, b_ref, o_ref):
        x = x_ref[:].astype(jnp.float32)        # (block_rows, H) in VMEM
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
        inv = jax.lax.rsqrt(var + eps)
        o_ref[:] = ((x - mean) * inv * g_ref[:] + b_ref[:]).astype(o_ref.dtype)

    grid = (n // block_rows,)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, h), x2d.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, h), lambda i: (i, 0)),
        interpret=interpret,
    )(x2d, gamma, beta)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_layer_norm(x, gamma, beta, eps: float = 1e-5,
                     force_pallas: bool | None = None):
    """LayerNorm over the last axis. ``force_pallas``: None = pallas on TPU,
    reference jnp elsewhere; True = pallas (interpreted off-TPU — tests);
    False = reference."""
    return _fln_fwd(x, gamma, beta, eps, force_pallas)[0]


def _fln_fwd(x, gamma, beta, eps, force_pallas):
    use_pallas = _on_tpu() if force_pallas is None else force_pallas
    h = x.shape[-1]
    lead = x.shape[:-1]
    out = None
    if use_pallas:
        n = 1
        for d in lead:
            n *= d
        x2d = x.reshape(n, h)
        # block over rows: biggest power-of-two divisor up to 256 keeps the
        # tile in VMEM for any realistic H while aligning to the 8-sublane tile
        block = 1
        while block < 256 and n % (block * 2) == 0:
            block *= 2
        try:
            out = _pallas_layer_norm(x2d, gamma, beta, eps, block,
                                     interpret=not _on_tpu()).reshape(x.shape)
        except Exception:  # pallas unavailable (platform/version) → reference
            out = None
    if out is None:
        out = _reference_layer_norm(x, gamma, beta, eps)
    return out, (x, gamma, beta)


def _fln_bwd(eps, force_pallas, res, g):
    x, gamma, beta = res
    # recompute-form VJP of the reference formula (rematerialisation)
    _, vjp = jax.vjp(lambda xx, gg, bb: _reference_layer_norm(xx, gg, bb, eps),
                     x, gamma, beta)
    return vjp(g)


fused_layer_norm.defvjp(_fln_fwd, _fln_bwd)
