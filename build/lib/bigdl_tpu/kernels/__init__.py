from bigdl_tpu.kernels.layernorm import fused_layer_norm

__all__ = ["fused_layer_norm"]
