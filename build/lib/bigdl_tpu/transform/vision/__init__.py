from bigdl_tpu.transform.vision.image import (
    AspectScale, Brightness, CenterCrop, ChannelNormalize, ChannelOrder,
    ColorJitter, Contrast, Expand, FeatureTransformer, HFlip, ImageFeature,
    ImageFrame, ImageFrameToSample, Lighting, MatToTensor, Pipeline,
    PixelBytesToMat, RandomCrop, RandomHFlip, RandomTransformer, Resize,
    Saturation,
)
