from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, DataSet, DistributedDataSet, LocalDataSet, TransformedDataSet,
    is_distributed,
)
from bigdl_tpu.dataset.sample import MiniBatch, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.transformer import (
    ChainedTransformer, Identity, MapTransformer, Transformer,
)
from bigdl_tpu.dataset.text import (
    Dictionary, LabeledSentenceToSample, SentenceTokenizer, TextToLabeledSentence,
)
