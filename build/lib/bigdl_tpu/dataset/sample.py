"""Sample and MiniBatch.

Reference parity (SURVEY.md §2.2, expected ``<dl>/dataset/Sample.scala``, ``MiniBatch.scala``
— unverified): a ``Sample`` is (feature tensors, label tensors) with contiguous storage; a
``MiniBatch`` stacks samples with optional padding; ``SampleToMiniBatch`` is the batching
transformer.

TPU-native: host-side numpy until the trainer's device put; batches keep STATIC shapes
(fixed batch size — the final partial batch is padded up and carries an explicit valid-count
so jit never sees a new shape; the reference padded too, for a different reason).
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer


class Sample:
    def __init__(self, feature, label=None):
        self.feature = (tuple(np.asarray(f) for f in feature)
                        if isinstance(feature, (tuple, list))
                        else (np.asarray(feature),))
        if label is None:
            self.label = ()
        else:
            self.label = (tuple(np.asarray(l) for l in label)
                          if isinstance(label, (tuple, list))
                          else (np.asarray(label),))

    @property
    def features(self):
        return self.feature

    @property
    def labels(self):
        return self.label

    def __repr__(self):
        fs = ",".join(str(f.shape) for f in self.feature)
        ls = ",".join(str(l.shape) for l in self.label)
        return f"Sample(feature={fs}, label={ls})"


class MiniBatch:
    """Stacked batch. ``size`` is the padded batch size; ``valid`` the real sample count."""

    def __init__(self, input, target=None, valid: Optional[int] = None):
        self.input = input
        self.target = target
        self.valid = valid if valid is not None else _batch_dim(input)

    def size(self) -> int:
        return _batch_dim(self.input)

    def __repr__(self):
        return f"MiniBatch(size={self.size()}, valid={self.valid})"


def _batch_dim(x) -> int:
    if isinstance(x, (tuple, list)):
        return _batch_dim(x[0])
    return int(np.asarray(x).shape[0])


class SampleToMiniBatch(Transformer):
    """Group Samples into fixed-size MiniBatches.

    ``pad_last=True`` (default) repeats trailing samples so every batch has exactly
    ``batch_size`` rows (static shapes for XLA) and records ``valid`` for correct metrics;
    ``pad_last=False`` drops the final partial batch (training-loop default).
    """

    def __init__(self, batch_size: int, pad_last: bool = True):
        assert batch_size > 0
        self.batch_size = batch_size
        self.pad_last = pad_last

    def __call__(self, prev: Iterator) -> Iterator:
        return self._gen(prev)

    def _gen(self, prev: Iterator):
        buf: list[Sample] = []
        for s in prev:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._stack(buf, self.batch_size)
                buf = []
        if buf and self.pad_last:
            valid = len(buf)
            while len(buf) < self.batch_size:
                buf.append(buf[valid - 1])
            yield self._stack(buf, self.batch_size, valid)

    @staticmethod
    def _stack(samples: Sequence[Sample], batch_size: int, valid: Optional[int] = None):
        # native GIL-free copy when available (runs in the prefetch producer
        # thread — overlap with the main thread is the point); numpy otherwise
        from bigdl_tpu.native import pack_batch
        n_f = len(samples[0].feature)
        feats = tuple(pack_batch([s.feature[i] for s in samples]) for i in range(n_f))
        n_l = len(samples[0].label)
        labels = tuple(pack_batch([s.label[i] for s in samples]) for i in range(n_l))
        input = feats[0] if n_f == 1 else feats
        target = (labels[0] if n_l == 1 else labels) if n_l else None
        return MiniBatch(input, target, valid if valid is not None else len(samples))
