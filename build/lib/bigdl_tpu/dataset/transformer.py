"""Composable data transformers.

Reference parity (SURVEY.md §2.2, expected ``<dl>/dataset/Transformer.scala`` — unverified):
a ``Transformer[A, B]`` maps ``Iterator[A] → Iterator[B]`` and composes with ``->``.

TPU-native: plain Python iterator stages on the host (input pipelines stay off-device, as
upstream's stayed off-JVM-heap); composition uses ``>>`` (closest Python analog of ``->``)
or ``.chain``. Heavy image work can later ride grain workers behind this same interface.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator


class Transformer:
    """Base: override ``__call__`` mapping an iterator to an iterator."""

    def __call__(self, prev: Iterator) -> Iterator:
        raise NotImplementedError

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """``a >> b`` — the reference's ``a -> b`` composition."""
        return ChainedTransformer(self, other)

    def chain(self, other: "Transformer") -> "ChainedTransformer":
        return self >> other

    def apply(self, data: Iterable) -> Iterator:
        return self(iter(data))


class ChainedTransformer(Transformer):
    def __init__(self, first: Transformer, second: Transformer):
        self.first, self.second = first, second

    def __call__(self, prev: Iterator) -> Iterator:
        return self.second(self.first(prev))


class MapTransformer(Transformer):
    """Lift an element-wise function into a Transformer."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def __call__(self, prev: Iterator) -> Iterator:
        return (self.fn(x) for x in prev)


class Identity(Transformer):
    def __call__(self, prev: Iterator) -> Iterator:
        return prev
