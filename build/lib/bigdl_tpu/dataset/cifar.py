"""CIFAR-10 loading.

Reference parity (SURVEY.md §2.2/§2.5; the reference's VGG/ResNet CIFAR trainings read the
binary CIFAR-10 set via ``<dl>/models/vgg/Utils.scala``-style loaders — unverified, mount
empty): loads the python-pickle or binary CIFAR-10 distributions if present under
``folder``; with no dataset on disk and no network (this environment), falls back to a
deterministic synthetic 10-class set with CIFAR-like statistics so end-to-end trainings
remain runnable and assertable.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from bigdl_tpu.dataset.sample import Sample

# per-channel mean/std of the real training set (BGR order matches reference pipelines)
TRAIN_MEAN = (0.4914, 0.4822, 0.4465)
TRAIN_STD = (0.2470, 0.2435, 0.2616)


def synthetic_cifar10(n: int, seed: int = 0):
    """Learnable synthetic stand-in: smooth 3-channel class prototypes + noise."""
    rng = np.random.default_rng(seed)
    protos = np.random.default_rng(4321).uniform(0, 1, size=(10, 3, 32, 32)).astype(
        np.float32)
    for _ in range(3):
        protos = (protos + np.roll(protos, 1, 2) + np.roll(protos, -1, 2)
                  + np.roll(protos, 1, 3) + np.roll(protos, -1, 3)) / 5.0
    labels = rng.integers(0, 10, size=n)
    imgs = protos[labels] + rng.normal(0, 0.15, size=(n, 3, 32, 32)).astype(np.float32)
    return np.clip(imgs, 0, 1).astype(np.float32), labels.astype(np.int32)


def _load_python_batches(folder: str, split: str):
    names = ([f"data_batch_{i}" for i in range(1, 6)] if split == "train"
             else ["test_batch"])
    root = folder
    sub = os.path.join(folder, "cifar-10-batches-py")
    if os.path.isdir(sub):
        root = sub
    xs, ys = [], []
    for name in names:
        path = os.path.join(root, name)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        xs.append(np.asarray(d[b"data"], np.uint8).reshape(-1, 3, 32, 32))
        ys.append(np.asarray(d[b"labels"], np.int64))
    return np.concatenate(xs) / np.float32(255.0), np.concatenate(ys).astype(np.int32)


def _load_binary_batches(folder: str, split: str):
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if split == "train"
             else ["test_batch.bin"])
    root = folder
    sub = os.path.join(folder, "cifar-10-batches-bin")
    if os.path.isdir(sub):
        root = sub
    xs, ys = [], []
    for name in names:
        path = os.path.join(root, name)
        if not os.path.exists(path):
            return None
        raw = np.fromfile(path, np.uint8).reshape(-1, 3073)  # 1 label + 3072 pixels
        ys.append(raw[:, 0].astype(np.int64))
        xs.append(raw[:, 1:].reshape(-1, 3, 32, 32))
    return np.concatenate(xs) / np.float32(255.0), np.concatenate(ys).astype(np.int32)


def load_cifar10(folder: str | None = None, split: str = "train",
                 synthetic_size: int | None = None):
    """Return ``(images float32 NCHW in [0,1], labels int32)``.

    With an explicit ``folder`` the python-pickle then binary layouts are tried and a
    missing/unreadable dataset is an error — never a silent synthetic substitution.
    Synthetic data is used only when no folder is given (this offline environment).
    """
    if folder:
        loaded = _load_python_batches(folder, split) or _load_binary_batches(folder, split)
        if loaded is None:
            raise FileNotFoundError(
                f"no CIFAR-10 batches (python or binary layout) under {folder!r}")
        return loaded
    n = synthetic_size or (2048 if split == "train" else 512)
    return synthetic_cifar10(n, seed=0 if split == "train" else 1)


def normalize(images: np.ndarray) -> np.ndarray:
    mean = np.asarray(TRAIN_MEAN, np.float32).reshape(1, 3, 1, 1)
    std = np.asarray(TRAIN_STD, np.float32).reshape(1, 3, 1, 1)
    return (images - mean) / std


def to_samples(images: np.ndarray, labels: np.ndarray) -> list[Sample]:
    return [Sample(images[i], labels[i]) for i in range(len(images))]


def train_val_sets(folder: str | None, batch_size: int, distributed: bool = False,
                   synthetic_size: int = 1024):
    """Normalized train/val MiniBatch datasets — the shared pipeline of the CIFAR
    training mains (resnet/vgg)."""
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.sample import SampleToMiniBatch

    imgs, labels = load_cifar10(folder, "train", synthetic_size=synthetic_size)
    timgs, tlabels = load_cifar10(folder, "test",
                                  synthetic_size=max(synthetic_size // 4, 256))
    train_set = (DataSet.array(to_samples(normalize(imgs), labels),
                               distributed=distributed)
                 >> SampleToMiniBatch(batch_size))
    test_set = (DataSet.array(to_samples(normalize(timgs), tlabels),
                              distributed=distributed)
                >> SampleToMiniBatch(batch_size))
    return train_set, test_set
