"""Background batch pipeline — overlap host data work with device compute.

Reference parity (SURVEY.md §7.4): the reference leans on Spark to materialise partitions
ahead of the training loop; its per-iteration cost hides batch assembly behind cluster
scheduling. On TPU the analog is a host-side producer thread: while the chip executes step
``k`` (dispatch is async), the producer decodes/stacks batch ``k+1`` **and** starts its
host→device transfer, so the step loop never waits on the feed in steady state. This is
SURVEY §7.4's named "most likely real-world bottleneck" for the ResNet-50 north star.

Design:
- ``PrefetchingFeed`` wraps a fresh dataset iterator per epoch. A daemon producer thread
  pulls ``MiniBatch``es, calls ``put_fn`` (the trainer's sharding-aware ``device_put``)
  and parks up to ``depth`` placed batches in a bounded queue. ``device_put`` only
  *enqueues* a DMA, so the producer is never blocked on the device — the queue depth
  bounds device-memory overcommit to ``depth`` batches.
- Exceptions in the producer surface in the consumer (training loop) with their original
  traceback as ``__cause__``.
- ``close()`` (also on ``__exit__`` / generator abandonment) stops the producer promptly —
  mid-epoch breaks (endWhen triggers) must not leak threads.
- ``depth=0`` degrades to fully synchronous iteration (debug / determinism studies).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator

_END = object()


class PrefetchingFeed:
    """Iterate ``(batch, placed)`` pairs with a background producer.

    ``make_iter``: zero-arg callable returning the epoch's batch iterator.
    ``put_fn``: MiniBatch → device-placed pytree (e.g. trainer's ``_put_batch``).
    ``depth``: producer queue bound (placed batches in flight); 0 = synchronous.
    """

    def __init__(self, make_iter: Callable[[], Iterator], put_fn: Callable,
                 depth: int = 2):
        if depth < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        self.make_iter = make_iter
        self.put_fn = put_fn
        self.depth = depth
        self._queue: queue.Queue | None = None
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------- producer
    @staticmethod
    def _put_responsive(q: queue.Queue, stop: threading.Event, item) -> None:
        """Blocking put that stays responsive to close(). Never gives up while
        the feed is live: the consumer is either draining (put succeeds) or
        closing (stop fires) — dropping the item would deadlock the consumer."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def _produce(self, it, q: queue.Queue, stop: threading.Event) -> None:
        try:
            for batch in it:
                if stop.is_set():
                    return
                placed = self.put_fn(batch)
                self._put_responsive(q, stop, (batch, placed))
                if stop.is_set():
                    return
            self._put_responsive(q, stop, _END)
        except BaseException as e:  # surfaced in the consumer
            self._put_responsive(q, stop, e)

    # ------------------------------------------------------------- consumer
    def __iter__(self):
        if self.depth == 0:
            for batch in self.make_iter():
                yield batch, self.put_fn(batch)
            return
        self._stop = threading.Event()
        self._queue = queue.Queue(maxsize=self.depth)
        self._thread = threading.Thread(
            target=self._produce, args=(self.make_iter(), self._queue, self._stop),
            name="bigdl-prefetch", daemon=True)
        self._thread.start()
        try:
            while True:
                item = self._queue.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    # re-raise the producer's exception with its original type
                    # (trainer retry/divisibility contracts depend on it); the
                    # producer traceback is already attached to the object
                    raise item
                yield item
        finally:
            self.close()

    def close(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._queue is not None:
            # unblock a producer stuck on put()
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
