"""MNIST loading (reference parity: ``pyspark/bigdl/dataset/mnist.py`` — unverified).

Reads the standard idx-format files if present; with no dataset on disk and no network
(this environment), falls back to a deterministic synthetic set: 10 fixed class prototypes
+ noise. The synthetic task is genuinely learnable, so end-to-end training tests can assert
loss ↓ / accuracy ↑ without the real data.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from bigdl_tpu.dataset.sample import Sample

TRAIN_MEAN, TRAIN_STD = 0.13066047740240005, 0.3081078

_IMAGES = {"train": "train-images-idx3-ubyte", "test": "t10k-images-idx3-ubyte"}
_LABELS = {"train": "train-labels-idx1-ubyte", "test": "t10k-labels-idx1-ubyte"}


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


def _find(folder: str, base: str):
    for cand in (base, base + ".gz"):
        p = os.path.join(folder, cand)
        if os.path.exists(p):
            return p
    return None


def synthetic_mnist(n: int, seed: int = 0):
    """Deterministic learnable stand-in: blurred class-prototype images + noise.

    The 10 prototypes are FIXED (independent of ``seed``) so train/test splits share the
    same class structure; ``seed`` only varies the labels/noise draw.
    """
    rng = np.random.default_rng(seed)
    protos = np.random.default_rng(1234).uniform(0, 1, size=(10, 28, 28)).astype(np.float32)
    # low-pass the prototypes so they have MNIST-like smooth structure
    for _ in range(3):
        protos = (protos + np.roll(protos, 1, 1) + np.roll(protos, -1, 1)
                  + np.roll(protos, 1, 2) + np.roll(protos, -1, 2)) / 5.0
    labels = rng.integers(0, 10, size=n)
    imgs = protos[labels] + rng.normal(0, 0.15, size=(n, 28, 28)).astype(np.float32)
    imgs = np.clip(imgs, 0, 1)
    return (imgs * 255).astype(np.uint8), labels.astype(np.int32)


def load_mnist(folder: str | None = None, split: str = "train",
               synthetic_size: int = 2048):
    """Return (images uint8 (N,28,28), labels int32 (N,)). Falls back to synthetic."""
    if folder:
        img_p = _find(folder, _IMAGES[split])
        lab_p = _find(folder, _LABELS[split])
        if img_p and lab_p:
            return _read_idx(img_p), _read_idx(lab_p).astype(np.int32)
    return synthetic_mnist(synthetic_size, seed=0 if split == "train" else 1)


def to_samples(images: np.ndarray, labels: np.ndarray,
               mean: float = TRAIN_MEAN, std: float = TRAIN_STD):
    """Normalize and wrap as Samples with NCHW (1, 28, 28) features."""
    imgs = (images.astype(np.float32) / 255.0 - mean) / std
    return [Sample(imgs[i][None, :, :], np.int32(labels[i])) for i in range(len(labels))]
