"""Text pipeline: dictionary, tokenizers, sentence→sample transformers, PTB loading.

Reference parity (SURVEY.md §2.2, expected ``<dl>/dataset/text/`` with ``Dictionary``,
``SentenceTokenizer``, ``TextToLabeledSentence``, ``LabeledSentenceToSample`` — unverified,
mount empty): the reference tokenizes text, builds a frequency-capped dictionary, converts
token streams into (input, shifted-target) LM samples. PTB reading for the LSTM LM
(baseline config #4) follows ``example/languagemodel``'s data prep.

With no dataset on disk (no network here), ``load_ptb`` falls back to a deterministic
synthetic Markov corpus with a learnable bigram structure, so LM perplexity is a real
training signal.
"""

from __future__ import annotations

import os
import re
from typing import Iterable, Iterator

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer


class Dictionary:
    """Token ↔ index mapping with frequency-capped vocabulary (reference ``Dictionary``).

    Index 0 is reserved for the unknown token (the reference reserves an <unk> slot).
    """

    UNK = "<unk>"

    def __init__(self, tokens: Iterable[str] | None = None,
                 vocab_size: int | None = None):
        self._word2idx: dict[str, int] = {self.UNK: 0}
        self._idx2word: list[str] = [self.UNK]
        if tokens is not None:
            self.build(tokens, vocab_size)

    def build(self, tokens: Iterable[str], vocab_size: int | None = None) -> "Dictionary":
        from collections import Counter
        counts = Counter(tokens)
        counts.pop(self.UNK, None)
        most = counts.most_common(None if vocab_size is None else vocab_size - 1)
        for w, _ in most:
            self._word2idx[w] = len(self._idx2word)
            self._idx2word.append(w)
        return self

    def get_index(self, word: str) -> int:
        return self._word2idx.get(word, 0)

    def get_word(self, index: int) -> str:
        return self._idx2word[index] if 0 <= index < len(self._idx2word) else self.UNK

    def vocab_size(self) -> int:
        return len(self._idx2word)

    def __len__(self) -> int:
        return len(self._idx2word)


class SentenceTokenizer(Transformer):
    """Split sentences into lowercase word tokens (reference ``SentenceTokenizer``)."""

    def __init__(self, pattern: str = r"[A-Za-z0-9<>']+"):
        self.pattern = re.compile(pattern)

    def __call__(self, prev: Iterator) -> Iterator:
        for sentence in prev:
            yield self.pattern.findall(sentence.lower())


class TextToLabeledSentence(Transformer):
    """tokens → (input tokens, next-token labels) for LM training."""

    def __init__(self, dictionary: Dictionary):
        self.dictionary = dictionary

    def __call__(self, prev: Iterator) -> Iterator:
        for tokens in prev:
            idx = np.asarray([self.dictionary.get_index(t) for t in tokens], np.int32)
            if len(idx) < 2:
                continue
            yield idx[:-1], idx[1:]


class LabeledSentenceToSample(Transformer):
    def __call__(self, prev: Iterator) -> Iterator:
        for inp, lbl in prev:
            yield Sample(inp, lbl)


def ptb_windows(ids: np.ndarray, bptt: int):
    """Slice a token-id stream into (input, target) windows of length ``bptt``."""
    n = (len(ids) - 1) // bptt
    xs = ids[:n * bptt].reshape(n, bptt)
    ys = ids[1:n * bptt + 1].reshape(n, bptt)
    return xs.astype(np.int32), ys.astype(np.int32)


def synthetic_ptb(n_tokens: int, vocab_size: int = 1000, seed: int = 0) -> np.ndarray:
    """Deterministic Markov-chain corpus: each token strongly predicts its successor."""
    rng = np.random.default_rng(seed)
    # sparse transition structure: each word has 4 likely successors
    succ = np.random.default_rng(99).integers(1, vocab_size, size=(vocab_size, 4))
    ids = np.empty(n_tokens, np.int32)
    ids[0] = 1
    noise = rng.random(n_tokens)
    choice = rng.integers(0, 4, size=n_tokens)
    rand_tok = rng.integers(1, vocab_size, size=n_tokens)
    for i in range(1, n_tokens):
        ids[i] = succ[ids[i - 1], choice[i]] if noise[i] > 0.1 else rand_tok[i]
    return ids


def load_ptb(folder: str | None = None, split: str = "train",
             dictionary: Dictionary | None = None, vocab_size: int = 10000,
             synthetic_size: int | None = None):
    """Return ``(token ids int32, Dictionary)`` for a PTB split.

    Reads ``ptb.<split>.txt`` under ``folder`` if present; otherwise a synthetic corpus.
    The train split builds the dictionary; pass it back in for valid/test.
    """
    path = folder and os.path.join(folder, f"ptb.{split}.txt")
    if path and os.path.exists(path):
        with open(path) as f:
            words = f.read().replace("\n", " <eos> ").split()
        if dictionary is None:
            dictionary = Dictionary(words, vocab_size)
        ids = np.asarray([dictionary.get_index(w) for w in words], np.int32)
        return ids, dictionary
    n = synthetic_size or (20000 if split == "train" else 2000)
    vocab = min(vocab_size, 1000)
    if dictionary is None:
        dictionary = Dictionary()
        dictionary._idx2word = [Dictionary.UNK] + [f"w{i}" for i in range(1, vocab)]
        dictionary._word2idx = {w: i for i, w in enumerate(dictionary._idx2word)}
    ids = synthetic_ptb(n, vocab, seed=0 if split == "train" else 1)
    return ids, dictionary
