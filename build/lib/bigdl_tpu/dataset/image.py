"""Classic image pipeline — the reference's pre-ImageFrame transformers.

Reference parity (SURVEY.md §2.2, expected ``<dl>/dataset/image/`` — unverified):
``BGRImgNormalizer``, ``BGRImgCropper``, ``HFlip``, ``ColorJitter``, ``Lighting``,
``BGRImgToSample`` worked on ``LabeledBGRImage`` records. Here the record type is
unified with the vision pipeline's :class:`ImageFeature` (images as HWC numpy in
BGR order), so the classic names are thin parameterizations of the same host-side
numpy ops — one implementation, both API generations.
"""

from __future__ import annotations

from typing import Sequence

from bigdl_tpu.transform.vision.image import (
    CenterCrop, ChannelNormalize, ColorJitter, HFlip, ImageFeature, ImageFrame,
    ImageFrameToSample, Lighting, MatToTensor, RandomCrop, RandomHFlip,
)

__all__ = [
    "BGRImgNormalizer", "BGRImgCropper", "BGRImgRdmCropper", "BGRImgToSample",
    "HFlip", "ColorJitter", "Lighting", "ImageFeature", "ImageFrame",
]


def BGRImgNormalizer(mean_b: float, mean_g: float, mean_r: float,
                     std_b: float = 1.0, std_g: float = 1.0, std_r: float = 1.0):
    """Per-channel (BGR order) normalize — reference ``BGRImgNormalizer(mean, std)``."""
    return ChannelNormalize((mean_b, mean_g, mean_r), (std_b, std_g, std_r))


def BGRImgCropper(crop_width: int, crop_height: int, is_random: bool = False):
    """Center or random crop — reference ``BGRImgCropper``."""
    if is_random:
        return RandomCrop(crop_height, crop_width)
    return CenterCrop(crop_height, crop_width)


BGRImgRdmCropper = lambda crop_width, crop_height: BGRImgCropper(  # noqa: E731
    crop_width, crop_height, is_random=True)


def BGRImgToSample():
    """HWC float BGR image + label → Sample (CHW) — reference ``BGRImgToBatch``'s
    per-record half; batching is ``SampleToMiniBatch``."""
    return MatToTensor() >> ImageFrameToSample()
