from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.random_generator import RandomGenerator

__all__ = ["Engine", "Table", "T", "RandomGenerator"]
