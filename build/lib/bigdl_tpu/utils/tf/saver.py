"""TF frozen-graph exporter — the ``saveTF`` analog.

Reference parity (SURVEY.md §2.5, expected ``<dl>/utils/tf/TensorflowSaver.scala``
— unverified, mount empty): serialize a native model as a frozen TensorFlow
GraphDef so TF-serving-style consumers can run it.

Scope: the inference layer set of the vision/classifier zoo — Linear,
SpatialConvolution (zero/explicit padding), Max/Avg pooling (floor mode),
ReLU/Tanh/Sigmoid/SoftMax/LogSoftMax, BatchNormalization (folded eval form),
Reshape/Flatten/View, Dropout (identity at inference), Sequential and Graph
containers. Spatial ops emit in NHWC with boundary transposes (TF CPU kernels
are NHWC-only); weights embed as Const nodes. Unsupported layers fail loudly.
"""

from __future__ import annotations

import numpy as np


class TFExportError(Exception):
    pass


def _require_tf():
    try:
        import tensorflow as tf
        return tf
    except ImportError as e:  # pragma: no cover
        raise TFExportError("tensorflow is required for save_tf") from e


def _emit(module, x, tf):
    """Return the TF tensor computing ``module`` on NCHW-convention input x."""
    from bigdl_tpu import nn

    t = type(module).__name__

    if isinstance(module, nn.Sequential):
        for child in module.modules:
            x = _emit(child, x, tf)
        return x
    if isinstance(module, nn.Graph):
        return _emit_graph(module, x, tf)

    params = {k: np.asarray(v) for k, v in module.get_params().items()}
    state = {k: np.asarray(v) for k, v in module.get_state().items()}

    if t == "Linear":
        if x.shape.rank and x.shape.rank > 2:
            x = tf.reshape(x, [x.shape[0] or -1,
                               int(np.prod(x.shape.as_list()[1:]))])
        y = tf.matmul(x, tf.constant(params["weight"].T))
        if "bias" in params:
            y = tf.nn.bias_add(y, tf.constant(params["bias"]))
        return y
    if t == "SpatialConvolution":
        if module.n_group != 1:
            raise TFExportError("grouped conv export not supported")
        w = tf.constant(params["weight"].transpose(2, 3, 1, 0))  # OIHW→HWIO
        y = tf.transpose(x, [0, 2, 3, 1])
        if module.pad_w == -1 or module.pad_h == -1:
            pad = "SAME"
        else:
            if module.pad_h or module.pad_w:
                y = tf.pad(y, [[0, 0], [module.pad_h, module.pad_h],
                               [module.pad_w, module.pad_w], [0, 0]])
            pad = "VALID"
        y = tf.nn.conv2d(y, w, strides=[1, module.stride_h, module.stride_w, 1],
                         padding=pad)
        if "bias" in params:
            y = tf.nn.bias_add(y, tf.constant(params["bias"]))
        return tf.transpose(y, [0, 3, 1, 2])
    if t in ("SpatialMaxPooling", "SpatialAveragePooling"):
        # non-default semantics must fail loudly, not export something else
        if getattr(module, "ceil_mode", False):
            raise TFExportError("ceil-mode pooling has no TF frozen-graph form")
        if getattr(module, "pad_mode", "torch") != "torch":
            raise TFExportError("pad_mode='same' pooling export not supported")
        if getattr(module, "global_pooling", False):
            raise TFExportError("global_pooling export not supported")
        if t == "SpatialAveragePooling" and not getattr(module, "divide", True):
            raise TFExportError("sum pooling (divide=False) export not supported")
        y = tf.transpose(x, [0, 2, 3, 1])
        if module.pad_h or module.pad_w:
            if t == "SpatialMaxPooling":
                y = tf.pad(y, [[0, 0], [module.pad_h, module.pad_h],
                               [module.pad_w, module.pad_w], [0, 0]],
                           constant_values=-np.inf)
            else:
                raise TFExportError(
                    "padded average pooling export not supported "
                    "(count semantics differ)")
        fn = tf.nn.max_pool2d if t == "SpatialMaxPooling" else tf.nn.avg_pool2d
        y = fn(y, ksize=[1, module.kh, module.kw, 1],
               strides=[1, module.dh, module.dw, 1], padding="VALID")
        return tf.transpose(y, [0, 3, 1, 2])
    if t in ("BatchNormalization", "SpatialBatchNormalization"):
        mean, var = state["running_mean"], state["running_var"]
        gamma = params.get("weight", np.ones_like(mean))
        beta = params.get("bias", np.zeros_like(mean))
        inv = gamma / np.sqrt(var + module.eps)
        shape = [1, -1] + [1] * (x.shape.rank - 2)
        return (x * tf.constant(inv.reshape(shape).astype(np.float32))
                + tf.constant((beta - mean * inv).reshape(shape)
                              .astype(np.float32)))
    if t == "ReLU":
        return tf.nn.relu(x)
    if t == "ReLU6":
        return tf.nn.relu6(x)
    if t == "Tanh":
        return tf.tanh(x)
    if t == "Sigmoid":
        return tf.sigmoid(x)
    if t == "SoftMax":
        return tf.nn.softmax(x)
    if t == "LogSoftMax":
        return tf.nn.log_softmax(x)
    if t in ("Dropout", "Identity", "Contiguous", "GaussianDropout",
             "GaussianNoise"):
        return x  # inference no-ops
    if t == "Flatten":
        return tf.reshape(x, [x.shape[0] or -1,
                              int(np.prod(x.shape.as_list()[1:]))])
    if t in ("Reshape", "View"):
        size = list(module.size)
        # mirror the native batch-mode rule (shape_ops.py): keep the batch dim
        # only when batch_mode is on (or auto-detected via element counts)
        n_rest = int(np.prod(x.shape.as_list()[1:]))
        bm = module.batch_mode
        if bm is None:  # native auto-detect (shape_ops.py): ndim>=2 and
            # non-batch element count matches the target
            bm = x.shape.rank >= 2 and n_rest == int(np.prod(size))
        if bm:
            return tf.reshape(x, [x.shape[0] or -1] + size)
        return tf.reshape(x, size)

    raise TFExportError(
        f"layer {t!r} has no TF export rule — add one in "
        f"bigdl_tpu/utils/tf/saver.py")


def _emit_graph(g, x, tf):
    values = {}
    if len(g.input_nodes) != 1:
        raise TFExportError("multi-input Graph export not supported")
    values[g.input_nodes[0].id] = x
    for node in g.sorted_nodes:
        if node.module is None:
            continue
        if node.prev_nodes:
            ins = [values[p.id] for p in node.prev_nodes]
        elif node.id in values:
            # module node used directly as the graph input (graph.py supports
            # `layer.inputs()` with no predecessors)
            ins = [values[node.id]]
        else:
            raise TFExportError(f"graph node {node!r} has no inputs")
        inp = ins[0] if len(ins) == 1 else ins
        tname = type(node.module).__name__
        if tname == "CAddTable":
            values[node.id] = tf.add_n(inp)
        elif tname == "JoinTable":
            m = node.module
            axis = m.dimension - 1
            if m.n_input_dims > 0 and ins[0].shape.rank == m.n_input_dims + 1:
                axis += 1  # native batched-input shift (containers.py)
            values[node.id] = tf.concat(inp, axis=axis)
        else:
            values[node.id] = _emit(node.module, inp, tf)
    if len(g.output_nodes) != 1:
        raise TFExportError("multi-output Graph export not supported")
    return values[g.output_nodes[0].id]


def save_tf(module, path: str, input_shape, input_name: str = "input",
            output_name: str = "output") -> None:
    """Export an inference model as a frozen GraphDef protobuf.

    ``input_shape``: full NCHW/feature shape including batch (use None for a
    dynamic batch dim).
    """
    tf = _require_tf()
    was_training = module.is_training()
    module.evaluate()
    try:
        graph = tf.Graph()
        with graph.as_default():
            x = tf.compat.v1.placeholder(tf.float32, input_shape,
                                         name=input_name)
            y = _emit(module, x, tf)
            tf.identity(y, name=output_name)
        gd = graph.as_graph_def()
        with open(path, "wb") as f:
            f.write(gd.SerializeToString())
    finally:
        if was_training:  # exporting mid-training must not flip the mode
            module.training()
