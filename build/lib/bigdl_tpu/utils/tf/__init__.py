from bigdl_tpu.utils.tf.loader import TFImportError, load_frozen_graph
from bigdl_tpu.utils.tf.saver import TFExportError, save_tf

__all__ = ["TFExportError", "TFImportError", "load_frozen_graph", "save_tf"]
