"""Module/object persistence.

Reference parity (SURVEY.md §2.5, expected ``<dl>/utils/File.scala`` and
``Module.save/load`` — unverified, mount empty): the reference offers Java-serialization
``Module.save(path)``/``Module.load`` plus the versioned protobuf ``saveModule`` format.

TPU-native: modules are pickle-safe (jit caches dropped, arrays → numpy on
``__getstate__``), so ``save``/``load`` are one format; a content header versions the file.
Writes are atomic (tmp + rename) so a killed process never leaves a torn checkpoint —
required by the retry-from-checkpoint semantics (SURVEY.md §5.3).
"""

from __future__ import annotations

import os
import pickle

MAGIC = b"BIGDL_TPU_V1\n"


def save(obj, path: str, overwrite: bool = True) -> None:
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"{path} exists (pass overwrite=True)")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(MAGIC)
        pickle.dump(obj, f)
    os.replace(tmp, path)


def load(path: str):
    with open(path, "rb") as f:
        head = f.read(len(MAGIC))
        if head != MAGIC:
            # plain pickle fallback (e.g. files written by other tools)
            f.seek(0)
        return pickle.load(f)
