"""Torch-style ``Table`` activity — heterogeneous int-keyed container.

Reference parity (SURVEY.md §2.5, expected ``<dl>/utils/Table.scala`` — unverified): the
reference uses ``Table`` (built with ``T(...)``) as the multi-input/multi-output ``Activity``
flowing between layers (e.g. ``ConcatTable`` outputs, ``JoinTable`` inputs, LSTM (h, c) state).

TPU-native design: a Table must be a JAX **pytree** so whole activities trace through ``jit``
and ``grad`` — so it registers with ``jax.tree_util``. Keys are 1-based ints (Torch/Lua
heritage) or strings; iteration order is sorted-int-first for determinism.
"""

from __future__ import annotations

from typing import Any, Iterator

import jax


class Table:
    """1-based int-keyed (plus string-keyed) container registered as a JAX pytree."""

    def __init__(self, *elements: Any, **named: Any) -> None:
        self._dict: dict[Any, Any] = {}
        for i, e in enumerate(elements):
            self._dict[i + 1] = e
        self._dict.update(named)

    # -------------------------------------------------------------- mapping
    def __getitem__(self, key: Any) -> Any:
        return self._dict[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        self._dict[key] = value

    def __contains__(self, key: Any) -> bool:
        return key in self._dict

    def __len__(self) -> int:
        return len(self._dict)

    def __iter__(self) -> Iterator[Any]:
        return iter(self.values())

    def keys(self):
        ints = sorted(k for k in self._dict if isinstance(k, int))
        others = sorted((k for k in self._dict if not isinstance(k, int)),
                        key=lambda k: (type(k).__name__, repr(k)))
        return ints + others

    def values(self):
        return [self._dict[k] for k in self.keys()]

    def items(self):
        return [(k, self._dict[k]) for k in self.keys()]

    def insert(self, value: Any) -> "Table":
        """Append at the next free 1-based int index (Torch ``table.insert``)."""
        i = 1
        while i in self._dict:
            i += 1
        self._dict[i] = value
        return self

    def to_list(self) -> list:
        return self.values()

    def to_tuple(self) -> tuple:
        return tuple(self.values())

    # --------------------------------------------------------------- dunder
    def __repr__(self) -> str:
        inner = ", ".join(f"{k}: {v!r}" for k, v in self.items())
        return f"T({{{inner}}})"

    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, Table):
            return NotImplemented
        if self.keys() != other.keys():
            return False
        import numpy as np
        for k in self.keys():
            a, b = self[k], other[k]
            if isinstance(a, Table) or isinstance(b, Table):
                if a != b:
                    return False
            elif not np.array_equal(np.asarray(a), np.asarray(b)):
                return False
        return True

    __hash__ = None  # mutable


def T(*elements: Any, **named: Any) -> Table:
    """Builder mirroring the reference's ``T()`` helper."""
    return Table(*elements, **named)


def _table_flatten(t: Table):
    keys = t.keys()
    return [t._dict[k] for k in keys], tuple(keys)


def _table_unflatten(keys, children) -> Table:
    t = Table()
    for k, c in zip(keys, children):
        t._dict[k] = c
    return t


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
