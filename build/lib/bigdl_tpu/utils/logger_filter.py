"""LoggerFilter — tame noisy third-party logs.

Reference parity (SURVEY.md §2.5, expected ``<dl>/utils/LoggerFilter.scala`` —
unverified, mount empty): the reference redirects chatty Spark/BigDL log4j
output to a file, keeping the console for training progress. The analog here
quiets the noisy Python loggers (jax compilation chatter, TF import noise)
and optionally redirects them to a file.
"""

from __future__ import annotations

import logging

_NOISY = ("jax", "jax._src", "tensorflow", "absl", "orbax")


class LoggerFilter:
    _handlers: list[tuple[logging.Logger, logging.Handler, bool]] = []
    _saved_levels: list[tuple[logging.Logger, int]] = []

    @classmethod
    def redirect(cls, path: str | None = None,
                 level: int = logging.ERROR,
                 loggers: tuple[str, ...] = _NOISY) -> None:
        """Raise ``loggers`` to ``level`` on the console; with ``path``, send
        their full output to a file instead of dropping it (reference
        ``LoggerFilter.redirect`` semantics)."""
        for name in loggers:
            lg = logging.getLogger(name)
            cls._saved_levels.append((lg, lg.level))
            lg.setLevel(level if path is None else logging.DEBUG)
            if path is not None:
                h = logging.FileHandler(path)
                h.setLevel(logging.DEBUG)
                lg.addHandler(h)
                cls._handlers.append((lg, h, lg.propagate))
                lg.propagate = False

    disable = redirect  # reference alias (``LoggerFilter.disable``)

    @classmethod
    def restore(cls) -> None:
        for lg, h, was_propagating in cls._handlers:
            lg.removeHandler(h)
            h.close()
            lg.propagate = was_propagating
        cls._handlers.clear()
        # reversed: nested redirects must unwind to the ORIGINAL levels
        for lg, lvl in reversed(cls._saved_levels):
            lg.setLevel(lvl)
        cls._saved_levels.clear()
