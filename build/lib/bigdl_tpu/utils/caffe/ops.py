"""Caffe-op adapter modules (cf. utils/tf/ops.py): the few Caffe layers with no
1:1 native equivalent. Module-level classes so imported nets serialize through
the portable format (registered under the ``caffe.`` namespace)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.abstractnn import TensorModule


class CaffeScale(TensorModule):
    """Per-channel affine ``y = x * gamma[c] (+ beta[c])`` — the Scale layer
    that conventionally follows BatchNorm in Caffe nets."""

    def __init__(self, gamma: np.ndarray, beta: np.ndarray | None = None):
        super().__init__()
        self._params = {"gamma": jnp.asarray(gamma)}
        if beta is not None:
            self._params["beta"] = jnp.asarray(beta)

    def apply(self, params, state, input, *, training=False, rng=None):
        shape = (1, -1) + (1,) * (input.ndim - 2)
        out = input * params["gamma"].reshape(shape)
        if "beta" in params:
            out = out + params["beta"].reshape(shape)
        return out, state


class CaffeSoftmax(TensorModule):
    """Softmax over an explicit axis (Caffe default: 1, the channel dim of an
    NCHW map — unlike jax.nn.softmax's last-dim default)."""

    def __init__(self, axis: int = 1):
        super().__init__()
        self.axis = int(axis)

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax
        return jax.nn.softmax(input, axis=self.axis), state


class CaffeGlobalPool(TensorModule):
    """Caffe global pooling: whole-plane reduction → (N, C, 1, 1)."""

    def __init__(self, kind: str):
        super().__init__()
        if kind not in ("max", "avg"):
            raise ValueError(kind)
        self.kind = kind

    def apply(self, params, state, input, *, training=False, rng=None):
        fn = jnp.max if self.kind == "max" else jnp.mean
        return fn(input, axis=(-2, -1), keepdims=True), state
