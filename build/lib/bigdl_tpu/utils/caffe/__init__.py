from bigdl_tpu.utils.caffe.loader import CaffeImportError, load_caffe
from bigdl_tpu.utils.caffe.saver import CaffeExportError, save_caffe

__all__ = ["CaffeExportError", "CaffeImportError", "load_caffe", "save_caffe"]
