"""Native runtime components (C++, ctypes-bound).

Reference parity (SURVEY.md §2.4): the reference ships native code for its
data-path hot spots (OpenCV JNI, MKL). The compute path here is XLA's problem;
what remains host-side and hot is batch assembly in the prefetch producer —
implemented in ``batchpack.cpp`` and called through ctypes so the GIL is
released during the copy.

The library is compiled on first use with the baked-in g++ (no pip/apt) and
cached next to the source; every entry point degrades to numpy when the
toolchain or compiled artifact is unavailable, gated by ``BIGDL_NATIVE``
(default on).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger("bigdl_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "batchpack.cpp")
_SO = os.path.join(_DIR, "_batchpack.so")

_lock = threading.Lock()
_lib = None
_lib_failed = False


def _enabled() -> bool:
    return os.environ.get("BIGDL_NATIVE", "1") == "1"


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or (os.path.getmtime(_SO)
                                           < os.path.getmtime(_SRC)):
                # pid-unique temp: concurrent first-use builds (multi-process
                # tests) must not install each other's half-written output
                tmp = f"{_SO}.{os.getpid()}.tmp"
                cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                       "-pthread", _SRC, "-o", tmp]
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
                os.replace(tmp, _SO)
                logger.info("built native batchpack: %s", _SO)
            lib = ctypes.CDLL(_SO)
            lib.pack_batch.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p]
            lib.gather_rows.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
            _lib = lib
        except Exception as e:
            logger.warning("native batchpack unavailable (%s); using numpy", e)
            _lib_failed = True
    return _lib


def native_available() -> bool:
    return _enabled() and _load() is not None


def pack_batch(arrays) -> np.ndarray:
    """Stack same-shaped arrays into a new contiguous batch (np.stack analog).
    The copy runs in C++ with the GIL released."""
    first = np.asarray(arrays[0])
    n = len(arrays)
    lib = _load() if _enabled() else None
    if lib is None or n < 2:
        return np.stack([np.asarray(a) for a in arrays])
    # NB: np.ascontiguousarray promotes 0-d to 1-d — only call it when needed
    if first.dtype.hasobject:
        # raw memcpy of PyObject* slots would skip refcounting → corruption
        # (hasobject also catches structured dtypes with embedded object fields)
        return np.stack([np.asarray(a) for a in arrays])
    mats = [m if m.flags.c_contiguous else np.ascontiguousarray(m)
            for m in (np.asarray(a) for a in arrays)]
    for m in mats:
        if m.shape != first.shape or m.dtype != first.dtype:
            return np.stack(mats)  # ragged → numpy's error/handling path
    out = np.empty((n,) + first.shape, first.dtype)
    ptrs = (ctypes.c_void_p * n)(*[m.ctypes.data for m in mats])
    lib.pack_batch(ptrs, n, first.nbytes, out.ctypes.data_as(ctypes.c_void_p))
    return out


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """dst[i] = src[idx[i]] over leading-axis rows (fancy-index analog)."""
    src = np.asarray(src)
    if src.dtype.hasobject:
        idx = np.asarray(idx)
        if len(idx) and (idx.min() < 0 or idx.max() >= len(src)):
            raise IndexError(f"gather_rows: index out of range [0, {len(src)})")
        return src[idx]
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, np.int64)
    # bounds policy is identical on both paths: negatives rejected (numpy's
    # wrap-around would make behavior depend on lib availability)
    if len(idx) and (idx.min() < 0 or idx.max() >= len(src)):
        raise IndexError(f"gather_rows: index out of range [0, {len(src)})")
    lib = _load() if _enabled() else None
    if lib is None:
        return src[idx]
    row_bytes = src[0].nbytes if len(src) else 0
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    if len(idx) == 0 or row_bytes == 0:
        return out
    lib.gather_rows(src.ctypes.data_as(ctypes.c_void_p),
                    idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    len(idx), row_bytes, out.ctypes.data_as(ctypes.c_void_p))
    return out
