// Native batch assembly — the data-loader hot path.
//
// Reference parity (SURVEY.md §2.4): the reference's input pipeline leans on
// native code (OpenCV JNI decode, JVM-side contiguous Sample storage). The
// TPU-native equivalent is this small library: stacking N sample buffers into
// one contiguous batch is pure memcpy work that Python does under the GIL
// (np.stack); calling it through ctypes releases the GIL, so the prefetch
// producer thread assembles batch k+1 while the main thread dispatches step k
// — the exact overlap the pipeline exists for.
//
// Built on demand with: g++ -O3 -march=native -shared -fPIC (see build.py).

#include <cstring>
#include <cstdint>
#include <thread>
#include <vector>

extern "C" {

// Copy n source buffers of nbytes each into dst (contiguous, stride nbytes).
void pack_batch(const void** srcs, int64_t n, int64_t nbytes, void* dst) {
    char* out = static_cast<char*>(dst);
    // memcpy is memory-bandwidth bound; split across a few threads only when
    // the batch is large enough to amortise thread startup
    const int64_t total = n * nbytes;
    const int64_t kParallelThreshold = 8 << 20;  // 8 MB
    int hw = (int)std::thread::hardware_concurrency();
    if (total < kParallelThreshold || n < 2 || hw < 2) {
        for (int64_t i = 0; i < n; ++i)
            std::memcpy(out + i * nbytes, srcs[i], (size_t)nbytes);
        return;
    }
    int n_threads = hw < 4 ? hw : 4;
    if (n_threads > n) n_threads = (int)n;
    std::vector<std::thread> workers;
    workers.reserve(n_threads);
    for (int t = 0; t < n_threads; ++t) {
        workers.emplace_back([=]() {
            for (int64_t i = t; i < n; i += n_threads)
                std::memcpy(out + i * nbytes, srcs[i], (size_t)nbytes);
        });
    }
    for (auto& w : workers) w.join();
}

// Gather rows: dst[i] = src[idx[i]] for row-sized nbytes — index-side shuffle
// without Python-level loops.
void gather_rows(const void* src, const int64_t* idx, int64_t n,
                 int64_t nbytes, void* dst) {
    const char* in = static_cast<const char*>(src);
    char* out = static_cast<char*>(dst);
    for (int64_t i = 0; i < n; ++i)
        std::memcpy(out + i * nbytes, in + idx[i] * nbytes, (size_t)nbytes);
}

}  // extern "C"
