"""``bigdl-tpu`` command-line entry point — the reference's spark-submit /
``scripts/bigdl.sh`` launcher analog (SURVEY.md §2.5 Build system, L8).

The reference launches training through ``spark-submit`` with env setup done by
``bigdl.sh`` and per-app scopt CLIs. TPU-native there is no cluster submitter:
one console script fans out to the model training mains (each keeping its
reference-style argparse options), the benchmark, and the multi-chip dry run.
Environment flags (the ``bigdl.*`` property tier) are plain ``BIGDL_*`` env
vars — see ``conf/bigdl-tpu.conf`` for the reference list.
"""

from __future__ import annotations

import argparse
import sys

# subcommand → (module with main(argv), description)
_TRAIN_MAINS = {
    "lenet": ("bigdl_tpu.models.lenet.train", "LeNet-5 / MNIST"),
    "resnet": ("bigdl_tpu.models.resnet.train", "ResNet CIFAR/ImageNet"),
    "inception": ("bigdl_tpu.models.inception.train", "Inception-v1/v2 ImageNet"),
    "vgg": ("bigdl_tpu.models.vgg.train", "VGG / CIFAR-10"),
    "rnn": ("bigdl_tpu.models.rnn.train", "PTB LSTM language model"),
    "autoencoder": ("bigdl_tpu.models.autoencoder.train", "MNIST autoencoder"),
    "ncf": ("bigdl_tpu.models.ncf.train", "Neural Collaborative Filtering"),
    "widedeep": ("bigdl_tpu.models.widedeep.train", "Wide & Deep recommender"),
    "textclassifier": ("bigdl_tpu.models.textclassifier.train",
                       "temporal-CNN text classification"),
    "treelstm": ("bigdl_tpu.models.treelstm.train", "binary TreeLSTM sentiment"),
    "transformerlm": ("bigdl_tpu.models.transformerlm.train",
                      "decoder-only Transformer LM (flash/ring attention)"),
}


def _run_module(modname: str, argv) -> int:
    import importlib

    mod = importlib.import_module(modname)
    out = mod.main(argv)
    return out if isinstance(out, int) else 0


def _launch_multihost(args) -> int:
    """Spawn args.nnodes processes, each a jax.distributed 'node' running the
    chosen train main with --distributed (reference parity: the spark-submit
    / bigdl.sh cluster launch, SURVEY.md §2.5 — one process per executor).
    On one machine this is the local[N] analog; across machines, run the same
    command per host with an explicit --port and a reachable coordinator."""
    import os
    import socket
    import subprocess
    import sys

    port = args.port
    if port == 0:
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
    mod, _ = _TRAIN_MAINS[args.model]
    rest = [a for a in args.rest if a != "--"]
    if "--distributed" not in rest:
        rest.append("--distributed")
    cpu = bool(args.devices_per_node)
    pre = ""
    if cpu:
        # the site hook preloads jax._src, so env alone is too late —
        # re-assert platform selection in-process (same dance as
        # tests/multihost_worker.py); cross-process CPU collectives ride gloo
        pre = ("import jax\n"
               "jax.config.update('jax_platforms', 'cpu')\n")
    backend_arg = "backend='cpu', " if cpu else ""
    code = (
        "import sys\n"
        f"{pre}"
        "from bigdl_tpu.utils.engine import Engine\n"
        f"Engine.init({backend_arg}"
        f"coordinator_address='localhost:{port}', "
        f"node_number={args.nnodes}, process_id=int(sys.argv[1]))\n"
        f"import importlib\n"
        f"importlib.import_module({mod!r}).main(sys.argv[2:])\n")
    procs = []
    for pid in range(args.nnodes):
        env = dict(os.environ)
        if cpu:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count="
                f"{args.devices_per_node}")
            env.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code, str(pid)] + rest, env=env))
    # wait for EVERY process (no short-circuit: an early crash must not
    # orphan the surviving workers), then report the first failure
    rcs = [p.wait() for p in procs]
    return next((rc for rc in rcs if rc), 0)


def _format_trace(ev: dict) -> str:
    """One tail-sampled request trace as an indented span tree."""
    lines = [f"trace {ev.get('trace_id')}  request {ev.get('request_id')}  "
             f"engine {ev.get('engine')}  e2e {ev.get('e2e_ms')}ms  "
             f"generated {ev.get('n_generated')}  "
             f"finish={ev.get('finish')}"]
    for span in ev.get("spans") or []:
        lines.append(f"  {span.get('name', '?'):<14} "
                     f"start {span.get('start_ms'):>10}ms  "
                     f"dur {span.get('dur_ms'):>10}ms")
    return "\n".join(lines)


def _run_diag(path: str, trace_id=None) -> int:
    """Re-render the unified run report from a saved JSONL event log
    (``BIGDL_OBS_LOG``): the LAST ``run_report`` record renders through the
    same formatter the trainer used, so the text matches the live run's
    byte-for-byte. Watchdog dumps and tail-sampled request traces in the log
    are summarized on stderr. With ``trace_id``, skip the report and print
    the matching ``request_trace`` span tree instead (matches the trace ID
    or the request ID — whichever the operator has in hand)."""
    from bigdl_tpu.obs import report as obs_report
    from bigdl_tpu.obs import trace

    try:
        events = trace.read_events(path)
    except OSError as e:
        print(f"diag: cannot read {path}: {e}", file=sys.stderr)
        return 1
    traces = [ev for ev in events if ev.get("kind") == "request_trace"]
    if trace_id is not None:
        hits = [ev for ev in traces
                if ev.get("trace_id") == trace_id
                or ev.get("request_id") == trace_id]
        if not hits:
            print(f"diag: no request_trace matching {trace_id!r} in {path} "
                  f"({len(traces)} traced request(s) in the log)",
                  file=sys.stderr)
            return 1
        for ev in hits:
            print(_format_trace(ev))
        return 0
    report = None
    dumps = 0
    kinds: dict = {}
    for ev in events:
        kind = ev.get("kind")
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind == "run_report":
            report = ev.get("report")
        elif kind == "watchdog_dump":
            dumps += 1
    if report is None:
        print(f"diag: no run_report event in {path} "
              f"(events seen: {kinds or 'none'})", file=sys.stderr)
        return 1
    print(obs_report.format_report(report))
    if dumps:
        print(f"diag: {dumps} watchdog dump(s) in the log — the run stalled; "
              f"thread stacks are in the watchdog_dump records",
              file=sys.stderr)
    if traces:
        slowest = sorted(traces, key=lambda ev: ev.get("e2e_ms") or 0.0,
                         reverse=True)[:3]
        print(f"diag: {len(traces)} tail-sampled request trace(s); slowest:",
              file=sys.stderr)
        for ev in slowest:
            print(f"diag:   trace {ev.get('trace_id')} "
                  f"e2e {ev.get('e2e_ms')}ms finish={ev.get('finish')} "
                  f"(--trace {ev.get('trace_id')} for the span tree)",
                  file=sys.stderr)
    return 0


def _render_top(metrics: dict, health=None) -> str:
    """Pure renderer for ``bigdl-tpu top``: one dashboard frame from a
    parsed ``/metrics`` scrape (``exporter.parse_metrics``) and an optional
    ``/healthz`` payload. Kept side-effect-free so tests can feed it
    canned scrapes."""
    import re

    def g(name, fmt="{:.4g}", default="-"):
        v = metrics.get(name)
        return fmt.format(v) if v is not None else default

    status = (health or {}).get("status", "?")
    wds = (health or {}).get("watchdogs") or []
    armed = sum(1 for w in wds if w.get("armed"))
    head = f"bigdl-tpu top — status {status}"
    if wds:
        head += f" · watchdogs {armed}/{len(wds)} armed"
    slo = (health or {}).get("slo") or {}
    if slo.get("active"):
        head += " · SLO BREACH " + ",".join(
            sorted(b.get("rule", "?") for b in slo["active"]))
    lines = [head]
    lines.append(
        "  train   mfu " + g("bigdl_train_mfu")
        + "   flops/s " + g("bigdl_train_model_flops_per_sec", "{:.3g}")
        + "   throughput " + g("bigdl_train_throughput", "{:.1f}")
        + "   step p50 " + g('bigdl_train_step_wall{quantile="0.5"}', "{:.4g}")
        + "s   stalls " + g("bigdl_train_feed_stall_total", "{:.0f}", "0"))
    lines.append(
        "  serve   flops/s " + g("bigdl_serve_model_flops_per_sec", "{:.3g}")
        + "   mfu " + g("bigdl_serve_mfu")
        + "   ttft p99 " + g('bigdl_serving_ttft_ms{quantile="0.99"}', "{:.1f}")
        + "ms   e2e p99 " + g('bigdl_serving_e2e_ms{quantile="0.99"}', "{:.1f}")
        + "ms")

    def gb(name):
        # bytes gauge → human-readable, "-" when the backend never said
        v = metrics.get(name)
        if v is None:
            return "-"
        for unit in ("B", "KB", "MB", "GB", "TB"):
            if abs(v) < 1024.0 or unit == "TB":
                return f"{v:.1f}{unit}" if unit != "B" else f"{v:.0f}B"
            v /= 1024.0

    headroom = metrics.get("bigdl_device_hbm_headroom")
    lines.append(
        "  device  hbm " + gb("bigdl_device_hbm_bytes_in_use")
        + "   peak " + gb("bigdl_device_hbm_peak_bytes")
        + "   headroom " + (f"{100 * headroom:.1f}%"
                            if headroom is not None else "-")
        + "   live " + g("bigdl_device_live_buffers", "{:.0f}")
        + " (" + gb("bigdl_device_live_buffer_bytes") + ")")
    # cluster view: every {host=}-labelled series from the spool merge
    hosts: dict = {}
    hpat = re.compile(r'^(\w+)\{host="([^"]*)"(?:,[^}]*)?\}$')
    for key, val in metrics.items():
        m = hpat.match(key)
        if m:
            hosts.setdefault(m.group(2), {})[m.group(1)] = val
    if hosts:
        lines.append("  hosts")
        for hid in sorted(hosts):
            h = hosts[hid]

            def hv(name, fmt="{:.4g}"):
                v = h.get(name)
                return fmt.format(v) if v is not None else "-"

            state = ("STALE" if h.get("bigdl_obs_host_up") == 0.0 else "up"
                     if h.get("bigdl_obs_host_up") is not None else "-")
            lines.append(
                f"    {hid:<12} {state:<6}"
                f" age {hv('bigdl_obs_host_age_seconds', '{:.0f}')}s"
                f"  thr {hv('bigdl_train_throughput', '{:.1f}')}"
                f"  mfu {hv('bigdl_train_mfu')}"
                f"  hbm {hv('bigdl_device_hbm_bytes_in_use', '{:.3g}')}"
                f"  headroom {hv('bigdl_device_hbm_headroom')}")
    tenants: dict = {}
    pat = re.compile(r'^bigdl_serving_tenant_(\w+)\{tenant="([^"]*)"\}$')
    for key, val in metrics.items():
        m = pat.match(key)
        if m:
            tenants.setdefault(m.group(2), {})[m.group(1)] = val
    if tenants:
        lines.append("  tenants")
        engs = (health or {}).get("engines") or {}
        for name in sorted(tenants):
            t = tenants[name]
            state = engs.get(name, {}).get("health", "?")
            if t.get("slo_degraded"):
                state += "/SLO"
            # paged engines report a live used/free page split; slot-grid
            # engines export 0/0 and render "-"
            pages = (f"{t.get('pages_used', 0):.0f}"
                     f"/{t.get('pages_free', 0):.0f}"
                     if t.get("pages_used", 0) or t.get("pages_free", 0)
                     else "-")
            lines.append(
                f"    {name:<12} {state:<10}"
                f" v{t.get('model_version', 0):.0f}"
                f" backlog {t.get('backlog', 0):.0f}"
                f" active {t.get('active_slots', 0):.0f}"
                f" done {t.get('completed', 0):.0f}"
                f" timeouts {t.get('timeouts', 0):.0f}"
                f" shed {t.get('shed', 0):.0f}"
                f" tps {t.get('decode_tps', 0):.1f}"
                f" pages {pages}")
    fleets: dict = {}
    fpat = re.compile(r'^bigdl_fleet_(\w+)\{fleet="([^"]*)"\}$')
    rpat = re.compile(
        r'^bigdl_fleet_replica_(\w+)\{fleet="([^"]*)",replica="([^"]*)"\}$')
    for key, val in metrics.items():
        m = fpat.match(key)
        if m:
            fleets.setdefault(m.group(2), {"replicas": {}})[m.group(1)] = val
    replicas: dict = {}
    for key, val in metrics.items():
        m = rpat.match(key)
        if m:
            replicas.setdefault(
                (m.group(2), m.group(3)), {})[m.group(1)] = val
    for (fname, rname), r in replicas.items():
        fleets.setdefault(fname, {"replicas": {}})["replicas"][rname] = r
    if fleets:
        hfleets = (health or {}).get("fleets") or {}
        for fname in sorted(fleets):
            f = fleets[fname]
            lines.append(
                f"  fleet {fname}"
                f" · healthy {f.get('healthy_replicas', 0):.0f}"
                f"/{len(f['replicas']) or f.get('healthy_replicas', 0):.0f}"
                f" · dispatched {f.get('dispatched', 0):.0f}"
                f" retries {f.get('retries', 0):.0f}"
                f" downs {f.get('replica_downs', 0):.0f}"
                f" rejected {f.get('rejected', 0):.0f}")
            hreps = (hfleets.get(fname) or {}).get("replicas") or {}
            for rname in sorted(f["replicas"]):
                r = f["replicas"][rname]
                state = hreps.get(rname, "?")
                lines.append(
                    f"    {rname:<12} {state:<10}"
                    f" queue {r.get('queue_depth', 0):.0f}"
                    f" active {r.get('active_slots', 0):.0f}"
                    f" done {r.get('completed', 0):.0f}"
                    f" shed {r.get('shed', 0):.0f}"
                    f" wait {r.get('est_wait_ms', 0):.0f}ms"
                    f" tps {r.get('decode_rate', 0):.1f}")
    return "\n".join(lines)


def _run_prof(args) -> int:
    """``bigdl-tpu prof``: the CLI form of ``/profilez`` — ask the running
    process for a ``jax.profiler.trace`` capture of ``--seconds`` and print
    the artifact path. The request blocks for the capture duration; a 409
    means another capture is already running."""
    import json
    import urllib.error
    import urllib.request

    url = (f"http://{args.host}:{args.port}/profilez"
           f"?seconds={args.seconds:g}")
    try:
        with urllib.request.urlopen(url,
                                    timeout=args.seconds + 30.0) as r:
            payload = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            detail = json.loads(e.read().decode()).get("error", "")
        except Exception:
            detail = ""
        print(f"prof: capture failed (HTTP {e.code}): {detail}",
              file=sys.stderr)
        return 1
    except Exception as e:  # noqa: BLE001 — connection errors end the run
        print(f"prof: cannot reach {url}: {e}", file=sys.stderr)
        return 1
    print(payload.get("artifact", ""))
    return 0


def _run_top(args) -> int:
    """Live terminal dashboard over the metrics endpoint: scrape
    ``/metrics`` + ``/healthz`` every ``--interval`` seconds and render one
    frame per poll (``--once`` for scripts)."""
    import json
    import time
    import urllib.error
    import urllib.request

    from bigdl_tpu.obs import exporter

    base = f"http://{args.host}:{args.port}"
    first = True
    while True:
        try:
            with urllib.request.urlopen(base + "/metrics", timeout=3.0) as r:
                metrics = exporter.parse_metrics(r.read().decode())
        except Exception as e:  # noqa: BLE001 — any scrape failure is fatal
            print(f"top: cannot scrape {base}/metrics: {e}", file=sys.stderr)
            return 1
        health = None
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=3.0) as r:
                health = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            # 503 (an engine died) still carries the JSON body
            try:
                health = json.loads(e.read().decode())
            except Exception:
                pass
        except Exception:
            pass
        if not first:
            print()
        first = False
        print(_render_top(metrics, health))
        if args.once:
            return 0
        time.sleep(args.interval)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    import os as _os
    if _os.environ.get("BIGDL_TRACE"):
        # tracing runs print their active obs configuration up front so the
        # artifact paths are known before any training output scrolls by
        from bigdl_tpu.obs import describe_config
        print(describe_config(), file=sys.stderr)
    # bench forwards option-style args; argparse REMAINDER cannot capture a
    # leading option (py3.12), so hand the tail to the benchmark CLI directly
    if argv[:1] == ["bench"]:
        from bigdl_tpu import benchmark
        return benchmark.main(argv[1:])
    if argv[:1] == ["converge"]:
        from bigdl_tpu import convergence
        return convergence.main(argv[1:])
    p = argparse.ArgumentParser(
        prog="bigdl-tpu",
        description="TPU-native BigDL: train models, benchmark, validate "
                    "multi-chip sharding")
    sub = p.add_subparsers(dest="command")

    train = sub.add_parser("train", help="run a model training main")
    train.add_argument("model", choices=sorted(_TRAIN_MAINS))
    train.add_argument("rest", nargs=argparse.REMAINDER,
                       help="arguments forwarded to the model's own CLI")

    sub.add_parser("bench", help="single-chip ResNet-50 benchmark "
                                  "(all bench.py options forwarded)")
    sub.add_parser("converge", help="accuracy-parity harness: train a "
                                    "BASELINE config on real data and judge "
                                    "the final metric against its target")
    dry = sub.add_parser("dryrun-multichip",
                         help="compile+run one sharded step on an n-device mesh")
    dry.add_argument("-n", "--n-devices", type=int, default=8)
    sub.add_parser("models", help="list available training mains")
    sub.add_parser("env", help="print the BIGDL_* environment flags in effect")

    diag = sub.add_parser(
        "diag", help="re-render the unified run report from a saved JSONL "
                     "event log (BIGDL_OBS_LOG / docs/observability.md)")
    diag.add_argument("jsonl", help="path to the JSONL event log")
    diag.add_argument("--trace", default=None, metavar="ID",
                      help="print the tail-sampled span tree for one request "
                           "(trace ID or request ID) instead of the report")

    top = sub.add_parser(
        "top", help="live dashboard over a running process's metrics "
                    "endpoint (/metrics + /healthz; BIGDL_METRICS_PORT)")
    top.add_argument("--host", default="127.0.0.1")
    top.add_argument("--port", type=int,
                     default=int(_os.environ.get("BIGDL_METRICS_PORT") or 0),
                     help="exporter port (default: $BIGDL_METRICS_PORT)")
    top.add_argument("--interval", type=float, default=2.0,
                     help="seconds between frames")
    top.add_argument("--once", action="store_true",
                     help="render one frame and exit (for scripts)")

    prof = sub.add_parser(
        "prof", help="trigger an on-demand jax.profiler capture on a "
                     "running process via its /profilez endpoint and print "
                     "the artifact path")
    prof.add_argument("--host", default="127.0.0.1")
    prof.add_argument("--port", type=int,
                      default=int(_os.environ.get("BIGDL_METRICS_PORT") or 0),
                      help="exporter port (default: $BIGDL_METRICS_PORT)")
    prof.add_argument("--seconds", type=float, default=2.0,
                      help="capture duration")

    launch = sub.add_parser(
        "launch", help="spawn an N-process jax.distributed training run on "
                       "this host (the spark-submit analog; each process = "
                       "one 'node')")
    launch.add_argument("-n", "--nnodes", type=int, default=2)
    launch.add_argument("--port", type=int, default=0,
                        help="coordinator port (0 = pick a free one)")
    launch.add_argument("--devices-per-node", type=int, default=None,
                        help="virtual CPU devices per process (default: "
                        "leave device discovery alone — real accelerators)")
    launch.add_argument("model", choices=sorted(_TRAIN_MAINS))
    launch.add_argument("rest", nargs=argparse.REMAINDER,
                        help="arguments forwarded to the model's own CLI")

    args = p.parse_args(argv)
    if args.command == "diag":
        return _run_diag(args.jsonl, trace_id=args.trace)
    if args.command == "top":
        if not args.port:
            print("top: no exporter port — pass --port or set "
                  "BIGDL_METRICS_PORT", file=sys.stderr)
            return 2
        return _run_top(args)
    if args.command == "prof":
        if not args.port:
            print("prof: no exporter port — pass --port or set "
                  "BIGDL_METRICS_PORT", file=sys.stderr)
            return 2
        return _run_prof(args)
    if args.command == "train":
        mod, _ = _TRAIN_MAINS[args.model]
        return _run_module(mod, args.rest)
    if args.command == "launch":
        return _launch_multihost(args)
    if args.command == "dryrun-multichip":
        import os
        # virtual CPU mesh: override any preset accelerator platform — this
        # subcommand validates shardings, not hardware
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.n_devices}"
            ).strip()
        from bigdl_tpu import dryrun
        dryrun.dryrun_multichip(args.n_devices)
        return 0
    if args.command == "models":
        for name, (_, desc) in sorted(_TRAIN_MAINS.items()):
            print(f"  {name:<16} {desc}")
        return 0
    if args.command == "env":
        import os
        for key in sorted(k for k in os.environ if k.startswith("BIGDL_")):
            print(f"{key}={os.environ[key]}")
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
