"""``bigdl-tpu`` command-line entry point — the reference's spark-submit /
``scripts/bigdl.sh`` launcher analog (SURVEY.md §2.5 Build system, L8).

The reference launches training through ``spark-submit`` with env setup done by
``bigdl.sh`` and per-app scopt CLIs. TPU-native there is no cluster submitter:
one console script fans out to the model training mains (each keeping its
reference-style argparse options), the benchmark, and the multi-chip dry run.
Environment flags (the ``bigdl.*`` property tier) are plain ``BIGDL_*`` env
vars — see ``conf/bigdl-tpu.conf`` for the reference list.
"""

from __future__ import annotations

import argparse
import sys

# subcommand → (module with main(argv), description)
_TRAIN_MAINS = {
    "lenet": ("bigdl_tpu.models.lenet.train", "LeNet-5 / MNIST"),
    "resnet": ("bigdl_tpu.models.resnet.train", "ResNet CIFAR/ImageNet"),
    "inception": ("bigdl_tpu.models.inception.train", "Inception-v1/v2 ImageNet"),
    "vgg": ("bigdl_tpu.models.vgg.train", "VGG / CIFAR-10"),
    "rnn": ("bigdl_tpu.models.rnn.train", "PTB LSTM language model"),
    "autoencoder": ("bigdl_tpu.models.autoencoder.train", "MNIST autoencoder"),
    "ncf": ("bigdl_tpu.models.ncf.train", "Neural Collaborative Filtering"),
    "widedeep": ("bigdl_tpu.models.widedeep.train", "Wide & Deep recommender"),
    "textclassifier": ("bigdl_tpu.models.textclassifier.train",
                       "temporal-CNN text classification"),
    "treelstm": ("bigdl_tpu.models.treelstm.train", "binary TreeLSTM sentiment"),
    "transformerlm": ("bigdl_tpu.models.transformerlm.train",
                      "decoder-only Transformer LM (flash/ring attention)"),
}


def _run_module(modname: str, argv) -> int:
    import importlib

    mod = importlib.import_module(modname)
    out = mod.main(argv)
    return out if isinstance(out, int) else 0


def _launch_multihost(args) -> int:
    """Spawn args.nnodes processes, each a jax.distributed 'node' running the
    chosen train main with --distributed (reference parity: the spark-submit
    / bigdl.sh cluster launch, SURVEY.md §2.5 — one process per executor).
    On one machine this is the local[N] analog; across machines, run the same
    command per host with an explicit --port and a reachable coordinator."""
    import os
    import socket
    import subprocess
    import sys

    port = args.port
    if port == 0:
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
    mod, _ = _TRAIN_MAINS[args.model]
    rest = [a for a in args.rest if a != "--"]
    if "--distributed" not in rest:
        rest.append("--distributed")
    cpu = bool(args.devices_per_node)
    pre = ""
    if cpu:
        # the site hook preloads jax._src, so env alone is too late —
        # re-assert platform selection in-process (same dance as
        # tests/multihost_worker.py); cross-process CPU collectives ride gloo
        pre = ("import jax\n"
               "jax.config.update('jax_platforms', 'cpu')\n")
    backend_arg = "backend='cpu', " if cpu else ""
    code = (
        "import sys\n"
        f"{pre}"
        "from bigdl_tpu.utils.engine import Engine\n"
        f"Engine.init({backend_arg}"
        f"coordinator_address='localhost:{port}', "
        f"node_number={args.nnodes}, process_id=int(sys.argv[1]))\n"
        f"import importlib\n"
        f"importlib.import_module({mod!r}).main(sys.argv[2:])\n")
    procs = []
    for pid in range(args.nnodes):
        env = dict(os.environ)
        if cpu:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count="
                f"{args.devices_per_node}")
            env.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code, str(pid)] + rest, env=env))
    # wait for EVERY process (no short-circuit: an early crash must not
    # orphan the surviving workers), then report the first failure
    rcs = [p.wait() for p in procs]
    return next((rc for rc in rcs if rc), 0)


def _run_diag(path: str) -> int:
    """Re-render the unified run report from a saved JSONL event log
    (``BIGDL_OBS_LOG``): the LAST ``run_report`` record renders through the
    same formatter the trainer used, so the text matches the live run's
    byte-for-byte. Watchdog dumps in the log are summarized on stderr."""
    from bigdl_tpu.obs import report as obs_report
    from bigdl_tpu.obs import trace

    try:
        events = trace.read_events(path)
    except OSError as e:
        print(f"diag: cannot read {path}: {e}", file=sys.stderr)
        return 1
    report = None
    dumps = 0
    kinds: dict = {}
    for ev in events:
        kind = ev.get("kind")
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind == "run_report":
            report = ev.get("report")
        elif kind == "watchdog_dump":
            dumps += 1
    if report is None:
        print(f"diag: no run_report event in {path} "
              f"(events seen: {kinds or 'none'})", file=sys.stderr)
        return 1
    print(obs_report.format_report(report))
    if dumps:
        print(f"diag: {dumps} watchdog dump(s) in the log — the run stalled; "
              f"thread stacks are in the watchdog_dump records",
              file=sys.stderr)
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    import os as _os
    if _os.environ.get("BIGDL_TRACE"):
        # tracing runs print their active obs configuration up front so the
        # artifact paths are known before any training output scrolls by
        from bigdl_tpu.obs import describe_config
        print(describe_config(), file=sys.stderr)
    # bench forwards option-style args; argparse REMAINDER cannot capture a
    # leading option (py3.12), so hand the tail to the benchmark CLI directly
    if argv[:1] == ["bench"]:
        from bigdl_tpu import benchmark
        return benchmark.main(argv[1:])
    if argv[:1] == ["converge"]:
        from bigdl_tpu import convergence
        return convergence.main(argv[1:])
    p = argparse.ArgumentParser(
        prog="bigdl-tpu",
        description="TPU-native BigDL: train models, benchmark, validate "
                    "multi-chip sharding")
    sub = p.add_subparsers(dest="command")

    train = sub.add_parser("train", help="run a model training main")
    train.add_argument("model", choices=sorted(_TRAIN_MAINS))
    train.add_argument("rest", nargs=argparse.REMAINDER,
                       help="arguments forwarded to the model's own CLI")

    sub.add_parser("bench", help="single-chip ResNet-50 benchmark "
                                  "(all bench.py options forwarded)")
    sub.add_parser("converge", help="accuracy-parity harness: train a "
                                    "BASELINE config on real data and judge "
                                    "the final metric against its target")
    dry = sub.add_parser("dryrun-multichip",
                         help="compile+run one sharded step on an n-device mesh")
    dry.add_argument("-n", "--n-devices", type=int, default=8)
    sub.add_parser("models", help="list available training mains")
    sub.add_parser("env", help="print the BIGDL_* environment flags in effect")

    diag = sub.add_parser(
        "diag", help="re-render the unified run report from a saved JSONL "
                     "event log (BIGDL_OBS_LOG / docs/observability.md)")
    diag.add_argument("jsonl", help="path to the JSONL event log")

    launch = sub.add_parser(
        "launch", help="spawn an N-process jax.distributed training run on "
                       "this host (the spark-submit analog; each process = "
                       "one 'node')")
    launch.add_argument("-n", "--nnodes", type=int, default=2)
    launch.add_argument("--port", type=int, default=0,
                        help="coordinator port (0 = pick a free one)")
    launch.add_argument("--devices-per-node", type=int, default=None,
                        help="virtual CPU devices per process (default: "
                        "leave device discovery alone — real accelerators)")
    launch.add_argument("model", choices=sorted(_TRAIN_MAINS))
    launch.add_argument("rest", nargs=argparse.REMAINDER,
                        help="arguments forwarded to the model's own CLI")

    args = p.parse_args(argv)
    if args.command == "diag":
        return _run_diag(args.jsonl)
    if args.command == "train":
        mod, _ = _TRAIN_MAINS[args.model]
        return _run_module(mod, args.rest)
    if args.command == "launch":
        return _launch_multihost(args)
    if args.command == "dryrun-multichip":
        import os
        # virtual CPU mesh: override any preset accelerator platform — this
        # subcommand validates shardings, not hardware
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.n_devices}"
            ).strip()
        from bigdl_tpu import dryrun
        dryrun.dryrun_multichip(args.n_devices)
        return 0
    if args.command == "models":
        for name, (_, desc) in sorted(_TRAIN_MAINS.items()):
            print(f"  {name:<16} {desc}")
        return 0
    if args.command == "env":
        import os
        for key in sorted(k for k in os.environ if k.startswith("BIGDL_")):
            print(f"{key}={os.environ[key]}")
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
