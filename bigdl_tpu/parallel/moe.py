"""Mixture-of-Experts with expert parallelism.

No reference counterpart (SURVEY.md §2.3 checklist: EP/MoE absent upstream —
design headroom for the TPU build, like ring attention). Switch-style top-1
routing in the GShard dense-dispatch formulation: every tensor keeps a static
shape (tokens × experts × capacity one-hot dispatch), so the whole layer is
three einsums + a softmax — exactly what the SPMD partitioner can shard.

Expert parallelism is NOT a separate communication path: the expert-indexed
parameters (E, D, H) are sharded over a mesh axis via the same TPRules
machinery as tensor parallelism (``expert_parallel_rules``), and XLA inserts
the token all-to-all implied by the dispatch einsums over ICI. One mechanism,
dp x ep meshes for free.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomNormal
from bigdl_tpu.parallel.tensor_parallel import TPRules
from jax.sharding import PartitionSpec as P


class MoE(TensorModule):
    """Switch/GShard MoE MLP block — top-1, top-2, or expert-choice routing.

    Input (N, D) or (N, T, D) → same shape. ``capacity_factor`` bounds tokens
    per expert; overflow tokens get dispatch weight zero, so their OUTPUT IS
    ZERO (the standard GShard drop) — wire the layer with an external residual
    connection (e.g. ``CAddTable`` around it) if dropped tokens should pass
    through. ``router="top2"`` dispatches each token to its two highest-prob
    experts with renormalized gates (GShard): under imbalance a token whose
    first choice overflowed usually still reaches its second, so capacity
    drops degrade instead of zeroing. ``router="expert_choice"`` inverts the
    selection (Zhou et al.): EXPERTS pick their top-capacity tokens —
    perfectly balanced by construction, no aux loss; a token may reach
    several experts or none.

    Routing health is OBSERVABLE, not silent (round-4 verdict weak #5) — the
    post-apply module state carries:

    - ``aux_loss``       — Switch load-balance loss (trained via the
      Optimizer's ``aux_loss_weight``);
    - ``router_z_loss``  — ``mean(logsumexp(logits)²)`` (ST-MoE); trained at
      ``z_loss_weight`` strength through the ``penalty`` state convention
      (layer-owned coefficient, like ActivityRegularization);
    - ``dropped_fraction`` — fraction of tokens with zero combine weight
      (every selection overflowed);
    - ``expert_load``      — (E,) first-choice routing fraction per expert;
    - ``expert_load_max``  — its max (hot-expert indicator).

    Scalars among these are auto-logged to TrainSummary/TB by the training
    loop (``Optimizer.OBSERVABLE_STATE_LEAVES``).
    """

    def __init__(self, input_size: int, hidden_size: int, n_experts: int,
                 capacity_factor: float = 1.25, router: str = "top1",
                 z_loss_weight: float = 0.0,
                 w_init: Optional[InitializationMethod] = None):
        super().__init__()
        if router not in ("top1", "top2", "expert_choice"):
            raise ValueError(f"router must be 'top1', 'top2' or "
                             f"'expert_choice', got {router!r}")
        if n_experts < 2:
            raise ValueError(f"n_experts must be >= 2, got {n_experts!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.router = router
        self.n_select = 2 if router == "top2" else 1
        self.z_loss_weight = float(z_loss_weight)
        self.w_init = w_init or RandomNormal(0.0, 0.02)
        self.reset()

    def reset(self) -> None:
        d, h, e = self.input_size, self.hidden_size, self.n_experts

        def mk(shape, fan_in, fan_out):
            return jnp.asarray(self.w_init.init(shape, fan_in=fan_in,
                                                fan_out=fan_out))

        self._params = {
            "w_gate": mk((d, e), d, e),
            "w1": mk((e, d, h), d, h),
            "b1": jnp.zeros((e, h), jnp.float32),
            "w2": mk((e, h, d), h, d),
            "b2": jnp.zeros((e, d), jnp.float32),
        }
        # state structure is static (jit/donation): every observability leaf
        # exists from reset; penalty only when the layer trains a z-loss
        self._state = {"aux_loss": jnp.zeros((), jnp.float32),
                       "router_z_loss": jnp.zeros((), jnp.float32),
                       "dropped_fraction": jnp.zeros((), jnp.float32),
                       "expert_load": jnp.zeros((e,), jnp.float32),
                       "expert_load_max": jnp.zeros((), jnp.float32)}
        if self.z_loss_weight > 0:
            self._state["penalty"] = jnp.zeros((), jnp.float32)
        self.zero_grad_parameters()

    def _capacity(self, n_tokens: int) -> int:
        import math
        # ceil (GShard/Switch convention): flooring could drop tokens even
        # under perfectly balanced routing with capacity_factor > 1; top-2
        # buffers hold up to n_select slots per token
        cap = math.ceil(self.n_select * n_tokens * self.capacity_factor
                        / self.n_experts)
        return max(cap, 1)

    def _router_health(self, new_state, logits, combine, frac) -> None:
        """ONE source of truth for the routing-health contract (round-4
        verdict weak #5): ST-MoE z-loss (+ penalty at z_loss_weight),
        dropped-token fraction (zero combine weight everywhere), per-expert
        load + its max."""
        z = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
        z_loss = jnp.mean(jnp.square(z))
        new_state["router_z_loss"] = z_loss
        if self.z_loss_weight > 0:
            new_state["penalty"] = self.z_loss_weight * z_loss
        got = jnp.sum(combine, axis=(1, 2)) > 0                     # (T,)
        new_state["dropped_fraction"] = 1.0 - jnp.mean(
            got.astype(jnp.float32))
        new_state["expert_load"] = frac
        new_state["expert_load_max"] = jnp.max(frac)

    @staticmethod
    def _expert_mlp(params, dispatch, combine, x):
        """Route tokens to expert buffers, run the per-expert MLP, combine —
        three einsums the SPMD partitioner shards on the expert axis."""
        xin = jnp.einsum("tec,td->ecd", dispatch, x)                # (E, C, D)
        hmid = jax.nn.relu(
            jnp.einsum("ecd,edh->ech", xin, params["w1"])
            + params["b1"][:, None, :])
        out_e = jnp.einsum("ech,ehd->ecd", hmid, params["w2"]) \
            + params["b2"][:, None, :]
        return jnp.einsum("tec,ecd->td", combine, out_e).astype(x.dtype)

    def _apply_expert_choice(self, params, state, x, logits, probs, cap,
                             flat_shape):
        """Expert-choice routing (Zhou et al.): EXPERTS pick their top-cap
        tokens by router score — perfectly balanced by construction (every
        expert processes exactly cap tokens, no aux loss needed); a token may
        reach several experts or none (dropped_fraction still reported)."""
        tokens, e = probs.shape
        cap = min(cap, tokens)   # top_k rejects k > T (cf > E overshoots)
        _, idx = jax.lax.top_k(probs.T, cap)                  # (E, C) tokens
        dispatch = jax.nn.one_hot(idx, tokens,
                                  dtype=jnp.float32).transpose(2, 0, 1)
        combine = dispatch * probs[:, :, None]                # (T, E, C)
        y = self._expert_mlp(params, dispatch, combine, x)

        new_state = dict(state)
        # balanced by construction — the Switch balance loss is identically
        # unnecessary; keep the leaf (static state structure) at zero
        new_state["aux_loss"] = jnp.zeros((), jnp.float32)
        # router PREFERENCE load (what top-1 would do) — the processed load
        # is uniform by construction, so this is the interesting signal
        frac = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, axis=-1), e,
                                       dtype=jnp.float32), axis=0)
        self._router_health(new_state, logits, combine, frac)

        if flat_shape:
            n, t, d = flat_shape
            y = y.reshape(n, t, d)
        return y, new_state

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        flat = x.ndim == 3
        if flat:
            n, t, d = x.shape
            x = x.reshape(n * t, d)
        tokens = x.shape[0]
        e = self.n_experts
        cap = self._capacity(tokens)

        logits = x @ params["w_gate"]                      # (T, E)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        if self.router == "expert_choice":
            return self._apply_expert_choice(params, state, x, logits, probs,
                                             cap, flat and (n, t, d))
        expert1 = jnp.argmax(probs, axis=-1)               # (T,)
        gate1 = jnp.take_along_axis(probs, expert1[:, None], axis=1)[:, 0]
        onehot1 = jax.nn.one_hot(expert1, e, dtype=jnp.float32)    # (T, E)

        # position of each first-choice token within its expert's queue
        pos1 = jnp.cumsum(onehot1, axis=0) * onehot1 - 1.0         # (T, E)
        keep1 = (pos1 < cap) & (onehot1 > 0)
        disp1 = jax.nn.one_hot(pos1.astype(jnp.int32), cap,
                               dtype=jnp.float32) * keep1[..., None]

        if self.n_select == 2:
            probs2 = probs * (1.0 - onehot1)               # mask first choice
            expert2 = jnp.argmax(probs2, axis=-1)
            gate2 = jnp.take_along_axis(probs, expert2[:, None], axis=1)[:, 0]
            onehot2 = jax.nn.one_hot(expert2, e, dtype=jnp.float32)
            # second-choice tokens queue BEHIND every first-choice token of
            # the same expert (GShard: first choices get buffer priority)
            pos2 = (jnp.cumsum(onehot2, axis=0)
                    + jnp.sum(onehot1, axis=0, keepdims=True)) * onehot2 - 1.0
            keep2 = (pos2 < cap) & (onehot2 > 0)
            disp2 = jax.nn.one_hot(pos2.astype(jnp.int32), cap,
                                   dtype=jnp.float32) * keep2[..., None]
            dispatch = disp1 + disp2                                # (T, E, C)
            # renormalized gates over the pair (GShard combine weights)
            denom = gate1 + gate2 + 1e-9
            combine = (disp1 * (gate1 / denom)[:, None, None]
                       + disp2 * (gate2 / denom)[:, None, None])
        else:
            dispatch = disp1                                        # (T, E, C)
            combine = disp1 * gate1[:, None, None]

        y = self._expert_mlp(params, dispatch, combine, x)

        # Switch aux loss: e * Σ_e (fraction of tokens) * (mean router prob);
        # top-2 uses the FIRST-choice fraction (GShard convention)
        frac = jnp.mean(onehot1, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(frac * mean_prob)
        new_state = dict(state)
        new_state["aux_loss"] = aux
        self._router_health(new_state, logits, combine, frac)

        if flat:
            y = y.reshape(n, t, d)
        return y, new_state

    def __repr__(self):
        return (f"MoE({self.input_size}, hidden={self.hidden_size}, "
                f"experts={self.n_experts}, router={self.router})")


def expert_parallel_rules(moe_path_prefix: str = "", axis: str = "model",
                          rules: Optional[TPRules] = None) -> TPRules:
    """TPRules sharding an MoE block's expert-indexed params over ``axis`` —
    expert parallelism through the same mechanism as tensor parallelism. The
    gate stays replicated; w1/b1/w2/b2 shard on the expert dim."""
    import re as _re
    r = rules if rules is not None else TPRules()
    # anchored + escaped (TPRules convention, cf. megatron_mlp_rules): prefix
    # "1" must not also match paths under "11"
    pre = f"(^|/){_re.escape(moe_path_prefix)}/" if moe_path_prefix else "(^|/)"
    r.add(f"{pre}w1$", P(axis, None, None))
    r.add(f"{pre}b1$", P(axis, None))
    r.add(f"{pre}w2$", P(axis, None, None))
    r.add(f"{pre}b2$", P(axis, None))
    return r


# portable serialization (utils/serializer.py): MoE checkpoints/archives like
# any other module
from bigdl_tpu.utils.serializer import register as _register_serializable  # noqa: E402

_register_serializable(MoE)
