"""Mixture-of-Experts with expert parallelism.

No reference counterpart (SURVEY.md §2.3 checklist: EP/MoE absent upstream —
design headroom for the TPU build, like ring attention). Switch-style top-1
routing in the GShard dense-dispatch formulation: every tensor keeps a static
shape (tokens × experts × capacity one-hot dispatch), so the whole layer is
three einsums + a softmax — exactly what the SPMD partitioner can shard.

Expert parallelism is NOT a separate communication path: the expert-indexed
parameters (E, D, H) are sharded over a mesh axis via the same TPRules
machinery as tensor parallelism (``expert_parallel_rules``), and XLA inserts
the token all-to-all implied by the dispatch einsums over ICI. One mechanism,
dp x ep meshes for free.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomNormal
from bigdl_tpu.parallel.tensor_parallel import TPRules
from jax.sharding import PartitionSpec as P


class MoE(TensorModule):
    """Switch-style top-1 MoE MLP block.

    Input (N, D) or (N, T, D) → same shape. ``capacity_factor`` bounds tokens
    per expert; overflow tokens get dispatch weight zero, so their OUTPUT IS
    ZERO (the standard GShard drop) — wire the layer with an external residual
    connection (e.g. ``CAddTable`` around it) if dropped tokens should pass
    through. The load-balancing auxiliary loss (Switch eq. 4) is exposed in
    the state as ``aux_loss`` for observability.
    """

    def __init__(self, input_size: int, hidden_size: int, n_experts: int,
                 capacity_factor: float = 1.25,
                 w_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.w_init = w_init or RandomNormal(0.0, 0.02)
        self.reset()

    def reset(self) -> None:
        d, h, e = self.input_size, self.hidden_size, self.n_experts

        def mk(shape, fan_in, fan_out):
            return jnp.asarray(self.w_init.init(shape, fan_in=fan_in,
                                                fan_out=fan_out))

        self._params = {
            "w_gate": mk((d, e), d, e),
            "w1": mk((e, d, h), d, h),
            "b1": jnp.zeros((e, h), jnp.float32),
            "w2": mk((e, h, d), h, d),
            "b2": jnp.zeros((e, d), jnp.float32),
        }
        self._state = {"aux_loss": jnp.zeros((), jnp.float32)}
        self.zero_grad_parameters()

    def _capacity(self, n_tokens: int) -> int:
        import math
        # ceil (GShard/Switch convention): flooring could drop tokens even
        # under perfectly balanced routing with capacity_factor > 1
        cap = math.ceil(n_tokens * self.capacity_factor / self.n_experts)
        return max(cap, 1)

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        flat = x.ndim == 3
        if flat:
            n, t, d = x.shape
            x = x.reshape(n * t, d)
        tokens = x.shape[0]
        e = self.n_experts
        cap = self._capacity(tokens)

        logits = x @ params["w_gate"]                      # (T, E)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        expert = jnp.argmax(probs, axis=-1)                # (T,)
        gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

        onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)      # (T, E)
        # position of each token within its expert's queue
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0            # (T, E)
        keep = (pos < cap) & (onehot > 0)
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                                dtype=jnp.float32) * keep[..., None]
        dispatch = pos_oh                                           # (T, E, C)

        # route tokens to expert buffers, run the per-expert MLP, combine
        xin = jnp.einsum("tec,td->ecd", dispatch, x)                # (E, C, D)
        hmid = jax.nn.relu(
            jnp.einsum("ecd,edh->ech", xin, params["w1"])
            + params["b1"][:, None, :])
        out_e = jnp.einsum("ech,ehd->ecd", hmid, params["w2"]) \
            + params["b2"][:, None, :]
        combine = dispatch * gate[:, None, None]
        y = jnp.einsum("tec,ecd->td", combine, out_e).astype(x.dtype)

        # Switch aux loss: e * Σ_e (fraction of tokens) * (mean router prob)
        frac = jnp.mean(onehot, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(frac * mean_prob)
        new_state = dict(state)
        new_state["aux_loss"] = aux

        if flat:
            y = y.reshape(n, t, d)
        return y, new_state

    def __repr__(self):
        return (f"MoE({self.input_size}, hidden={self.hidden_size}, "
                f"experts={self.n_experts})")


def expert_parallel_rules(moe_path_prefix: str = "", axis: str = "model",
                          rules: Optional[TPRules] = None) -> TPRules:
    """TPRules sharding an MoE block's expert-indexed params over ``axis`` —
    expert parallelism through the same mechanism as tensor parallelism. The
    gate stays replicated; w1/b1/w2/b2 shard on the expert dim."""
    import re as _re
    r = rules if rules is not None else TPRules()
    # anchored + escaped (TPRules convention, cf. megatron_mlp_rules): prefix
    # "1" must not also match paths under "11"
    pre = f"(^|/){_re.escape(moe_path_prefix)}/" if moe_path_prefix else "(^|/)"
    r.add(f"{pre}w1$", P(axis, None, None))
    r.add(f"{pre}b1$", P(axis, None))
    r.add(f"{pre}w2$", P(axis, None, None))
    r.add(f"{pre}b2$", P(axis, None))
    return r


# portable serialization (utils/serializer.py): MoE checkpoints/archives like
# any other module
from bigdl_tpu.utils.serializer import register as _register_serializable  # noqa: E402

_register_serializable(MoE)
