from bigdl_tpu.parallel.sharding import (
    batch_sharding, replicated, shard_leading_axis, zero1_state_sharding,
)
