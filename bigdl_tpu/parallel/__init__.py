from bigdl_tpu.parallel.ring_attention import full_attention, ring_attention
from bigdl_tpu.parallel.sharding import (
    batch_sharding, replicated, shard_leading_axis, zero1_state_sharding,
)
from bigdl_tpu.parallel.moe import MoE, expert_parallel_rules
from bigdl_tpu.parallel.pipeline import GPipe
from bigdl_tpu.parallel.tensor_parallel import (
    TPRules, column_parallel, megatron_mlp_rules, row_parallel,
)
from bigdl_tpu.parallel.embedding import (
    ShardedEmbedding, SparseEmbeddingUpdate, build_sparse_plan, dedup_ids,
    embedding_parallel_rules, find_sharded_embeddings, model_embedding_rules,
)
