"""Ring attention — sequence/context parallelism for long sequences.

No reference counterpart (SURVEY.md §5.7: sequence parallelism is absent upstream —
the longest-sequence workload is a PTB LSTM). This is a required capability of the
TPU build: long-context attention whose memory scales with the *local* sequence
shard, communication riding the ICI ring.

Design (blockwise ring attention, Liu et al. 2023 style, re-derived for shard_map):
the sequence axis of Q/K/V is sharded over the mesh's ``seq`` axis. Each device
keeps its Q shard resident and processes one K/V block per ring step, carrying the
numerically-stable streaming-softmax accumulators (running max ``m``, normalizer
``l``, un-normalized output ``o``); after each step K/V blocks rotate to the next
device with ``lax.ppermute``. After ``n`` steps every Q row has attended to the
full global sequence; communication is n-1 K/V block transfers per device —
point-to-point neighbor traffic, exactly what the torus ICI is built for. Causal
masking compares *global* row/column indices, so blocks that are entirely in the
future are suppressed by the mask (their contribution underflows to zero in the
streaming softmax).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _ring_attention_kernel(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-shard body under shard_map. q/k/v: (batch, heads, t_local, d)."""
    n = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    b, h, t_local, d = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    q_pos = rank * t_local + jnp.arange(t_local)  # global row indices

    def step(i, carry):
        o, l, m, k_blk, v_blk = carry
        # the block currently held originated on device (rank - i) mod n
        src = (rank - i) % n
        # fp32 islands: scores and the streaming-softmax accumulators (m, l, o)
        # stay fp32 across all n ring steps; the two matmuls run in the input
        # dtype with fp32 accumulation (MXU-native under bf16).
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + p.sum(axis=-1)
        o_new = o * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        # rotate K/V to the neighbor for the next step (skipped result unused on last)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return o_new, l_new, m_new, k_next, v_next

    # derive accumulators from q so they carry shard_map's varying-axis tag
    o0 = jnp.zeros_like(q, dtype=jnp.float32)
    l0 = (q[..., 0] * 0.0).astype(jnp.float32)
    m0 = l0 + _NEG_INF
    o, l, m, _, _ = lax.fori_loop(0, n, step, (o0, l0, m0, k, v))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh: Optional[Mesh] = None, seq_axis: str = "seq",
                   causal: bool = False, scale: Optional[float] = None):
    """Global attention over sequence-sharded Q/K/V.

    Args: ``q/k/v`` of shape (batch, heads, seq, head_dim) — global arrays (or
    already sharded on ``seq``); ``mesh`` defaults to the Engine mesh. Returns the
    attention output with the same shape/sharding. Falls back to single-device
    attention when the mesh has no ``seq_axis`` or it has size 1.
    """
    if mesh is None:
        from bigdl_tpu.utils.engine import Engine
        mesh = Engine.mesh()
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    axes = dict(mesh.shape)
    if seq_axis not in axes or axes[seq_axis] == 1:
        return full_attention(q, k, v, causal=causal, scale=scale)
    if q.shape[2] % axes[seq_axis] != 0:
        raise ValueError(
            f"sequence length {q.shape[2]} not divisible by seq-parallel size "
            f"{axes[seq_axis]}")
    # on a combined dp × sp mesh the batch dim stays data-sharded — otherwise
    # every data group would all-gather the batch and compute attention redundantly
    batch_axis = data_axis if (data_axis := _present_axis(axes, q.shape[0])) else None
    spec = P(batch_axis, None, seq_axis, None)
    fn = jax.shard_map(
        functools.partial(_ring_attention_kernel, axis_name=seq_axis,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def _present_axis(axes: dict, batch: int, name: str = "data"):
    """The data axis name iff it exists, is >1, and divides the batch."""
    size = axes.get(name, 1)
    return name if size > 1 and batch % size == 0 else None


def full_attention(q, k, v, causal: bool = False, scale: Optional[float] = None,
                   kv_mask=None):
    """Single-device reference attention (also the oracle in tests).

    Mixed-precision contract: the two matmuls run in the input dtype (bf16 →
    MXU double rate) with fp32 accumulation (``preferred_element_type`` — the
    MXU accumulates fp32 natively, this just keeps XLA from truncating), and the
    softmax itself is an fp32 island. Output returns in the input dtype.

    ``kv_mask``: optional boolean mask broadcastable to the (b, h, q, k)
    score shape; False positions are excluded from the softmax (the KV-cache
    decode path masks the unwritten cache tail with this).
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
