"""Pipeline parallelism — GPipe-style stage sharding over the ``pipe`` axis.

No reference counterpart (SURVEY.md §2.3 checklist: PP absent upstream —
design headroom for the TPU build, like ring attention and MoE). Two stage
models:

- **Homogeneous** (``GPipe(stage, n_stages=S)``): S clones of one module.
  Per-stage params stack on a leading stage dim sharded over ``pipe`` — the
  cheapest schedule, kept as the fast path.
- **Heterogeneous** (``GPipe(stages=[embed, block, ..., head])``): arbitrary
  per-stage modules whose param pytrees and boundary activation shapes may all
  differ — the shape a real model needs (a TransformerLM's embedding, blocks
  and tied head are not clones). SPMD still requires every device to run ONE
  program, so per-rank stage dispatch is a ``lax.switch`` on the device's
  ``pipe`` rank (XLA compiles all branches, each device executes its own), and
  the two heterogeneous data planes are engineered flat:
  * activations cross stage boundaries as zero-padded flat f32 buffers sized
    to the largest boundary (each branch unflattens its own static shape);
  * per-stage params are flattened, zero-padded to the largest stage and
    stacked (S, P) with the stage dim sharded over ``pipe`` — each rank holds
    ONLY its own stage's weights (true pipeline memory scaling), and each
    switch branch reconstructs its stage's pytree from its row with static
    offsets/dtypes.

At tick ``t`` a device applies its stage, then ``ppermute``\\ s the flat buffer
right; after ``M + S - 1`` ticks every microbatch crossed all stages. The
backward pipeline needs no hand-written schedule: jax reverse-mode
differentiates the ``scan`` + ``switch`` + ``ppermute`` chain, yielding the
reversed-communication schedule automatically — the train step stays ONE
jitted program. (A manual 1F1B interleave would need a hand-scheduled VJP; the
GPipe-style all-forward-then-all-backward memory profile is what autodiff
gives, softened by ``nn.Remat`` on stages when activations dominate.)

Stages must be stateless — BatchNorm running stats would silently diverge per
rank; use ``BatchNormalization(sync=True)`` inside ``shard_map`` data-parallel
code instead, or LayerNorm in pipelined transformer stacks — and must not
need RNG (build blocks with dropout=0).

Off-mesh (no ``pipe`` axis) the same microbatch loop runs sequentially without
communication, so tests and single-chip runs get identical math.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from bigdl_tpu.nn.abstractnn import AbstractModule, Container


def _check_stage(stage: AbstractModule) -> AbstractModule:
    if jax.tree_util.tree_leaves(stage.get_state()):
        raise ValueError(
            "GPipe stages must be stateless: per-rank running statistics "
            "(e.g. BatchNorm) would silently diverge across pipeline ranks. "
            "Use LayerNorm in pipelined stacks, or BatchNormalization("
            "sync=True) under data-parallel shard_map instead.")
    if stage.needs_rng():
        raise ValueError(
            "GPipe stages must not need RNG (build blocks with dropout=0); "
            "the pipeline schedule replays stages across microbatch ticks")
    return stage


class GPipe(Container):
    """Pipeline container. ``GPipe(stage, n_stages=S)`` composes S fresh
    clones; ``GPipe(stages=[...])`` pipelines arbitrary heterogeneous modules.
    Executed as a pipeline over the ``pipe`` mesh axis when present."""

    def __init__(self, stage: Optional[AbstractModule] = None,
                 n_stages: int = 1, n_microbatches: int = 2,
                 axis_name: str = "pipe",
                 stages: Optional[Sequence[AbstractModule]] = None,
                 remat: bool = False):
        if (stage is None) == (stages is None):
            raise ValueError("pass exactly one of `stage` or `stages`")
        # remat: recompute each stage's internals in backward instead of
        # stashing them across the whole GPipe schedule — the standard relief
        # for the all-forward-then-all-backward activation profile autodiff
        # gives this scan (a hand-scheduled 1F1B would change the SCHEDULE;
        # remat changes what is LIVE, which is the memory that matters here)
        self.remat = bool(remat)
        if stages is not None:
            mods = [_check_stage(m) for m in stages]
            n_stages = len(mods)
            self.homogeneous = False
        else:
            _check_stage(stage)
            mods = [stage]
            for _ in range(n_stages - 1):
                c = stage.clone()
                c.reset()  # independent parameters per stage
                mods.append(c)
            self.homogeneous = True
        super().__init__(*mods)
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.axis_name = axis_name

    # ------------------------------------------------------------------ run
    def _stage_apply(self, i: int, params, x, training):
        # stages are stateless, but containers still want the structured
        # (empty) state tree
        def run(p, xx):
            out, _ = self.modules[i].apply(p, self.modules[i].get_state(), xx,
                                           training=training, rng=None)
            return out
        if self.remat:
            run = jax.checkpoint(run)
        return run(params, x)

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.utils.engine import Engine

        s, m = self.n_stages, self.n_microbatches
        b = input.shape[0]
        if b % m != 0:
            raise ValueError(f"batch {b} not divisible by n_microbatches {m}")

        mesh = Engine.mesh() if Engine.is_initialized() else None
        axes = dict(mesh.shape) if mesh is not None else {}
        if axes.get(self.axis_name, 1) == s and s > 1:
            # under dp x pp the batch stays sharded over `data` inside the
            # shard_map (replicating it would all-gather and nullify dp)
            data_axis = Engine.DATA_AXIS if Engine.DATA_AXIS in axes else None
            d = axes.get(data_axis, 1) if data_axis else 1
            if d > 1 and (b % d != 0 or (b // d) % m != 0):
                raise ValueError(
                    f"batch {b} must divide by data size {d} and the local "
                    f"batch by n_microbatches {m}")
            run = (self._apply_sharded if self.homogeneous
                   else self._apply_sharded_hetero)
            return run(params, input, training, mesh,
                       data_axis if d > 1 else None), state

        # sequential fallback: same stage composition, no communication
        y = input
        for i in range(s):
            y = self._stage_apply(i, params[str(i)], y, training)
        return y, state

    # ------------------------------------------- homogeneous (stacked) path
    def _apply_sharded(self, params, x, training, mesh, data_axis=None):
        s, m = self.n_stages, self.n_microbatches
        axis = self.axis_name
        x_spec = P(data_axis) if data_axis else P()
        # stack per-stage params on a leading stage dim (sharded over `pipe`)
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *[params[str(i)] for i in range(s)])

        def body(p_stk, xs):
            rank = lax.axis_index(axis)
            p = jax.tree_util.tree_map(lambda l: l[0], p_stk)  # my stage
            micro = xs.reshape((m, xs.shape[0] // m) + xs.shape[1:])
            # carries become device-varying after the first ppermute; mark the
            # (invariant) zeros accordingly or scan rejects the carry typing
            zero = lax.pcast(jnp.zeros_like(micro[0]), (axis,), to="varying")
            out_acc = lax.pcast(jnp.zeros_like(micro), (axis,), to="varying")
            perm = [(i, i + 1) for i in range(s - 1)]

            def tick(carry, t):
                recv, out_acc = carry
                feed = micro[jnp.minimum(t, m - 1)]
                inp = jnp.where(jnp.logical_and(rank == 0, t < m), feed, recv)
                out = self._stage_apply(0, p, inp, training)
                # last stage banks microbatch t-(s-1) when it emerges
                slot = jnp.clip(t - (s - 1), 0, m - 1)
                bank = jnp.logical_and(rank == s - 1, t >= s - 1)
                prev = lax.dynamic_index_in_dim(out_acc, slot, 0,
                                                keepdims=False)
                out_acc = lax.dynamic_update_index_in_dim(
                    out_acc, jnp.where(bank, out, prev), slot, axis=0)
                recv = lax.ppermute(out, axis, perm)
                return (recv, out_acc), None

            (recv, out_acc), _ = lax.scan(tick, (zero, out_acc),
                                          jnp.arange(m + s - 1))
            # results live on the last stage only → broadcast over the axis
            out_acc = jnp.where(lax.axis_index(axis) == s - 1, out_acc, 0.0)
            out_acc = lax.psum(out_acc, axis)
            return out_acc.reshape(xs.shape)

        spec_p = jax.tree_util.tree_map(lambda _: P(axis), stacked)
        fn = jax.shard_map(body, mesh=mesh,
                           in_specs=(spec_p, x_spec), out_specs=x_spec)
        return fn(stacked, x)

    # ------------------------------------------ heterogeneous (switch) path
    def _apply_sharded_hetero(self, params, x, training, mesh, data_axis=None):
        s, m = self.n_stages, self.n_microbatches
        axis = self.axis_name
        x_spec = P(data_axis) if data_axis else P()
        d = dict(mesh.shape).get(data_axis, 1) if data_axis else 1
        bm = (x.shape[0] // d) // m  # per-rank microbatch size

        # --- static boundary shapes: chain eval_shape through the stages
        stage_params = [params[str(i)] for i in range(s)]
        in_shapes = []   # stage i input aval
        out_shapes = []  # stage i output aval
        cur = jax.ShapeDtypeStruct((bm,) + x.shape[1:], x.dtype)
        for i in range(s):
            in_shapes.append(cur)
            cur = jax.eval_shape(
                lambda p, xx, i=i: self._stage_apply(i, p, xx, training),
                stage_params[i], cur)
            if not hasattr(cur, "shape"):
                raise ValueError("GPipe stages must return a single array")
            out_shapes.append(cur)
        # the flat wire must also carry the stage-0 feed (rank 0 reshapes recv
        # into the feed shape on late ticks), so include the input extent too
        buf_len = max([int(np.prod(o.shape)) for o in out_shapes]
                      + [int(np.prod(in_shapes[0].shape))])

        # --- flatten+pad+stack per-stage params: (S, P) sharded over `pipe`,
        # so each rank materialises only its own stage's weights
        flat, offsets = [], []
        for sp in stage_params:
            leaves = jax.tree_util.tree_leaves(sp)
            offs, off = [], 0
            for l in leaves:
                offs.append((off, l.shape, l.dtype))
                off += int(np.prod(l.shape))
            offsets.append(offs)
            vec = (jnp.concatenate([jnp.ravel(l).astype(jnp.float32)
                                    for l in leaves])
                   if leaves else jnp.zeros((0,), jnp.float32))
            flat.append(vec)
        p_len = max(v.shape[0] for v in flat)
        p_stk = jnp.stack([jnp.pad(v, (0, p_len - v.shape[0])) for v in flat])
        treedefs = [jax.tree_util.tree_structure(sp) for sp in stage_params]

        def unflatten(i, row):
            leaves = [lax.dynamic_slice(row, (off,), (int(np.prod(shape)),))
                      .reshape(shape).astype(dtype)
                      for off, shape, dtype in offsets[i]]
            return jax.tree_util.tree_unflatten(treedefs[i], leaves)

        def body(p_stk, xs):
            rank = lax.axis_index(axis)
            row = p_stk[0]  # my stage's flattened params
            micro = xs.reshape((m, bm) + xs.shape[1:])
            # switch branches must agree on varying-axes typing: the feed is
            # pipe-invariant while recv is pipe-varying — promote everything
            # to the same set up front
            micro = lax.pcast(micro, (axis,), to="varying")
            vaxes = (axis,) if data_axis is None else (axis, data_axis)

            def branch(i):
                def run(row, recv, t):
                    if i == 0:
                        feed = micro[jnp.minimum(t, m - 1)]
                        inp = jnp.where(
                            t < m, feed,
                            recv[:feed.size].reshape(feed.shape)
                            .astype(feed.dtype))
                    else:
                        av = in_shapes[i]
                        inp = recv[:int(np.prod(av.shape))] \
                            .reshape(av.shape).astype(av.dtype)
                    out = self._stage_apply(i, unflatten(i, row), inp,
                                            training)
                    vec = jnp.ravel(out).astype(jnp.float32)
                    return jnp.pad(vec, (0, buf_len - vec.shape[0]))
                return run

            branches = [branch(i) for i in range(s)]
            zero = lax.pcast(jnp.zeros((buf_len,), jnp.float32),
                             vaxes, to="varying")
            out_acc = lax.pcast(jnp.zeros((m, buf_len), jnp.float32),
                                vaxes, to="varying")
            perm = [(i, i + 1) for i in range(s - 1)]

            def tick(carry, t):
                recv, out_acc = carry
                out = lax.switch(jnp.clip(rank, 0, s - 1), branches,
                                 row, recv, t)
                slot = jnp.clip(t - (s - 1), 0, m - 1)
                bank = jnp.logical_and(rank == s - 1, t >= s - 1)
                prev = lax.dynamic_index_in_dim(out_acc, slot, 0,
                                                keepdims=False)
                out_acc = lax.dynamic_update_index_in_dim(
                    out_acc, jnp.where(bank, out, prev), slot, axis=0)
                recv = lax.ppermute(out, axis, perm)
                return (recv, out_acc), None

            (_, out_acc), _ = lax.scan(tick, (zero, out_acc),
                                       jnp.arange(m + s - 1))
            # banked results live on the last rank only → broadcast, then
            # unflatten to the last stage's output shape
            out_acc = jnp.where(rank == s - 1, out_acc, 0.0)
            out_acc = lax.psum(out_acc, axis)
            fs = out_shapes[-1]
            n_out = int(np.prod(fs.shape))
            out = out_acc[:, :n_out].reshape((m,) + fs.shape).astype(fs.dtype)
            return out.reshape((m * bm,) + fs.shape[1:])

        fn = jax.shard_map(body, mesh=mesh,
                           in_specs=(P(axis), x_spec), out_specs=x_spec)
        return fn(p_stk, x)

    def __repr__(self):
        kind = "homogeneous" if self.homogeneous else "heterogeneous"
        return (f"GPipe(stages={self.n_stages} [{kind}], "
                f"microbatches={self.n_microbatches})")


from bigdl_tpu.utils.serializer import register as _register_serializable  # noqa: E402

_register_serializable(GPipe)
