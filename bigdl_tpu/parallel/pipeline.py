"""Pipeline parallelism — GPipe-style stage sharding over the ``pipe`` axis.

No reference counterpart (SURVEY.md §2.3 checklist: PP absent upstream —
design headroom for the TPU build, like ring attention and MoE). Two stage
models:

- **Homogeneous** (``GPipe(stage, n_stages=S)``): S clones of one module.
  Per-stage params stack on a leading stage dim sharded over ``pipe`` — the
  cheapest schedule, kept as the fast path.
- **Heterogeneous** (``GPipe(stages=[embed, block, ..., head])``): arbitrary
  per-stage modules whose param pytrees and boundary activation shapes may all
  differ — the shape a real model needs (a TransformerLM's embedding, blocks
  and tied head are not clones). SPMD still requires every device to run ONE
  program, so per-rank stage dispatch is a ``lax.switch`` on the device's
  ``pipe`` rank (XLA compiles all branches, each device executes its own), and
  the two heterogeneous data planes are engineered flat:
  * activations cross stage boundaries as zero-padded flat f32 buffers sized
    to the largest boundary (each branch unflattens its own static shape);
  * per-stage params are flattened, zero-padded to the largest stage and
    stacked (S, P) with the stage dim sharded over ``pipe`` — each rank holds
    ONLY its own stage's weights (true pipeline memory scaling), and each
    switch branch reconstructs its stage's pytree from its row with static
    offsets/dtypes.

At tick ``t`` a device applies its stage, then ``ppermute``\\ s the flat buffer
right; after ``M + S - 1`` ticks every microbatch crossed all stages. The
backward pipeline needs no hand-written schedule: jax reverse-mode
differentiates the ``scan`` + ``switch`` + ``ppermute`` chain, yielding the
reversed-communication schedule automatically — the train step stays ONE
jitted program, at the GPipe all-forward-then-all-backward memory profile
(activation residuals for all M microbatches live between the forward and
backward halves), softened by ``remat=True``.

``schedule="1f1b"`` (round-4 verdict #4) replaces that profile with a
hand-scheduled **one-forward-one-backward** interleave for TRAINING: the
loss moves INSIDE the pipelined program (``pipeline_train_step``, picked up
automatically by the Optimizer when a 1f1b GPipe is the root model), each
backward is an explicit per-stage ``jax.vjp`` with recompute (only the
stage's INPUT is stashed, the standard remat trade), and a statically
simulated PipeDream-flush schedule drives forwards and backwards through
one ``lax.scan``. In-flight microbatches per rank are bounded by
``min(S - rank, M)`` instead of ``M``, so the activation stash is
``O(S × microbatch)`` instead of ``O(M × microbatch)`` — the thing 1F1B
exists to fix — while producing bit-identical gradients (pinned by test
against the autodiff GPipe schedule).

Stages must be stateless — BatchNorm running stats would silently diverge per
rank; use ``BatchNormalization(sync=True)`` inside ``shard_map`` data-parallel
code instead, or LayerNorm in pipelined transformer stacks — and must not
need RNG (build blocks with dropout=0).

Off-mesh (no ``pipe`` axis) the same microbatch loop runs sequentially without
communication, so tests and single-chip runs get identical math.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from bigdl_tpu.nn.abstractnn import AbstractModule, Container


def _simulate_1f1b(s: int, m: int):
    """Statically simulate the PipeDream-flush (non-interleaved 1F1B)
    schedule for ``s`` stages × ``m`` microbatches under a one-op-per-tick,
    one-hop-per-tick wire model. Returns int32 numpy tables of shape (T, S):

    - ``f_tab[t, r]``  — microbatch whose FORWARD rank r runs at tick t (-1 none)
    - ``b_tab[t, r]``  — microbatch whose BACKWARD rank r runs at tick t
    - ``rf_tab[t, r]`` — microbatch whose forward activation ARRIVES at rank r
      at tick t (sent by r-1 at t-1)
    - ``rb_tab[t, r]`` — microbatch whose output-gradient arrives at rank r
      (sent by r+1 at t-1)

    Policy: backward-when-ready, else forward, with at most
    ``min(s - r, m)`` microbatches in flight per rank — exactly the classic
    1F1B steady state. The simulation also validates ring-buffer safety:
    in-flight microbatch indices are distinct mod s, so stashes keyed
    ``micro % s`` can never collide."""
    next_f = [0] * s
    next_b = [0] * s
    f_done = [[None] * m for _ in range(s)]
    b_done = [[None] * m for _ in range(s)]
    rows = []
    t = 0
    while any(next_b[r] < m for r in range(s)):
        row = []
        for r in range(s):
            f_i = b_i = -1
            can_f = (next_f[r] < m
                     and (next_f[r] - next_b[r]) < min(s - r, m))
            if can_f and r > 0:
                up = f_done[r - 1][next_f[r]]
                can_f = up is not None and up + 1 <= t
            can_b = next_b[r] < next_f[r]
            if can_b:
                i = next_b[r]
                if r == s - 1:
                    can_b = f_done[r][i] is not None and f_done[r][i] < t
                else:
                    dn = b_done[r + 1][i]
                    can_b = dn is not None and dn + 1 <= t
            if can_b:
                b_i = next_b[r]
            elif can_f:
                f_i = next_f[r]
            row.append((f_i, b_i))
        for r, (f_i, b_i) in enumerate(row):
            if f_i >= 0:
                # ring-slot safety: no other in-flight micro shares f_i mod s
                assert all((j - f_i) % s != 0
                           for j in range(next_b[r], next_f[r])), \
                    "1F1B stash ring collision"
                f_done[r][f_i] = t
                next_f[r] += 1
            if b_i >= 0:
                b_done[r][b_i] = t
                next_b[r] += 1
        rows.append(row)
        t += 1
        if t > 6 * (m + s) + 32:
            raise RuntimeError("1F1B schedule simulation did not converge")
    T = len(rows)
    f_tab = np.full((T, s), -1, np.int32)
    b_tab = np.full((T, s), -1, np.int32)
    rf_tab = np.full((T, s), -1, np.int32)
    rb_tab = np.full((T, s), -1, np.int32)
    for tt, row in enumerate(rows):
        for r, (f_i, b_i) in enumerate(row):
            f_tab[tt, r] = f_i
            b_tab[tt, r] = b_i
            if f_i >= 0 and r + 1 < s and tt + 1 < T:
                rf_tab[tt + 1, r + 1] = f_i
            if b_i >= 0 and r - 1 >= 0 and tt + 1 < T:
                rb_tab[tt + 1, r - 1] = b_i
    # every rank must complete m forwards and m backwards, in order
    for r in range(s):
        assert sorted(i for i in f_tab[:, r] if i >= 0) == list(range(m))
        assert sorted(i for i in b_tab[:, r] if i >= 0) == list(range(m))
    return f_tab, b_tab, rf_tab, rb_tab


def _check_stage(stage: AbstractModule) -> AbstractModule:
    if jax.tree_util.tree_leaves(stage.get_state()):
        raise ValueError(
            "GPipe stages must be stateless: per-rank running statistics "
            "(e.g. BatchNorm) would silently diverge across pipeline ranks. "
            "Use LayerNorm in pipelined stacks, or BatchNormalization("
            "sync=True) under data-parallel shard_map instead.")
    if stage.needs_rng():
        raise ValueError(
            "GPipe stages must not need RNG (build blocks with dropout=0); "
            "the pipeline schedule replays stages across microbatch ticks")
    return stage


class GPipe(Container):
    """Pipeline container. ``GPipe(stage, n_stages=S)`` composes S fresh
    clones; ``GPipe(stages=[...])`` pipelines arbitrary heterogeneous modules.
    Executed as a pipeline over the ``pipe`` mesh axis when present."""

    def __init__(self, stage: Optional[AbstractModule] = None,
                 n_stages: int = 1, n_microbatches: int = 2,
                 axis_name: str = "pipe",
                 stages: Optional[Sequence[AbstractModule]] = None,
                 remat: bool = False, schedule: str = "gpipe"):
        if (stage is None) == (stages is None):
            raise ValueError("pass exactly one of `stage` or `stages`")
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got {schedule!r}")
        # "1f1b" changes the TRAINING step only (pipeline_train_step, picked
        # up by the Optimizer when this GPipe is the root model); forward/
        # inference always uses the GPipe tick loop — identical math
        self.schedule = schedule
        # remat: recompute each stage's internals in backward instead of
        # stashing them across the whole GPipe schedule — the standard relief
        # for the all-forward-then-all-backward activation profile autodiff
        # gives this scan (a hand-scheduled 1F1B would change the SCHEDULE;
        # remat changes what is LIVE, which is the memory that matters here)
        self.remat = bool(remat)
        if stages is not None:
            mods = [_check_stage(m) for m in stages]
            n_stages = len(mods)
            self.homogeneous = False
        else:
            _check_stage(stage)
            mods = [stage]
            for _ in range(n_stages - 1):
                c = stage.clone()
                c.reset()  # independent parameters per stage
                mods.append(c)
            self.homogeneous = True
        super().__init__(*mods)
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.axis_name = axis_name

    # ------------------------------------------------------------------ run
    def _stage_apply(self, i: int, params, x, training):
        # stages are stateless, but containers still want the structured
        # (empty) state tree
        def run(p, xx):
            out, _ = self.modules[i].apply(p, self.modules[i].get_state(), xx,
                                           training=training, rng=None)
            return out
        if self.remat:
            run = jax.checkpoint(run)
        return run(params, x)

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.utils.engine import Engine

        s, m = self.n_stages, self.n_microbatches
        b = input.shape[0]
        if b % m != 0:
            raise ValueError(f"batch {b} not divisible by n_microbatches {m}")

        mesh = Engine.mesh() if Engine.is_initialized() else None
        axes = dict(mesh.shape) if mesh is not None else {}
        if axes.get(self.axis_name, 1) == s and s > 1:
            # under dp x pp the batch stays sharded over `data` inside the
            # shard_map (replicating it would all-gather and nullify dp)
            data_axis = Engine.DATA_AXIS if Engine.DATA_AXIS in axes else None
            d = axes.get(data_axis, 1) if data_axis else 1
            if d > 1 and (b % d != 0 or (b // d) % m != 0):
                raise ValueError(
                    f"batch {b} must divide by data size {d} and the local "
                    f"batch by n_microbatches {m}")
            run = (self._apply_sharded if self.homogeneous
                   else self._apply_sharded_hetero)
            return run(params, input, training, mesh,
                       data_axis if d > 1 else None), state

        # sequential fallback: same stage composition, no communication
        y = input
        for i in range(s):
            y = self._stage_apply(i, params[str(i)], y, training)
        return y, state

    # ------------------------------------------- homogeneous (stacked) path
    def _apply_sharded(self, params, x, training, mesh, data_axis=None):
        s, m = self.n_stages, self.n_microbatches
        axis = self.axis_name
        x_spec = P(data_axis) if data_axis else P()
        # stack per-stage params on a leading stage dim (sharded over `pipe`)
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *[params[str(i)] for i in range(s)])

        def body(p_stk, xs):
            rank = lax.axis_index(axis)
            p = jax.tree_util.tree_map(lambda l: l[0], p_stk)  # my stage
            micro = xs.reshape((m, xs.shape[0] // m) + xs.shape[1:])
            # carries become device-varying after the first ppermute; mark the
            # (invariant) zeros accordingly or scan rejects the carry typing
            zero = lax.pcast(jnp.zeros_like(micro[0]), (axis,), to="varying")
            out_acc = lax.pcast(jnp.zeros_like(micro), (axis,), to="varying")
            perm = [(i, i + 1) for i in range(s - 1)]

            def tick(carry, t):
                recv, out_acc = carry
                feed = micro[jnp.minimum(t, m - 1)]
                inp = jnp.where(jnp.logical_and(rank == 0, t < m), feed, recv)
                out = self._stage_apply(0, p, inp, training)
                # last stage banks microbatch t-(s-1) when it emerges
                slot = jnp.clip(t - (s - 1), 0, m - 1)
                bank = jnp.logical_and(rank == s - 1, t >= s - 1)
                prev = lax.dynamic_index_in_dim(out_acc, slot, 0,
                                                keepdims=False)
                out_acc = lax.dynamic_update_index_in_dim(
                    out_acc, jnp.where(bank, out, prev), slot, axis=0)
                recv = lax.ppermute(out, axis, perm)
                return (recv, out_acc), None

            (recv, out_acc), _ = lax.scan(tick, (zero, out_acc),
                                          jnp.arange(m + s - 1))
            # results live on the last stage only → broadcast over the axis
            out_acc = jnp.where(lax.axis_index(axis) == s - 1, out_acc, 0.0)
            out_acc = lax.psum(out_acc, axis)
            return out_acc.reshape(xs.shape)

        spec_p = jax.tree_util.tree_map(lambda _: P(axis), stacked)
        fn = jax.shard_map(body, mesh=mesh,
                           in_specs=(spec_p, x_spec), out_specs=x_spec)
        return fn(stacked, x)

    # ------------------------------------------ heterogeneous (switch) path
    def _boundary_shapes(self, stage_params, x, bm, training):
        """Chain eval_shape through the stages: (in_shapes, out_shapes,
        buf_len) for the zero-padded flat activation wire."""
        s = self.n_stages
        in_shapes = []   # stage i input aval
        out_shapes = []  # stage i output aval
        cur = jax.ShapeDtypeStruct((bm,) + x.shape[1:], x.dtype)
        for i in range(s):
            in_shapes.append(cur)
            cur = jax.eval_shape(
                lambda p, xx, i=i: self._stage_apply(i, p, xx, training),
                stage_params[i], cur)
            if not hasattr(cur, "shape"):
                raise ValueError("GPipe stages must return a single array")
            out_shapes.append(cur)
        # the flat wire must also carry the stage-0 feed (rank 0 reshapes recv
        # into the feed shape on late ticks), so include the input extent too
        buf_len = max([int(np.prod(o.shape)) for o in out_shapes]
                      + [int(np.prod(in_shapes[0].shape))])
        return in_shapes, out_shapes, buf_len

    def _flat_param_machinery(self, stage_params):
        """Flatten+pad+stack per-stage params: (S, P) sharded over ``pipe``,
        so each rank materialises only its own stage's weights. Returns
        (p_stk, unflatten, offsets) where ``unflatten(i, row)`` rebuilds
        stage i's pytree from its padded row."""
        flat, offsets = [], []
        for sp in stage_params:
            leaves = jax.tree_util.tree_leaves(sp)
            offs, off = [], 0
            for l in leaves:
                offs.append((off, l.shape, l.dtype))
                off += int(np.prod(l.shape))
            offsets.append(offs)
            vec = (jnp.concatenate([jnp.ravel(l).astype(jnp.float32)
                                    for l in leaves])
                   if leaves else jnp.zeros((0,), jnp.float32))
            flat.append(vec)
        p_len = max(v.shape[0] for v in flat)
        p_stk = jnp.stack([jnp.pad(v, (0, p_len - v.shape[0])) for v in flat])
        treedefs = [jax.tree_util.tree_structure(sp) for sp in stage_params]

        def unflatten(i, row):
            leaves = [lax.dynamic_slice(row, (off,), (int(np.prod(shape)),))
                      .reshape(shape).astype(dtype)
                      for off, shape, dtype in offsets[i]]
            return jax.tree_util.tree_unflatten(treedefs[i], leaves)

        return p_stk, unflatten, offsets

    def _apply_sharded_hetero(self, params, x, training, mesh, data_axis=None):
        s, m = self.n_stages, self.n_microbatches
        axis = self.axis_name
        x_spec = P(data_axis) if data_axis else P()
        d = dict(mesh.shape).get(data_axis, 1) if data_axis else 1
        bm = (x.shape[0] // d) // m  # per-rank microbatch size

        stage_params = [params[str(i)] for i in range(s)]
        in_shapes, out_shapes, buf_len = self._boundary_shapes(
            stage_params, x, bm, training)
        p_stk, unflatten, _ = self._flat_param_machinery(stage_params)

        def body(p_stk, xs):
            rank = lax.axis_index(axis)
            row = p_stk[0]  # my stage's flattened params
            micro = xs.reshape((m, bm) + xs.shape[1:])
            # switch branches must agree on varying-axes typing: the feed is
            # pipe-invariant while recv is pipe-varying — promote everything
            # to the same set up front
            micro = lax.pcast(micro, (axis,), to="varying")
            vaxes = (axis,) if data_axis is None else (axis, data_axis)

            def branch(i):
                def run(row, recv, t):
                    if i == 0:
                        feed = micro[jnp.minimum(t, m - 1)]
                        inp = jnp.where(
                            t < m, feed,
                            recv[:feed.size].reshape(feed.shape)
                            .astype(feed.dtype))
                    else:
                        av = in_shapes[i]
                        inp = recv[:int(np.prod(av.shape))] \
                            .reshape(av.shape).astype(av.dtype)
                    out = self._stage_apply(i, unflatten(i, row), inp,
                                            training)
                    vec = jnp.ravel(out).astype(jnp.float32)
                    return jnp.pad(vec, (0, buf_len - vec.shape[0]))
                return run

            branches = [branch(i) for i in range(s)]
            zero = lax.pcast(jnp.zeros((buf_len,), jnp.float32),
                             vaxes, to="varying")
            out_acc = lax.pcast(jnp.zeros((m, buf_len), jnp.float32),
                                vaxes, to="varying")
            perm = [(i, i + 1) for i in range(s - 1)]

            def tick(carry, t):
                recv, out_acc = carry
                out = lax.switch(jnp.clip(rank, 0, s - 1), branches,
                                 row, recv, t)
                slot = jnp.clip(t - (s - 1), 0, m - 1)
                bank = jnp.logical_and(rank == s - 1, t >= s - 1)
                prev = lax.dynamic_index_in_dim(out_acc, slot, 0,
                                                keepdims=False)
                out_acc = lax.dynamic_update_index_in_dim(
                    out_acc, jnp.where(bank, out, prev), slot, axis=0)
                recv = lax.ppermute(out, axis, perm)
                return (recv, out_acc), None

            (_, out_acc), _ = lax.scan(tick, (zero, out_acc),
                                       jnp.arange(m + s - 1))
            # banked results live on the last rank only → broadcast, then
            # unflatten to the last stage's output shape
            out_acc = jnp.where(rank == s - 1, out_acc, 0.0)
            out_acc = lax.psum(out_acc, axis)
            fs = out_shapes[-1]
            n_out = int(np.prod(fs.shape))
            out = out_acc[:, :n_out].reshape((m,) + fs.shape).astype(fs.dtype)
            return out.reshape((m * bm,) + fs.shape[1:])

        fn = jax.shard_map(body, mesh=mesh,
                           in_specs=(P(axis), x_spec), out_specs=x_spec)
        return fn(p_stk, x)

    # --------------------------------------------- 1F1B training schedule
    def pipeline_train_step(self, params, x, y, criterion, mesh,
                            data_axis=None):
        """Hand-scheduled 1F1B training step: returns ``(loss, grads)`` with
        the criterion INSIDE the pipelined program (the only way to interleave
        backwards with forwards — autodiff of ``apply`` is structurally
        all-forward-then-all-backward). Each backward is an explicit
        per-stage ``jax.vjp`` with forward recompute, so the per-rank stash
        holds only stage INPUTS for in-flight microbatches:
        ``min(S - rank, M)`` buffers instead of GPipe's ``M``. Gradients are
        bit-compatible with the autodiff schedule (pinned by test)."""
        s, m = self.n_stages, self.n_microbatches
        axis = self.axis_name
        x_spec = P(data_axis) if data_axis else P()
        d = dict(mesh.shape).get(data_axis, 1) if data_axis else 1
        bm = (x.shape[0] // d) // m

        stage_params = [params[str(i)] for i in range(s)]
        in_shapes, out_shapes, buf_len = self._boundary_shapes(
            stage_params, x, bm, True)
        p_stk, unflatten, offsets = self._flat_param_machinery(stage_params)
        p_len = p_stk.shape[1]
        f_tab, b_tab, rf_tab, rb_tab = _simulate_1f1b(s, m)
        n_ticks = f_tab.shape[0]
        crit_averages = bool(getattr(criterion, "size_average", True))
        # mean criteria: full-batch mean == mean of equal-size micro means
        scale = 1.0 / m if crit_averages else 1.0

        # mixed precision mirrors the generic step: fp32 master params/wires,
        # stage compute in the Engine dtype (bf16 → MXU double rate); the
        # cast's transpose returns fp32 gradients through the per-stage vjp
        from bigdl_tpu.nn.precision import cast_floating
        from bigdl_tpu.utils.engine import Engine
        compute_dtype = Engine.compute_dtype()
        mixed = compute_dtype != jnp.float32

        def stage_flat(i, row, buf):
            av = in_shapes[i]
            inp = buf[:int(np.prod(av.shape))].reshape(av.shape) \
                .astype(av.dtype)
            p = unflatten(i, row)
            if mixed:
                p = cast_floating(p, compute_dtype)
                inp = cast_floating(inp, compute_dtype)
            out = self._stage_apply(i, p, inp, True)
            vec = jnp.ravel(out).astype(jnp.float32)
            return jnp.pad(vec, (0, buf_len - vec.shape[0]))

        def body(p_stk_l, xs, ys):
            rank = lax.axis_index(axis)
            row = p_stk_l[0]          # my stage's flattened params
            micro_x = xs.reshape((m, bm) + xs.shape[1:])
            micro_y = ys.reshape((m, ys.shape[0] // m) + ys.shape[1:])
            vaxes = (axis,) if data_axis is None else (axis, data_axis)
            micro_x = lax.pcast(micro_x, (axis,), to="varying")
            micro_y = lax.pcast(micro_y, (axis,), to="varying")

            def zeros(shape):
                return lax.pcast(jnp.zeros(shape, jnp.float32), vaxes,
                                 to="varying")

            fwd_branches = [
                (lambda i: lambda row_, buf: stage_flat(i, row_, buf))(i)
                for i in range(s)]

            def bwd_branch(i):
                def run(row_, x_buf, g_buf, y_mb):
                    if i == s - 1:
                        def f(rw, xb):
                            out_flat = stage_flat(i, rw, xb)
                            fs = out_shapes[i]
                            out = out_flat[:int(np.prod(fs.shape))] \
                                .reshape(fs.shape).astype(fs.dtype)
                            return criterion.apply(out, y_mb) * scale
                        loss_i, vjp = jax.vjp(f, row_, x_buf)
                        # the cotangent must carry the same varying-axes
                        # typing as the primal loss under shard_map
                        d_row, dx = vjp(jnp.ones_like(loss_i))
                        return (d_row.astype(jnp.float32), dx,
                                loss_i.astype(jnp.float32))

                    def f(rw, xb):
                        return stage_flat(i, rw, xb)
                    _, vjp = jax.vjp(f, row_, x_buf)
                    d_row, dx = vjp(g_buf)
                    # zero loss must carry the same varying-axes typing as
                    # the last branch's real loss (switch output contract)
                    return (d_row.astype(jnp.float32), dx,
                            lax.pcast(jnp.zeros((), jnp.float32), vaxes,
                                      to="varying"))
                return run
            bwd_branches = [bwd_branch(i) for i in range(s)]

            rankc = jnp.clip(rank, 0, s - 1)
            f_j = jnp.asarray(f_tab)
            b_j = jnp.asarray(b_tab)
            rf_j = jnp.asarray(rf_tab)
            rb_j = jnp.asarray(rb_tab)

            def tick(carry, t):
                fwd_in, bwd_in, x_stash, gsum, loss_acc, wire_f, wire_b = carry
                # 1. bank last tick's arrivals into the micro-keyed rings
                rfm = rf_j[t, rankc]
                fwd_in = jnp.where(
                    rfm >= 0,
                    lax.dynamic_update_index_in_dim(
                        fwd_in, wire_f, lax.rem(jnp.maximum(rfm, 0), s), 0),
                    fwd_in)
                rbm = rb_j[t, rankc]
                bwd_in = jnp.where(
                    rbm >= 0,
                    lax.dynamic_update_index_in_dim(
                        bwd_in, wire_b, lax.rem(jnp.maximum(rbm, 0), s), 0),
                    bwd_in)

                # 2. forward op (scheduled ranks only; cond skips the rest)
                fi = f_j[t, rankc]
                fslot = lax.rem(jnp.maximum(fi, 0), s)
                feed = micro_x[jnp.clip(fi, 0, m - 1)]
                feed = jnp.pad(jnp.ravel(feed).astype(jnp.float32),
                               (0, buf_len - feed.size))
                inp = jnp.where(rank == 0, feed,
                                lax.dynamic_index_in_dim(fwd_in, fslot, 0,
                                                         keepdims=False))
                x_stash = jnp.where(
                    fi >= 0,
                    lax.dynamic_update_index_in_dim(x_stash, inp, fslot, 0),
                    x_stash)
                # the last rank's forward output is never delivered (ppermute
                # stops at s-2) and its backward recomputes from x_stash —
                # skip the compute, keep only the stash write above
                send_f = lax.cond(
                    jnp.logical_and(fi >= 0, rankc < s - 1),
                    lambda: lax.switch(rankc, fwd_branches, row, inp),
                    lambda: zeros((buf_len,)))

                # 3. backward op: vjp with recompute off the stashed input
                bi = b_j[t, rankc]
                bslot = lax.rem(jnp.maximum(bi, 0), s)
                x_in = lax.dynamic_index_in_dim(x_stash, bslot, 0,
                                                keepdims=False)
                g_in = lax.dynamic_index_in_dim(bwd_in, bslot, 0,
                                                keepdims=False)
                y_mb = micro_y[jnp.clip(bi, 0, m - 1)]
                # NOTE on varying-axes typing: row is data-INVARIANT (pipe-
                # sharded, data-replicated), so shard_map's vjp psums d_row
                # over the data axis automatically — d_row/gsum are typed
                # V:pipe and already hold the cross-data-rank SUM.
                d_row, dx, loss_i = lax.cond(
                    bi >= 0,
                    lambda: lax.switch(rankc, bwd_branches, row, x_in, g_in,
                                       y_mb),
                    lambda: (lax.pcast(jnp.zeros((p_len,), jnp.float32),
                                       (axis,), to="varying"),
                             zeros((buf_len,)), zeros(())))
                gsum = gsum + d_row
                loss_acc = loss_acc + loss_i

                # 4. wires: activations hop right, gradients hop left
                wire_f = lax.ppermute(send_f, axis,
                                      [(i, i + 1) for i in range(s - 1)])
                wire_b = lax.ppermute(dx, axis,
                                      [(i + 1, i) for i in range(s - 1)])
                return (fwd_in, bwd_in, x_stash, gsum, loss_acc,
                        wire_f, wire_b), None

            init = (zeros((s, buf_len)), zeros((s, buf_len)),
                    zeros((s, buf_len)),
                    lax.pcast(jnp.zeros((p_len,), jnp.float32), (axis,),
                              to="varying"),
                    zeros(()), zeros((buf_len,)), zeros((buf_len,)))
            (_, _, _, gsum, loss_acc, _, _), _ = lax.scan(
                tick, init, jnp.arange(n_ticks))

            # loss lives on the last rank only
            loss = lax.psum(loss_acc, axis)
            if data_axis is not None:
                # gsum already holds the cross-data SUM (vjp auto-psum, see
                # above); mean criteria need the mean of per-shard grads
                loss = (lax.pmean(loss, data_axis) if crit_averages
                        else lax.psum(loss, data_axis))
                if crit_averages:
                    gsum = gsum / d
            return gsum[None, :], loss

        fn = jax.shard_map(body, mesh=mesh,
                           in_specs=(P(axis), x_spec, x_spec),
                           out_specs=(P(axis), P()))
        g_stk, loss = fn(p_stk, x, y)

        grads = {}
        for i in range(s):
            leaves = [g_stk[i, off:off + int(np.prod(shape))]
                      .reshape(shape).astype(dtype)
                      for off, shape, dtype in offsets[i]]
            grads[str(i)] = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(stage_params[i]), leaves)
        return loss, grads

    def __repr__(self):
        kind = "homogeneous" if self.homogeneous else "heterogeneous"
        return (f"GPipe(stages={self.n_stages} [{kind}], "
                f"microbatches={self.n_microbatches}, "
                f"schedule={self.schedule})")


from bigdl_tpu.utils.serializer import register as _register_serializable  # noqa: E402

_register_serializable(GPipe)
