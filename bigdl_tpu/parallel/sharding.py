"""Sharding helpers — the TPU-native replacement for the reference's parameter-partition
machinery.

Reference parity (SURVEY.md §2.3/§5.8, expected ``<dl>/parameters/AllReduceParameter.scala``
— unverified): the reference flattens all parameters into one vector, splits it into
``partitionNum`` slices, and moves gradient/weight slices through the Spark BlockManager —
structurally reduce-scatter → per-slice optimizer update → all-gather (ZeRO-1).

TPU-native: no flattening, no explicit messaging. Pytrees get ``NamedSharding`` annotations
over the Engine mesh and XLA's SPMD partitioner emits the ICI collectives:

- replicated params + batch sharded on ``data`` → XLA inserts the gradient all-reduce;
- ``zero1_state_sharding`` shards optimizer slots over ``data`` → the (elementwise) update
  computes sharded and XLA all-gathers the new params — the exact slice-owned update the
  reference ran over BlockManager, minus the seam.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis))


def shard_leading_axis(mesh: Mesh, x_shape, axis: str = "data") -> NamedSharding:
    """Shard dim 0 over ``axis`` when divisible, else replicate (per-leaf decision)."""
    n = int(dict(mesh.shape)[axis])
    if len(x_shape) > 0 and x_shape[0] % n == 0 and x_shape[0] >= n:
        return NamedSharding(mesh, P(axis))
    return NamedSharding(mesh, P())


def zero1_state_sharding(mesh: Mesh, state_tree, axis: str = "data"):
    """A sharding pytree for optimizer slots: leading-axis sharded where divisible.

    Matches the reference's slice-owned optimizer state (each partition updates 1/N of the
    parameter vector); here the slicing is per-leaf along dim 0 and XLA handles the
    reduce-scatter/all-gather placement.
    """
    import jax

    return jax.tree_util.tree_map(
        lambda x: shard_leading_axis(mesh, np.shape(x), axis), state_tree)


# -------------------------------------------------- spec export (elastic ckpt)
def spec_to_tuple(sharding):
    """A :class:`NamedSharding`'s PartitionSpec as plain nested tuples —
    the mesh-independent, picklable form elastic checkpoints record per leaf.
    Anything that is not a NamedSharding (single-device arrays, callback
    shardings) maps to None, i.e. "replicated / whole array"."""
    if not isinstance(sharding, NamedSharding):
        return None
    return tuple(tuple(e) if isinstance(e, (list, tuple)) else e
                 for e in tuple(sharding.spec))


def adapt_spec(spec, mesh: Mesh, shape) -> P:
    """Re-target a recorded spec tuple onto ``mesh``: per dimension, keep the
    axis names that exist on the new mesh AND still divide the dim; everything
    else degrades to replication. This is what makes a sharded checkpoint
    topology-portable — a leaf saved row-sharded over a 'model' axis loads
    replicated on a mesh without one, and a zero1 slot saved over 8 'data'
    devices re-slices over 4."""
    if spec is None:
        return P()
    sizes = dict(mesh.shape)
    out = []
    for dim, entry in enumerate(spec):
        if entry is None:
            out.append(None)
            continue
        axes = tuple(entry) if isinstance(entry, (list, tuple)) else (entry,)
        if not all(a in sizes for a in axes):
            out.append(None)
            continue
        n = int(np.prod([sizes[a] for a in axes]))
        if dim < len(shape) and shape[dim] % n == 0 and shape[dim] >= n:
            out.append(entry if isinstance(entry, str) else tuple(axes))
        else:
            out.append(None)
    while out and out[-1] is None:
        out.pop()  # trailing Nones are implicit
    return P(*out)
