"""Sharded embedding engine: row-sharded tables, deduped gathers, sparse updates.

The recsys workload (ROADMAP item 2, PAPER.md's NCF/Wide&Deep heritage) is dominated by a
huge (V, D) embedding table that can neither be replicated nor densely updated: the plain
``LookupTable`` gather VJP scatter-adds into the FULL weight and the optimizer then steps
every row — O(table) HBM traffic for O(batch) touched rows. Three composable pieces fix
the three halves of that:

- :class:`ShardedEmbedding` — wraps a ``LookupTable``/``HashBucketEmbedding`` and places
  the (V, D) weight ROW-sharded on the ``model`` mesh axis (GSPMD, PAPERS.md 2105.04663)
  while ids stay ``data``-sharded, the same gather-by-index dispatch shape as
  ``parallel/moe.py``'s expert routing. Gathers are exact row copies, so the sharded
  forward/backward is bitwise-equal to the replicated layer.
- **deduped gathers** — per-batch static-shape ``jnp.unique`` (:func:`dedup_ids`) so a
  power-law id distribution gathers each hot row once; an inverse map scatters rows back
  to positions. Padded with the out-of-range sentinel ``V`` so shapes stay static.
- :class:`SparseEmbeddingUpdate` — an :class:`OptimMethod` wrapper (the sparse sibling of
  ``kernels/fused_update.FlatParamUpdate``): the train step differentiates a zero
  per-unique-row **delta** injected through the module-state channel instead of the table
  weight (the weight itself is gathered under ``stop_gradient``), so autodiff produces an
  exact (U, D) row-gradient and never materializes a dense (V, D) gradient; the wrapped
  method's ``sparse_update`` then steps ONLY the touched rows and their slot rows
  (lazy semantics: untouched rows and slots are bitwise-unchanged).

``build_sparse_plan`` discovers the sharded tables in a model and the Optimizer fuses the
whole thing into its jitted step (see ``optim/optimizer.py``); ``embedding_parallel_rules``
/ ``model_embedding_rules`` produce the ``TPRules`` placement for ``DistriOptimizer``.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.nn.abstractnn import Container
from bigdl_tpu.nn.embedding import LookupTable, check_ids_enabled
from bigdl_tpu.optim.optim_method import OptimMethod, tree_map
from bigdl_tpu.parallel.tensor_parallel import TPRules
from bigdl_tpu.utils.engine import Engine

logger = logging.getLogger("bigdl_tpu.parallel")

_DELTA_KEY = "delta"   # injected by the sparse train step (module-state channel)
_UIDS_KEY = "uids"     # returned by apply in sparse mode; stripped by the step


def dedup_ids(flat_ids, n_rows: int):
    """Static-shape per-batch dedup: ``(uids, inv)`` with ``uids`` the sorted
    unique ids padded to ``flat_ids.shape`` with the out-of-range sentinel
    ``n_rows``, and ``inv`` the inverse map (``uids[inv] == flat_ids``).
    A gather of the sentinel row clamps harmlessly (never referenced by
    ``inv``); a ``mode="drop"`` scatter drops it."""
    size = int(flat_ids.shape[0])
    uids, inv = jnp.unique(flat_ids, size=size, fill_value=n_rows,
                           return_inverse=True)
    return uids.astype(jnp.int32), inv.reshape(-1).astype(jnp.int32)


def _shard_enabled() -> bool:
    return os.environ.get("BIGDL_EMBED_SHARD", "1") == "1"


def _dedup_enabled() -> bool:
    return os.environ.get("BIGDL_EMBED_DEDUP", "1") == "1"


class ShardedEmbedding(Container):
    """Row-sharded, dedup-gathering wrapper around a ``LookupTable`` (or
    ``HashBucketEmbedding``). One child named ``table`` — the param pytree is
    ``{"table": {"weight": (V, D)}}`` so placement rules and checkpoints
    address the weight as ``.../table/weight``.

    Forward paths (all bitwise-equal to the wrapped layer's, gathers being
    exact row copies):

    - plain: full-table renorm + gather (dedup off);
    - dedup (``BIGDL_EMBED_DEDUP``, default on): gather unique rows once,
      scatter back by the inverse map — each hot row's HBM read happens once;
    - sparse-train: when the optimizer injected a ``delta`` into this module's
      state for the step, rows come from ``stop_gradient(weight)[uids] +
      delta`` and the batch's ``uids`` ride back through the returned state.

    Under a live mesh whose ``axis`` (default ``model``) is >1 wide and
    divides V, traced forwards constrain the weight to ``P(axis, None)`` and
    the leading id axis to ``P("data")`` (``BIGDL_EMBED_SHARD``, default on) —
    the GSPMD partitioner then keeps the table row-sharded through gather,
    scatter and optimizer update.
    """

    def __init__(self, inner: LookupTable, axis: str = "model",
                 dedup: Optional[bool] = None):
        if not isinstance(inner, LookupTable):
            raise TypeError(
                f"ShardedEmbedding wraps a LookupTable/HashBucketEmbedding, "
                f"got {type(inner).__name__}")
        super().__init__(inner)
        self.axis = axis
        self.dedup = dedup  # None → BIGDL_EMBED_DEDUP (default on)

    @property
    def table(self) -> LookupTable:
        return self.modules[0]

    def named_children(self):
        return [("table", self.modules[0])]

    def reset(self) -> None:
        self.modules[0].reset()

    def _dedup_on(self) -> bool:
        return self.dedup if self.dedup is not None else _dedup_enabled()

    def _constrain(self, w, idx):
        """GSPMD placement hints (traced values only — eager forwards skip)."""
        if not _shard_enabled() or not isinstance(w, jax.core.Tracer):
            return w, idx
        if not Engine.is_initialized():
            return w, idx
        mesh = Engine.mesh()
        if mesh is None:
            return w, idx
        axes = dict(mesh.shape)
        if axes.get(self.axis, 1) > 1 and w.shape[0] % axes[self.axis] == 0:
            w = jax.lax.with_sharding_constraint(
                w, NamedSharding(mesh, P(self.axis, None)))
        dax = Engine.DATA_AXIS
        if (axes.get(dax, 1) > 1 and idx.ndim >= 1
                and idx.shape[0] % axes[dax] == 0):
            spec = P(dax, *([None] * (idx.ndim - 1)))
            idx = jax.lax.with_sharding_constraint(
                idx, NamedSharding(mesh, spec))
        return w, idx

    def apply(self, params, state, input, *, training=False, rng=None):
        t = self.table
        tstate = state.get("table", {}) if isinstance(state, dict) else {}
        w = params["table"]["weight"]
        idx = t._ids(input)
        w, idx = self._constrain(w, idx)
        sparse_mode = isinstance(state, dict) and _DELTA_KEY in state
        if sparse_mode:
            flat = idx.reshape(-1)
            uids, inv = dedup_ids(flat, t.n_index)
            # the delta trick: the weight is gathered under stop_gradient and a
            # zero (U, D) delta is added pre-renorm, so grad-wrt-delta IS the
            # exact dense grad restricted to the unique rows (renorm is
            # row-local) and no (V, D) gradient is ever materialized
            rows = jax.lax.stop_gradient(w)[uids]
            if state[_DELTA_KEY] is not None:
                rows = rows + state[_DELTA_KEY]
            rows = t._renorm_rows(rows)
            out = rows[inv].reshape(idx.shape + (t.n_output,))
            out = t._mask_padding(out, idx)
            return out, {"table": tstate, _UIDS_KEY: uids}
        if self._dedup_on():
            flat = idx.reshape(-1)
            uids, inv = dedup_ids(flat, t.n_index)
            rows = t._renorm_rows(w[uids])
            out = rows[inv].reshape(idx.shape + (t.n_output,))
        else:
            out = t._renorm(w)[idx]
        return t._mask_padding(out, idx), {"table": tstate}

    def forward(self, input):
        # mirror LookupTable.forward: the eager entry point runs the host-side
        # BIGDL_CHECK_IDS guard on the concrete batch before the jitted apply
        if check_ids_enabled():
            self.table._ids(jnp.asarray(input))
        return super().forward(input)

    def __repr__(self):
        return f"ShardedEmbedding({self.table!r}, axis={self.axis!r})"


# --------------------------------------------------------------- placement
def embedding_parallel_rules(prefix: str = "", axis: str = "model",
                             rules: Optional[TPRules] = None) -> TPRules:
    """TPRules placing every ``.../table/weight`` under ``prefix`` row-sharded
    on ``axis`` (the embedding analog of ``moe.expert_parallel_rules``)."""
    r = rules if rules is not None else TPRules()
    pre = f"(^|/){re.escape(prefix)}/" if prefix else "(^|/)"
    r.add(f"{pre}table/weight$", P(axis, None))
    return r


def model_embedding_rules(model, rules: Optional[TPRules] = None) -> TPRules:
    """Exact-path TPRules for every :class:`ShardedEmbedding` found in
    ``model`` (each on its own configured axis)."""
    r = rules if rules is not None else TPRules()
    for path, mod in find_sharded_embeddings(model):
        leaf = "/".join(path + ("table", "weight"))
        r.add(f"^{re.escape(leaf)}$", P(mod.axis, None))
    return r


def find_sharded_embeddings(model):
    """All ``(module_path, module)`` ShardedEmbeddings in a module tree, in
    child order; paths are tuples of child names (Graph children are exec
    indices)."""
    found = []

    def walk(m, path):
        if isinstance(m, ShardedEmbedding):
            found.append((path, m))
            return
        if isinstance(m, Container):
            for name, child in m.named_children():
                walk(child, path + (name,))

    walk(model, ())
    return found


# ------------------------------------------------------------ sparse plan
def _tree_get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _tree_set(tree, path, value):
    if not path:
        return value
    new = dict(tree)
    new[path[0]] = _tree_set(tree[path[0]], path[1:], value)
    return new


@dataclasses.dataclass(frozen=True)
class SparseEntry:
    key: str            # joined module path — the stable slot-dict key
    module_path: tuple  # path to the ShardedEmbedding module
    n_rows: int
    n_output: int

    @property
    def weight_path(self) -> tuple:
        return self.module_path + ("table", "weight")


class SparsePlan:
    """Which tables train sparsely, plus the pytree surgery the step needs:
    inject per-table deltas into model state, pop the returned uids, and mask
    the (dense-zero) embedding-weight gradient leaves to 0-size."""

    def __init__(self, entries):
        self.entries = list(entries)

    def inject(self, mstate, deltas: dict):
        for e in self.entries:
            sub = dict(_tree_get(mstate, e.module_path))
            sub[_DELTA_KEY] = deltas[e.key]
            mstate = _tree_set(mstate, e.module_path, sub)
        return mstate

    def pop_uids(self, mstate):
        uids = {}
        for e in self.entries:
            sub = dict(_tree_get(mstate, e.module_path))
            uids[e.key] = sub.pop(_UIDS_KEY)
            mstate = _tree_set(mstate, e.module_path, sub)
        return uids, mstate

    def mask_embed(self, tree):
        """Embedding weight leaves → 0-size placeholders (the frozen-leaf
        trimming idiom): the inner method's dense pass never allocates or
        touches (V, D) there, but the pytree STRUCTURE is unchanged."""
        for e in self.entries:
            leaf = _tree_get(tree, e.weight_path)
            tree = _tree_set(tree, e.weight_path,
                             jnp.zeros((0,), jnp.asarray(leaf).dtype))
        return tree

    def zero_deltas(self, model, params, mstate, inp, rng):
        """Trace-time probe: abstractly evaluate one forward with ``delta=None``
        injected to discover each table's static unique-row capacity U (the
        flattened per-table id count after model wiring), then return zero
        (U, D) deltas. Pure metadata — runs under ``jax.eval_shape``."""
        def sds(x):
            return (None if x is None
                    else jax.ShapeDtypeStruct(jnp.shape(x), x.dtype))
        probe_state = self.inject(mstate, {e.key: None for e in self.entries})
        abstract = jax.eval_shape(
            lambda p, s, x, r: model.apply(p, s, x, training=True, rng=r)[1],
            tree_map(sds, params), tree_map(sds, probe_state),
            tree_map(sds, inp), sds(rng))
        deltas = {}
        for e in self.entries:
            u = _tree_get(abstract, e.module_path)[_UIDS_KEY].shape[0]
            w = _tree_get(params, e.weight_path)
            deltas[e.key] = jnp.zeros((u, e.n_output), w.dtype)
        return deltas

    def __repr__(self):
        return f"SparsePlan({[e.key for e in self.entries]})"


def build_sparse_plan(model, method):
    """Discover the sparse-trainable tables in ``model`` under ``method``.
    Returns ``(SparsePlan | None, reason | None)`` — ``reason`` is set when
    sharded tables exist but cannot train sparsely (the optimizer logs it
    once and keeps the dense path)."""
    mods = find_sharded_embeddings(model)
    if not mods:
        return None, None
    if not method.supports_sparse_update():
        return None, (f"{method!r} does not support sparse_update "
                      "(stateful schedule / layer_lr_mults / non-elementwise)")
    if model.has_regularizers():
        return None, ("model has weight regularizers — their gradient is "
                      "dense over the table")
    entries = []
    for path, m in mods:
        scale = m.grad_scales()["table"]["weight"]
        if scale != 1.0:
            # frozen (0) or grad-scaled tables keep the dense/frozen path
            continue
        t = m.table
        entries.append(SparseEntry(key="/".join(path) or ".",
                                   module_path=path,
                                   n_rows=t.n_index, n_output=t.n_output))
    if not entries:
        return None, "every sharded table is frozen or grad-scaled"
    return SparsePlan(entries), None


# ------------------------------------------------------- optimizer wrapper
class SparseEmbeddingUpdate(OptimMethod):
    """Method wrapper fusing sparse per-row embedding updates with the inner
    method's dense update over everything else (the sparse sibling of
    ``kernels/fused_update.FlatParamUpdate``). Slot layout::

        {"dense": inner slots with embed-weight leaves trimmed to 0-size,
         "embed": {entry.key: inner.init_state(weight)}}   # full (V, D) slots

    Driven by the Optimizer's sparse step through :meth:`sparse_apply`; the
    plain ``update`` protocol is intentionally unsupported (there is no dense
    (V, D) gradient to feed it — that is the point)."""

    elementwise_update = False

    def __init__(self, method: OptimMethod, plan: SparsePlan):
        self.method = method
        self.plan = plan

    def init_state(self, params) -> dict:
        return self.init_state_trimmed(params, None)

    def init_state_trimmed(self, params, trainable=None) -> dict:
        mp = self.plan.mask_embed(params)
        dense = self.method.init_state_trimmed(mp, trainable)
        embed = {e.key: self.method.init_state(_tree_get(params, e.weight_path))
                 for e in self.plan.entries}
        return {"dense": dense, "embed": embed}

    def update(self, params, grads, state, step):
        raise RuntimeError(
            "SparseEmbeddingUpdate is driven by the optimizer's sparse step "
            "(sparse_apply); it has no dense update form")

    def sparse_apply(self, params, grads, row_grads, uids_map, state, step,
                     trainable=None):
        """One optimizer update: the inner method's dense pass over the masked
        tree, then per-table gather-update-scatter over the unique rows.
        ``row_grads``/``uids_map`` are ``{entry.key: (U, D) grad / (U,) ids}``
        from the delta trick; the sentinel id V clamps on gather and drops on
        scatter, so its (zero-grad) row update is dead code."""
        mp = self.plan.mask_embed(params)
        mg = self.plan.mask_embed(grads)
        new_mp, new_dense = self.method.update_trimmed(
            mp, mg, state["dense"], step, trainable)
        new_params = new_mp
        new_embed = {}
        for e in self.plan.entries:
            w = _tree_get(params, e.weight_path)
            u = uids_map[e.key]
            slots = state["embed"][e.key]
            rows = w[u]
            slot_rows = tree_map(lambda s: s[u], slots)
            new_rows, new_slot_rows = self.method.sparse_update(
                rows, row_grads[e.key], slot_rows, step)
            # NOT unique_indices: the sentinel V repeats in u — but it is
            # out-of-range, so mode="drop" discards those writes and the
            # remaining indices are genuinely unique
            new_w = w.at[u].set(new_rows, mode="drop")
            new_slots = tree_map(lambda s, nr: s.at[u].set(nr, mode="drop"),
                                 slots, new_slot_rows)
            new_params = _tree_set(new_params, e.weight_path, new_w)
            new_embed[e.key] = new_slots
        return new_params, {"dense": new_dense, "embed": new_embed}

    def get_learning_rate(self, step: int) -> float:
        return self.method.get_learning_rate(step)

    def __repr__(self):
        return f"SparseEmbeddingUpdate({self.method!r}, {self.plan!r})"


from bigdl_tpu.utils.serializer import register as _register_serializable  # noqa: E402

_register_serializable(ShardedEmbedding)
