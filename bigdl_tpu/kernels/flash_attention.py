"""Pallas TPU kernel — single-chip flash attention.

Complements the multi-chip ring attention (parallel/ring_attention.py): ring
shards the SEQUENCE over mesh devices and rotates K/V over ICI; this kernel is
the intra-chip analog of the same streaming-softmax idea. Plain XLA attention
materialises the (T, T) score matrix in HBM twice (softmax in, probs out);
flash keeps one (block_q, block_k) score tile at a time in VMEM with running
max/sum statistics, so HBM traffic drops from O(T^2) to O(T·d) and the two
matmuls per tile stay on the MXU.

Grid layout (TPU grids execute sequentially, innermost-last): (batch*heads,
q_blocks, k_blocks) with the k-dim innermost; the running (m, l, acc) state
lives in VMEM scratch carried across k iterations, initialised at k==0 and
flushed to the output block at the last k step — the standard Pallas
accumulation pattern.

Semantics: forward = Pallas kernel on TPU (interpreter elsewhere — tests);
backward = recompute-form VJP of the reference jnp attention
(rematerialisation: one extra fused forward instead of stashing the
probability matrix — same trade as kernels/layernorm.py).
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)
_fallback_warned = False


def _reference_attention(q, k, v, causal: bool):
    """Plain jnp attention over (..., T, d) — the numerical oracle and VJP."""
    d = q.shape[-1]
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v.astype(p.dtype)).astype(q.dtype)


def _pallas_flash_call(q3, k3, v3, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    scale = 1.0 / (d ** 0.5)
    n_k = t // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        # causal block skip: a k-block strictly above the diagonal contributes
        # nothing — skip its two matmuls entirely (halves causal FLOPs)
        live = (j * block_k <= i * block_q + block_q - 1) if causal else True

        @pl.when(live)
        def _step():
            q = q_ref[0].astype(jnp.float32)
            k = k_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if causal:
                qi = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                kj = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(kj <= qi, s, -jnp.inf)

            m_prev = m_scr[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            alpha = jnp.where(jnp.isfinite(m_prev),
                              jnp.exp(m_prev - safe_m), 0.0)
            p = jnp.exp(jnp.where(jnp.isfinite(s), s - safe_m, -jnp.inf))
            l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
                p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[:] = m_new

        @pl.when(j == n_k - 1)
        def _flush():
            denom = jnp.maximum(l_scr[:], 1e-37)
            o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        grid=(bh, t // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pick_block(t: int, target: int) -> int:
    block = 1
    while block < target and t % (block * 2) == 0:
        block *= 2
    return block


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False,
                    force_pallas: bool | None = None):
    """Streaming-softmax attention over (batch, heads, T, d) operands.

    ``force_pallas``: None = pallas on TPU, reference jnp elsewhere; True =
    pallas (interpreted off-TPU — tests); False = reference.
    """
    return _fa_fwd(q, k, v, causal, force_pallas)[0]


def _fa_fwd(q, k, v, causal, force_pallas):
    use_pallas = _on_tpu() if force_pallas is None else force_pallas
    out = None
    if use_pallas:
        b, h, t, d = q.shape
        # measured on v5e (T=2048, d=64): 256/512 tiles amortise grid-step
        # overhead ~30% better than 128/128 and beat XLA's fused attention;
        # VMEM stays comfortable (score tile 256x512 fp32 = 512 KB)
        block_q, block_k = _pick_block(t, 256), _pick_block(t, 512)
        # degenerate tiles can't use the MXU profitably; fall back
        if block_q >= 8 and block_k >= 8:
            try:
                q3 = q.reshape(b * h, t, d)
                k3 = k.reshape(b * h, t, d)
                v3 = v.reshape(b * h, t, d)
                out = _pallas_flash_call(
                    q3, k3, v3, causal, block_q, block_k,
                    interpret=not _on_tpu()).reshape(b, h, t, d)
            except Exception as e:  # pallas unavailable → reference
                global _fallback_warned
                if not _fallback_warned:
                    _fallback_warned = True
                    logger.warning(
                        "flash_attention Pallas kernel failed (%s: %s); "
                        "falling back to O(T^2) reference attention — "
                        "long-context memory/speed benefits are lost",
                        type(e).__name__, e)
                out = None
    if out is None:
        out = _reference_attention(q, k, v, causal)
    return out, (q, k, v)


def _fa_bwd(causal, force_pallas, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda qq, kk, vv: _reference_attention(qq, kk, vv, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
