"""Pallas TPU kernel — single-chip flash attention.

Complements the multi-chip ring attention (parallel/ring_attention.py): ring
shards the SEQUENCE over mesh devices and rotates K/V over ICI; this kernel is
the intra-chip analog of the same streaming-softmax idea. Plain XLA attention
materialises the (T, T) score matrix in HBM twice (softmax in, probs out);
flash keeps one (block_q, block_k) score tile at a time in VMEM with running
max/sum statistics, so HBM traffic drops from O(T^2) to O(T·d) and the two
matmuls per tile stay on the MXU.

Grid layout (TPU grids execute sequentially, innermost-last): (batch*heads,
q_blocks, k_blocks) with the k-dim innermost; the running (m, l, acc) state
lives in VMEM scratch carried across k iterations, initialised at k==0 and
flushed to the output block at the last k step — the standard Pallas
accumulation pattern.

Semantics: forward AND backward are Pallas kernels on TPU (interpreter
elsewhere — tests). The backward is the standard flash-2 scheme: the forward
additionally saves the per-row logsumexp L = m + log(l); backward recomputes
each (block_q, block_k) probability tile from (q, k, L) in VMEM and streams
  dq += (p * (dO·v^T - D)) · k,   dv += p^T · dO,   dk += ds^T · q
with D = rowsum(dO * O) precomputed in one fused elementwise pass — so
TRAINING memory is O(T·d) too, not just inference (the O(T^2) score matrix is
never materialised in either direction; asserted by test against the compiled
HLO). Off-TPU (or if the kernel build fails) the recompute-form VJP of the
reference jnp attention remains as fallback.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)
_fallback_warned = False


def _reference_attention(q, k, v, causal: bool):
    """Plain jnp attention over (..., T, d) — the numerical oracle and VJP."""
    d = q.shape[-1]
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32)
    s = s / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v.astype(p.dtype)).astype(q.dtype)


def _pallas_flash_call(q3, k3, v3, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    scale = 1.0 / (d ** 0.5)
    n_k = t // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        # causal block skip: a k-block strictly above the diagonal contributes
        # nothing — skip its two matmuls entirely (halves causal FLOPs)
        live = (j * block_k <= i * block_q + block_q - 1) if causal else True

        @pl.when(live)
        def _step():
            q = q_ref[0].astype(jnp.float32)
            k = k_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if causal:
                qi = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                kj = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(kj <= qi, s, -jnp.inf)

            m_prev = m_scr[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            alpha = jnp.where(jnp.isfinite(m_prev),
                              jnp.exp(m_prev - safe_m), 0.0)
            p = jnp.exp(jnp.where(jnp.isfinite(s), s - safe_m, -jnp.inf))
            l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
                p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[:] = m_new

        @pl.when(j == n_k - 1)
        def _flush():
            denom = jnp.maximum(l_scr[:], 1e-37)
            o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)
            # per-row logsumexp residual for the flash backward: rows with no
            # live block (cannot happen causally — the diagonal is live) would
            # be -inf; clamp through the same denom guard
            lse_ref[0] = (m_scr[:] + jnp.log(denom))[:, 0]

    out, lse = pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
                   jax.ShapeDtypeStruct((bh, t), jnp.float32)],
        grid=(bh, t // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, block_q), lambda b, i, j: (b, i))],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse


def _pallas_flash_bwd_dq(q3, k3, v3, do3, lse3, dd3, causal,
                         block_q, block_k, interpret):
    """dq = Σ_j (p_ij * (dO_i·v_j^T - D_i)) · k_j * scale, streaming over j
    with the probability tile recomputed from (q, k, lse) in VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    scale = 1.0 / (d ** 0.5)
    n_k = t // block_k

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, acc_scr):
        i = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            acc_scr[:] = jnp.zeros_like(acc_scr)

        live = (j * block_k <= i * block_q + block_q - 1) if causal else True

        @pl.when(live)
        def _step():
            q = q_ref[0].astype(jnp.float32)
            k = k_ref[0].astype(jnp.float32)
            v = v_ref[0].astype(jnp.float32)
            do = do_ref[0].astype(jnp.float32)
            lse = lse_ref[0][:, None]                     # (bq, 1)
            dd = dd_ref[0][:, None]                       # (bq, 1)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if causal:
                qi = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                kj = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(kj <= qi, s, -jnp.inf)
            p = jnp.exp(s - lse)                          # (bq, bk)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - dd) * scale
            acc_scr[:] = acc_scr[:] + jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(j == n_k - 1)
        def _flush():
            dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        grid=(bh, t // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, dd3)


def _pallas_flash_bwd_dkv(q3, k3, v3, do3, lse3, dd3, causal,
                          block_q, block_k, interpret):
    """dv = Σ_i p_ij^T · dO_i ; dk = Σ_i ds_ij^T · q_i * scale — grid iterates
    k-blocks outer, q-blocks inner, with (dk, dv) accumulators in VMEM."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    scale = 1.0 / (d ** 0.5)
    n_q = t // block_q

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
               dk_ref, dv_ref, dk_scr, dv_scr):
        j = pl.program_id(1)   # k block
        i = pl.program_id(2)   # q block (innermost)

        @pl.when(i == 0)
        def _init():
            dk_scr[:] = jnp.zeros_like(dk_scr)
            dv_scr[:] = jnp.zeros_like(dv_scr)

        # causal: a q block entirely above this k block contributes nothing
        live = (i * block_q + block_q - 1 >= j * block_k) if causal else True

        @pl.when(live)
        def _step():
            q = q_ref[0].astype(jnp.float32)
            k = k_ref[0].astype(jnp.float32)
            v = v_ref[0].astype(jnp.float32)
            do = do_ref[0].astype(jnp.float32)
            lse = lse_ref[0][None, :]                     # (1, bq)
            dd = dd_ref[0][None, :]                       # (1, bq)
            # transposed orientation: s_T (bk, bq)
            s_t = jax.lax.dot_general(k, q, (((1,), (1,)), ((), ())),
                                      preferred_element_type=jnp.float32) * scale
            if causal:
                kj = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_k, block_q), 0)
                qi = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_k, block_q), 1)
                s_t = jnp.where(kj <= qi, s_t, -jnp.inf)
            p_t = jnp.exp(s_t - lse)                      # (bk, bq)
            dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
                p_t, do, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            dp_t = jax.lax.dot_general(v, do, (((1,), (1,)), ((), ())),
                                       preferred_element_type=jnp.float32)
            ds_t = p_t * (dp_t - dd) * scale
            dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
                ds_t, q, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        @pl.when(i == n_q - 1)
        def _flush():
            dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
            dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)

    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((bh, t, d), k3.dtype),
                   jax.ShapeDtypeStruct((bh, t, d), v3.dtype)],
        grid=(bh, t // block_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),
        ],
        out_specs=[pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
                   pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, dd3)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pick_block(t: int, target: int) -> int:
    block = 1
    while block < target and t % (block * 2) == 0:
        block *= 2
    return block


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal: bool = False,
                    force_pallas: bool | None = None):
    """Streaming-softmax attention over (batch, heads, T, d) operands.

    ``force_pallas``: None = pallas on TPU, reference jnp elsewhere; True =
    pallas (interpreted off-TPU — tests); False = reference.
    """
    return _fa_fwd(q, k, v, causal, force_pallas)[0]


def _fa_fwd(q, k, v, causal, force_pallas):
    use_pallas = _on_tpu() if force_pallas is None else force_pallas
    out = lse = None
    if use_pallas:
        b, h, t, d = q.shape
        # measured on v5e (T=2048, d=64): 256/512 tiles amortise grid-step
        # overhead ~30% better than 128/128 and beat XLA's fused attention;
        # VMEM stays comfortable (score tile 256x512 fp32 = 512 KB)
        block_q, block_k = _pick_block(t, 256), _pick_block(t, 512)
        # degenerate tiles can't use the MXU profitably; fall back
        if block_q >= 8 and block_k >= 8:
            try:
                q3 = q.reshape(b * h, t, d)
                k3 = k.reshape(b * h, t, d)
                v3 = v.reshape(b * h, t, d)
                out, lse = _pallas_flash_call(
                    q3, k3, v3, causal, block_q, block_k,
                    interpret=not _on_tpu())
                out = out.reshape(b, h, t, d)
            except Exception as e:  # pallas unavailable → reference
                global _fallback_warned
                if not _fallback_warned:
                    _fallback_warned = True
                    logger.warning(
                        "flash_attention Pallas kernel failed (%s: %s); "
                        "falling back to O(T^2) reference attention — "
                        "long-context memory/speed benefits are lost",
                        type(e).__name__, e)
                out = lse = None
    if out is None:
        out = _reference_attention(q, k, v, causal)
        return out, (q, k, v, None, None)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, force_pallas, res, g):
    q, k, v, out, lse = res
    if lse is not None:
        try:
            return _flash_bwd(q, k, v, out, lse, g, causal)
        except Exception as e:  # pallas bwd unavailable → reference VJP
            global _fallback_warned
            if not _fallback_warned:
                _fallback_warned = True
                logger.warning(
                    "flash_attention Pallas backward failed (%s: %s); "
                    "falling back to the O(T^2) reference VJP",
                    type(e).__name__, e)
    _, vjp = jax.vjp(
        lambda qq, kk, vv: _reference_attention(qq, kk, vv, causal), q, k, v)
    return vjp(g)


def _flash_bwd(q, k, v, out, lse, g, causal):
    """Streaming flash-2 backward: O(T·d) memory, probability tiles recomputed
    from (q, k, lse) in VMEM."""
    b, h, t, d = q.shape
    block_q, block_k = _pick_block(t, 128), _pick_block(t, 128)
    reshape = lambda a: a.reshape(b * h, t, d)
    q3, k3, v3, do3 = reshape(q), reshape(k), reshape(v), reshape(g)
    # D_i = rowsum(dO * O): one fused elementwise pass, O(T·d) reads
    dd3 = jnp.sum(do3.astype(jnp.float32) * reshape(out).astype(jnp.float32),
                  axis=-1)
    interp = not _on_tpu()
    dq = _pallas_flash_bwd_dq(q3, k3, v3, do3, lse, dd3, causal,
                              block_q, block_k, interp)
    dk, dv = _pallas_flash_bwd_dkv(q3, k3, v3, do3, lse, dd3, causal,
                                   block_q, block_k, interp)
    unshape = lambda a, like: a.reshape(b, h, t, d).astype(like.dtype)
    return unshape(dq, q), unshape(dk, k), unshape(dv, v)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
