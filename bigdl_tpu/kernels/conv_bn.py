"""Fused conv → batch-norm → ReLU kernel.

The vision zoo (LeNet/ResNet/Inception/VGG) is built from
``SpatialConvolution → SpatialBatchNormalization → ReLU`` triples. Under one
``jit`` XLA already fuses the BN *elementwise tail* into the conv epilogue,
but the module boundary still costs structure: three modules means three
params/state subtrees threaded through every step, three ``named_scope``
rows, and — the real prize — no way to run the classic inference-time
BN *folding*, where the per-channel scale/shift collapses into the conv
weights and the normalisation disappears from the program entirely.

:class:`FusedConvBNReLU` owns a (conv, bn) pair as one module:

- **training** (and eval with folding off): delegates to the wrapped
  modules' own ``apply`` in sequence — the SAME ops in the SAME order, so
  the fused module is **bitwise identical** to the unfused stack in fp32
  (pinned by tests/test_kernels.py) while presenting one fusion region to
  the compiler and one node to the graph;
- **inference with folding** (``BIGDL_CONVBN_FOLD``, default on): the BN
  running statistics are folded into the conv — ``w' = w · s``,
  ``b' = b · s + (β − μ·s)`` with ``s = γ·rsqrt(σ² + ε)`` — and the whole
  triple runs as ONE conv(+bias)(+relu). Equivalent within float tolerance
  (the op order changes); the training path is never folded.

Models opt in via the graph-level pass :func:`bigdl_tpu.nn.graph.fuse_conv_bn`
(env knob ``BIGDL_CONVBN_FUSE=1`` applies it automatically in the Optimizer);
with the knob off no model is touched — the legacy path is byte-identical.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp


def fold_bn_scale_shift(bn_params: dict, bn_state: dict, eps: float):
    """Per-channel (scale, shift) equivalent to an eval-mode batch norm:
    ``bn(y) == y * scale + shift`` with running statistics. Math in fp32
    (the unfused BN normalises in fp32 too)."""
    mean = bn_state["running_mean"].astype(jnp.float32)
    var = bn_state["running_var"].astype(jnp.float32)
    inv = jax.lax.rsqrt(var + eps)
    if "weight" in bn_params:  # affine
        scale = bn_params["weight"].astype(jnp.float32) * inv
        shift = bn_params["bias"].astype(jnp.float32) - mean * scale
    else:
        scale = inv
        shift = -mean * scale
    return scale, shift


def fold_bn_into_conv(weight, bias, scale, shift):
    """Fold a per-output-channel (scale, shift) into OIHW conv weights:
    returns ``(w', b')`` with ``w' = w·s`` (output-channel axis 0) and
    ``b' = b·s + shift`` (``bias`` may be None)."""
    w = weight.astype(jnp.float32) * scale[:, None, None, None]
    b = shift if bias is None else bias.astype(jnp.float32) * scale + shift
    return w.astype(weight.dtype), b


def fold_enabled() -> bool:
    """Inference folding knob, read at trace time (``BIGDL_CONVBN_FOLD``,
    default on — folding only ever applies inside an explicitly fused
    module, so the legacy unfused path is unaffected either way)."""
    return os.environ.get("BIGDL_CONVBN_FOLD", "1") != "0"


from bigdl_tpu.nn.abstractnn import Container  # noqa: E402
from bigdl_tpu.utils.serializer import register as _register_serializable  # noqa: E402


@_register_serializable
class FusedConvBNReLU(Container):
    """One module owning a ``SpatialConvolution → SpatialBatchNormalization
    (→ ReLU)`` triple. Params/state nest as children ``{"0": conv, "1": bn}``
    (Container semantics: freeze/regularizers/serialization all keep
    working). ``fold_inference=None`` defers to ``BIGDL_CONVBN_FOLD`` at
    trace time; the training path is never folded."""

    def __init__(self, conv, bn, relu: bool = False,
                 fold_inference: bool | None = None):
        super().__init__(conv, bn)
        self.conv, self.bn = conv, bn
        self.with_relu = bool(relu)
        self.fold_inference = fold_inference

    def _folds(self) -> bool:
        if self.fold_inference is not None:
            return bool(self.fold_inference)
        return fold_enabled()

    def apply(self, params, state, input, *, training=False, rng=None):
        with jax.named_scope(f"fused_conv_bn[{self.conv.name}]"):
            if not training and self._folds():
                return self._apply_folded(params, state, input)
            # delegation path: the exact unfused op sequence — bitwise equal
            # to Sequential(conv, bn[, relu]) in fp32
            out, cs = self.conv.apply(params["0"], state["0"], input,
                                      training=training, rng=None)
            out, bs = self.bn.apply(params["1"], state["1"], out,
                                    training=training, rng=None)
            if self.with_relu:
                out = jax.nn.relu(out)
            return out, {"0": cs, "1": bs}

    def _apply_folded(self, params, state, input):
        from bigdl_tpu.nn import layout
        scale, shift = fold_bn_scale_shift(params["1"], state["1"],
                                           self.bn.eps)
        cp = params["0"]
        w, b = fold_bn_into_conv(cp["weight"], cp.get("bias"), scale, shift)
        # reuse the conv's own apply for layout/groups/padding/squeeze; the
        # folded shift rides its bias slot when the conv has one
        if "bias" in cp:
            out, cs = self.conv.apply({"weight": w, "bias": b.astype(w.dtype)},
                                      state["0"], input, training=False,
                                      rng=None)
        else:
            out, cs = self.conv.apply({"weight": w}, state["0"], input,
                                      training=False, rng=None)
            out = out + b.astype(out.dtype).reshape(
                layout.bias_shape(self.bn.n_output, out.ndim))
        if self.with_relu:
            out = jax.nn.relu(out)
        return out, {"0": cs, "1": dict(state["1"])}

    def __repr__(self):
        tail = " -> ReLU" if self.with_relu else ""
        return f"FusedConvBNReLU({self.conv!r} -> {self.bn!r}{tail})"
