"""TPU kernels: fused ops the module zoo and trainer dispatch into.

- ``layernorm`` — Pallas fused LayerNorm (one VMEM residency per row block);
- ``flash_attention`` — streaming-softmax attention (imported on demand);
- ``conv_bn`` — fused conv→bn(→relu) with inference-time BN folding;
- ``fused_update`` — flat-param (dtype-grouped vector) optimizer updates.
"""

from bigdl_tpu.kernels.layernorm import fused_layer_norm

__all__ = ["fused_layer_norm", "FusedConvBNReLU", "fold_bn_into_conv",
           "fold_bn_scale_shift", "FlatParamUpdate", "flat_supported"]


def __getattr__(name):
    # conv_bn/fused_update pull in the nn/optim packages — import lazily so
    # `from bigdl_tpu.kernels import fused_layer_norm` (the normalization
    # layer's hot path) never pays for or cycles through them
    if name in ("FusedConvBNReLU", "fold_bn_into_conv", "fold_bn_scale_shift",
                "fold_enabled"):
        from bigdl_tpu.kernels import conv_bn
        return getattr(conv_bn, name)
    if name in ("FlatParamUpdate", "flat_supported", "FlatSpec"):
        from bigdl_tpu.kernels import fused_update
        return getattr(fused_update, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
