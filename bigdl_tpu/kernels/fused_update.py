"""Flat-param optimizer update — one fused vector kernel per step.

A model's parameter pytree has one leaf per weight tensor; an elementwise
optimizer (SGD/Adam/…) traced over it emits one tiny kernel *per leaf per
op* — on small-layer models (LeNet: 10 leaves; anything with many norms or
biases: hundreds) the per-kernel launch/bookkeeping overhead dominates the
actual update math. The fix, standard in TPU training stacks: flatten the
params/grads/slot pytrees into a handful of contiguous 1-D vectors (one per
dtype), run the update as a few big fused vector ops, and slice the result
back into leaves. Concatenate/slice/reshape are exact, and an elementwise
update computes bit-for-bit the same value per element on the flat vector
as per leaf — the jitted flat update is **bitwise identical** to the jitted
per-leaf reference (pinned by tests/test_kernels.py). Inside the full
compiled train step, XLA may contract FMAs differently around the two
forms, so end-to-end training agrees to ~1 ulp rather than bitwise.

:class:`FlatParamUpdate` wraps any :class:`OptimMethod` whose ``update`` is
purely elementwise (``elementwise_update = True`` on the class): the inner
method's ``tree_map`` update simply runs over the {dtype: vector} pytree
instead of the model tree. Slots are created flat and STAY flat (the scan
carry / donation / checkpoint all see a static small pytree); only
params/grads are flattened and the new params unflattened, per step.

Enable with ``BIGDL_FLAT_UPDATE=1`` / ``Optimizer.set_flat_update(True)``;
default off (legacy path byte-identical). Methods with per-leaf behavior
(``layer_lr_mults``, LARS's per-layer trust ratio, L-BFGS's own flattening,
composite routing) are automatically left on the per-leaf path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def flat_supported(method) -> bool:
    """Can ``method`` run on the flat vector? Requires a purely elementwise
    update (class opt-in) and no per-leaf LR multipliers."""
    if isinstance(method, FlatParamUpdate):
        return False
    if getattr(method, "layer_lr_mults", None):
        return False  # path-keyed multipliers need the leaf structure
    return bool(getattr(method, "elementwise_update", False))


class FlatSpec:
    """Static flattening plan for one pytree structure: leaves group by
    dtype (first-seen order) and concatenate into one 1-D vector per group.
    Built from tracers or arrays — only shape/dtype are read."""

    def __init__(self, tree):
        leaves, self.treedef = jax.tree_util.tree_flatten(tree)
        self.metas = []           # per leaf: (group_key, offset, shape)
        sizes: dict[str, int] = {}  # running group sizes → offsets
        for leaf in leaves:
            key = str(jnp.result_type(leaf))
            shape = tuple(jnp.shape(leaf))
            n = 1
            for d in shape:
                n *= d
            off = sizes.get(key, 0)
            self.metas.append((key, off, shape))
            sizes[key] = off + n
        self.group_keys = list(sizes)

    def flatten(self, tree) -> dict:
        """Pytree → {dtype_key: 1-D vector} (order per ``metas``)."""
        leaves = self.treedef.flatten_up_to(tree)
        groups: dict[str, list] = {k: [] for k in self.group_keys}
        for (key, _, _), leaf in zip(self.metas, leaves):
            groups[key].append(jnp.reshape(leaf, (-1,)))
        return {k: (v[0] if len(v) == 1 else jnp.concatenate(v))
                if v else jnp.zeros((0,), k)
                for k, v in groups.items()}

    def unflatten(self, flat: dict):
        """{dtype_key: vector} → pytree of the original structure."""
        leaves = []
        for key, off, shape in self.metas:
            n = 1
            for d in shape:
                n *= d
            leaves.append(jnp.reshape(flat[key][off:off + n], shape))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


from bigdl_tpu.optim.optim_method import OptimMethod  # noqa: E402


class FlatParamUpdate(OptimMethod):
    """Run an elementwise :class:`OptimMethod` over dtype-grouped flat
    vectors. Stateless wrapper: the flattening plan is re-derived from the
    (static) parameter structure on every call, so two wrappers over the
    same inner method are interchangeable (checkpoint slots carry over)."""

    def __init__(self, inner: OptimMethod):
        self.inner = inner

    @property
    def learningrate_schedule(self):
        return getattr(self.inner, "learningrate_schedule", None)

    def init_state(self, params) -> dict:
        spec = FlatSpec(params)
        return self.inner.init_state(spec.flatten(params))

    def update(self, params, grads, state, step):
        spec = FlatSpec(params)
        new_flat, new_state = self.inner.update(
            spec.flatten(params), spec.flatten(grads), state, step)
        return spec.unflatten(new_flat), new_state

    def get_learning_rate(self, step):
        return self.inner.get_learning_rate(step)

    def __repr__(self):
        return f"FlatParamUpdate({self.inner!r})"
