"""Vision pipeline: ImageFrame / ImageFeature + feature transformer zoo.

Reference parity (SURVEY.md §2.2, expected ``<dl>/transform/vision/image/`` —
unverified): the reference wraps OpenCV mats in ``ImageFeature`` dict-records
collected in an ``ImageFrame`` (local or RDD), transformed by a ``FeatureTransformer``
zoo (Resize/Crop/Flip/ChannelNormalize/Brightness/ColorJitter/Lighting/Expand/…),
ending in ``MatToTensor`` + ``ImageFrameToSample``.

TPU-native: decode/augment stays on the HOST (as upstream — the accelerator never
decodes JPEGs); images are numpy HWC arrays (PIL for codec work, pure numpy for the
math), and the pipeline output feeds ``SampleToMiniBatch`` → device. Randomized
transforms draw from a per-pipeline ``numpy.random.Generator`` seeded via
``Engine``'s seed for reproducibility.

Deterministic parallel randomness: when the parallel transform engine
(``dataset/parallel.py``) runs a sample under ``sample_index_scope(i)``, the
``_rng`` property resolves to a per-sample generator derived from
(this transformer's seed material, sample index ``i``) instead of the shared
sequential stream — so the SAME sample gets the SAME augmentation no matter
how many workers run the pipeline or in what order they finish. Outside a
scope (the classic serial path) draws come from the shared stream exactly as
before.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import (
    Transformer, current_sample_index, current_sample_rng_cache,
)

_FLOAT = np.float32


class ImageFeature(dict):
    """Dict-record for one image: keys ``image`` (HWC numpy), ``label``,
    ``uri``, plus anything transformers attach."""

    IMAGE, LABEL, URI, ORIGINAL_SIZE = "image", "label", "uri", "original_size"

    def __init__(self, image=None, label=None, uri: Optional[str] = None):
        super().__init__()
        if image is not None:
            self[self.IMAGE] = np.asarray(image)
            self[self.ORIGINAL_SIZE] = tuple(np.asarray(image).shape)
        if label is not None:
            self[self.LABEL] = label
        if uri is not None:
            self[self.URI] = uri

    @property
    def image(self) -> np.ndarray:
        return self[self.IMAGE]

    @image.setter
    def image(self, v) -> None:
        self[self.IMAGE] = v

    @property
    def label(self):
        return self.get(self.LABEL)


class ImageFrame:
    """A collection of ImageFeatures with ``transform`` chaining.

    The reference's distributed (RDD) variant collapses into the local one: data
    parallelism happens at the MiniBatch/mesh level, not the record level.
    """

    def __init__(self, features: Sequence[ImageFeature]):
        self.features = list(features)

    # ------------------------------------------------------------- factories
    @staticmethod
    def from_arrays(images, labels=None) -> "ImageFrame":
        labels = labels if labels is not None else [None] * len(images)
        return ImageFrame([ImageFeature(im, lb) for im, lb in zip(images, labels)])

    @staticmethod
    def read(paths, with_labels: Optional[dict] = None) -> "ImageFrame":
        """Decode image files via PIL (HWC uint8 RGB). ``with_labels`` maps
        path → label."""
        from PIL import Image as PILImage
        feats = []
        for p in paths:
            arr = np.asarray(PILImage.open(p).convert("RGB"))
            feats.append(ImageFeature(arr, (with_labels or {}).get(p), uri=p))
        return ImageFrame(feats)

    # ------------------------------------------------------------ transforms
    def transform(self, transformer: "FeatureTransformer") -> "ImageFrame":
        self.features = list(transformer(iter(self.features)))
        return self

    def __len__(self) -> int:
        return len(self.features)

    def __iter__(self):
        return iter(self.features)

    def to_samples(self) -> list:
        return list(ImageFrameToSample()(iter(self.features)))


class FeatureTransformer(Transformer):
    """Per-record transformer; compose with ``>>`` (the reference's ``->``)."""

    # Per-instance salt (RandomGenerator.next_salt): transformers built from the
    # same Engine seed must still draw *decorrelated* streams (Brightness/Contrast/
    # Saturation inside one ColorJitter would otherwise make identical random
    # picks). The salt counter resets with RandomGenerator.set_seed, so an
    # identically-seeded run rebuilding the same pipeline reproduces exactly.

    def __init__(self):
        self._seed_material = list(self._seed())
        self._stream_rng = np.random.default_rng(self._seed_material)

    @classmethod
    def _seed(cls):
        from bigdl_tpu.utils.random_generator import RandomGenerator
        salt = RandomGenerator.next_salt()
        try:
            from bigdl_tpu.utils.engine import Engine
            if Engine.is_initialized():
                return [Engine.config().seed, salt]
        except Exception:
            pass
        return [int.from_bytes(os.urandom(4), "little"), salt]

    @property
    def _rng(self) -> np.random.Generator:
        """Sequential stream rng — unless a ``sample_index_scope`` is active,
        in which case a per-(transformer, sample) generator derived from
        (seed material, sample index). The derived generator is cached for the
        scope's duration so several draws inside one ``transform_feature``
        advance ONE stream (Expand's ratio/y/x must not all see draw #1)."""
        index = current_sample_index()
        if index is None or self._seed_material is None:
            return self._stream_rng
        cache = current_sample_rng_cache()
        rng = cache.get(id(self)) if cache is not None else None
        if rng is None:
            rng = np.random.default_rng([*self._seed_material, index])
            if cache is not None:
                cache[id(self)] = rng
        return rng

    @_rng.setter
    def _rng(self, rng) -> None:
        # direct assignment (legacy/custom transformers): honor it as the
        # sequential stream; per-sample derivation is disabled because the
        # seed material behind the assigned generator is unknown
        self._seed_material = None
        self._stream_rng = rng

    def set_seed(self, seed: int) -> "FeatureTransformer":
        self._seed_material = [int(seed)]
        self._stream_rng = np.random.default_rng(self._seed_material)
        return self

    def transform_feature(self, feature: ImageFeature) -> ImageFeature:
        raise NotImplementedError

    def element_fn(self):
        """Per-record callable — FeatureTransformers are element-wise by
        construction, so every vision stage fuses and parallelizes."""
        return self.transform_feature

    def __call__(self, prev: Iterator) -> Iterator:
        return (self.transform_feature(f) for f in prev)


class Resize(FeatureTransformer):
    """Bilinear resize to (height, width) via PIL."""

    def __init__(self, resize_h: int, resize_w: int):
        super().__init__()
        self.resize_h, self.resize_w = resize_h, resize_w

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        from PIL import Image as PILImage
        img = f.image
        dtype = img.dtype
        pil = PILImage.fromarray(img.astype(np.uint8) if dtype != np.uint8 else img)
        out = np.asarray(pil.resize((self.resize_w, self.resize_h),
                                    PILImage.BILINEAR))
        f.image = out.astype(dtype) if dtype != np.uint8 else out
        return f


class AspectScale(FeatureTransformer):
    """Scale the short edge to ``min_size`` keeping aspect ratio (reference
    ``AspectScale``, the ImageNet eval resize)."""

    def __init__(self, min_size: int, max_size: int = 1000):
        super().__init__()
        self.min_size, self.max_size = min_size, max_size

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        h, w = f.image.shape[:2]
        scale = self.min_size / min(h, w)
        if max(h, w) * scale > self.max_size:
            scale = self.max_size / max(h, w)
        return Resize(int(round(h * scale)), int(round(w * scale))) \
            .transform_feature(f)


class CenterCrop(FeatureTransformer):
    def __init__(self, crop_h: int, crop_w: int):
        super().__init__()
        self.crop_h, self.crop_w = crop_h, crop_w

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        h, w = f.image.shape[:2]
        if h < self.crop_h or w < self.crop_w:
            raise ValueError(f"image {h}x{w} smaller than crop "
                             f"{self.crop_h}x{self.crop_w}")
        y = (h - self.crop_h) // 2
        x = (w - self.crop_w) // 2
        f.image = f.image[y:y + self.crop_h, x:x + self.crop_w]
        return f


class RandomCrop(FeatureTransformer):
    def __init__(self, crop_h: int, crop_w: int):
        super().__init__()
        self.crop_h, self.crop_w = crop_h, crop_w

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        h, w = f.image.shape[:2]
        if h < self.crop_h or w < self.crop_w:
            raise ValueError(f"image {h}x{w} smaller than crop "
                             f"{self.crop_h}x{self.crop_w}")
        y = int(self._rng.integers(0, h - self.crop_h + 1))
        x = int(self._rng.integers(0, w - self.crop_w + 1))
        f.image = f.image[y:y + self.crop_h, x:x + self.crop_w]
        return f


class HFlip(FeatureTransformer):
    """Deterministic horizontal flip (see RandomHFlip for the coin-toss)."""

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        f.image = f.image[:, ::-1]
        return f


class RandomHFlip(FeatureTransformer):
    def __init__(self, p: float = 0.5):
        super().__init__()
        self.p = p

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        if self._rng.random() < self.p:
            f.image = f.image[:, ::-1]
        return f


class ChannelNormalize(FeatureTransformer):
    """(x - mean) / std per channel; promotes to float32."""

    def __init__(self, means: Sequence[float], stds: Sequence[float]):
        super().__init__()
        self.means = np.asarray(means, _FLOAT)
        self.stds = np.asarray(stds, _FLOAT)

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        f.image = (f.image.astype(_FLOAT) - self.means) / self.stds
        return f


class PixelBytesToMat(FeatureTransformer):
    """Raw HWC bytes → float array (decode-less path for pre-decoded data)."""

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        f.image = f.image.astype(_FLOAT)
        return f


class Brightness(FeatureTransformer):
    """Add a uniform delta in [delta_low, delta_high] (reference Brightness)."""

    def __init__(self, delta_low: float, delta_high: float):
        super().__init__()
        self.delta_low, self.delta_high = delta_low, delta_high

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        delta = self._rng.uniform(self.delta_low, self.delta_high)
        f.image = f.image.astype(_FLOAT) + _FLOAT(delta)
        return f


class Contrast(FeatureTransformer):
    """Scale by a uniform factor in [low, high]."""

    def __init__(self, delta_low: float, delta_high: float):
        super().__init__()
        self.delta_low, self.delta_high = delta_low, delta_high

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        factor = self._rng.uniform(self.delta_low, self.delta_high)
        f.image = f.image.astype(_FLOAT) * _FLOAT(factor)
        return f


class Saturation(FeatureTransformer):
    """Blend with the grayscale image by a random factor in [low, high]."""

    def __init__(self, delta_low: float, delta_high: float):
        super().__init__()
        self.delta_low, self.delta_high = delta_low, delta_high

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        img = f.image.astype(_FLOAT)
        factor = _FLOAT(self._rng.uniform(self.delta_low, self.delta_high))
        gray = img.mean(axis=2, keepdims=True)
        f.image = gray + factor * (img - gray)
        return f


class ColorJitter(FeatureTransformer):
    """Random brightness/contrast/saturation in random order (reference
    ``ColorJitter``)."""

    def __init__(self, brightness: float = 32.0, contrast: float = 0.5,
                 saturation: float = 0.5):
        super().__init__()
        self.parts = [Brightness(-brightness, brightness),
                      Contrast(1 - contrast, 1 + contrast),
                      Saturation(1 - saturation, 1 + saturation)]

    def set_seed(self, seed: int) -> "ColorJitter":
        super().set_seed(seed)
        for i, p in enumerate(self.parts):
            p.set_seed(seed + i + 1)
        return self

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        order = self._rng.permutation(len(self.parts))
        for i in order:
            f = self.parts[int(i)].transform_feature(f)
        return f


class Lighting(FeatureTransformer):
    """AlexNet-style PCA lighting noise: ``img += eigvec @ (alpha * eigval)``
    with ``alpha ~ N(0, alphastd)`` (reference ``Lighting``)."""

    def __init__(self, alphastd: float, eigval: Sequence[float],
                 eigvec: Sequence[Sequence[float]]):
        super().__init__()
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, _FLOAT)
        self.eigvec = np.asarray(eigvec, _FLOAT)

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        alpha = self._rng.normal(0, self.alphastd, size=3).astype(_FLOAT)
        rgb = self.eigvec @ (alpha * self.eigval)
        f.image = f.image.astype(_FLOAT) + rgb
        return f


class Expand(FeatureTransformer):
    """Place the image on a larger canvas at a random offset (SSD-style)."""

    def __init__(self, max_expand_ratio: float = 4.0,
                 means: Sequence[float] = (123.0, 117.0, 104.0)):
        super().__init__()
        self.max_expand_ratio = max_expand_ratio
        self.means = np.asarray(means, _FLOAT)

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        img = f.image.astype(_FLOAT)
        h, w, c = img.shape
        ratio = self._rng.uniform(1.0, self.max_expand_ratio)
        nh, nw = int(h * ratio), int(w * ratio)
        canvas = np.broadcast_to(self.means, (nh, nw, c)).copy()
        y = int(self._rng.integers(0, nh - h + 1))
        x = int(self._rng.integers(0, nw - w + 1))
        canvas[y:y + h, x:x + w] = img
        f.image = canvas
        return f


class ChannelOrder(FeatureTransformer):
    """Swap RGB↔BGR (the reference pipelines are BGR; PIL decodes RGB)."""

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        f.image = f.image[:, :, ::-1]
        return f


class RandomTransformer(FeatureTransformer):
    """Apply ``inner`` with probability p."""

    def __init__(self, inner: FeatureTransformer, p: float):
        super().__init__()
        self.inner = inner
        self.p = p

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        if self._rng.random() < self.p:
            return self.inner.transform_feature(f)
        return f


class MatToTensor(FeatureTransformer):
    """HWC → CHW float32 (the device layout; reference ``MatToTensor``)."""

    def transform_feature(self, f: ImageFeature) -> ImageFeature:
        f.image = np.ascontiguousarray(
            f.image.astype(_FLOAT).transpose(2, 0, 1))
        return f


class ImageFrameToSample(Transformer):
    """ImageFeature stream → Sample stream (feature = image, label if any)."""

    @staticmethod
    def _to_sample(f: ImageFeature) -> Sample:
        label = f.get(ImageFeature.LABEL)
        if label is None:
            return Sample(f.image)
        return Sample(f.image, np.int32(label)
                      if np.isscalar(label) else np.asarray(label))

    def element_fn(self):
        # one feature → one sample: fuses with the vision chain ahead of it
        return self._to_sample

    def __call__(self, prev: Iterator) -> Iterator:
        return (self._to_sample(f) for f in prev)


class Pipeline:
    """Convenience: chain feature transformers then materialize samples."""

    def __init__(self, *transformers: FeatureTransformer):
        self.transformers = list(transformers)

    def __call__(self, frame: ImageFrame) -> list:
        for t in self.transformers:
            frame = frame.transform(t)
        return frame.to_samples()
