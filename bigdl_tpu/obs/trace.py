"""Thread-aware span tracer — Chrome-trace export + JSONL event log.

``span("train/step")`` context managers record (name, thread, start, duration)
tuples; nesting is implicit per thread (Chrome/Perfetto reconstruct the tree
from time containment of same-``tid`` events, and :func:`open_spans` exposes
the live per-thread stacks for the hang watchdog). Two outputs:

- **Chrome trace JSON** (:func:`export_chrome`): ``X`` complete events with
  microsecond ``ts``/``dur`` per thread, plus thread-name metadata — loads
  directly in ``chrome://tracing`` / Perfetto.
- **JSONL event log** (:func:`event`): one JSON object per line for
  *structured* occurrences — watchdog dumps, robustness events, the end-of-run
  report — written immediately (a hung process must already have its dump on
  disk).

Gating: ``BIGDL_TRACE`` (truthy) enables span recording; ``BIGDL_TRACE_DIR``
picks the output directory (default ``./bigdl-trace``); ``BIGDL_OBS_LOG``
names the JSONL file explicitly (and enables the event log even with tracing
off — events then flow, spans don't). The disabled path is near-zero cost:
``span()`` returns a module-singleton no-op context manager and allocates
nothing — pinned by a counting test on ``_SPANS_CREATED``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

#: finished-span buffer bound; beyond it spans are counted, not stored
_MAX_SPANS = 262_144

_lock = threading.Lock()
_ENABLED = False
_EXPLICIT = False          # configure() wins over configure_from_env()
_TRACE_DIR: Optional[str] = None
_JSONL_PATH: Optional[str] = None
_JSONL_FILE = None

_finished: list = []       # (name, tid, t0_s, dur_s, args)
_dropped = 0
_totals: dict = {}         # name -> [count, total_seconds]
_threads: dict = {}        # tid -> thread name (as of first span)
_open_stacks: dict = {}    # tid -> [(name, t0_s), ...] — owner-thread writes

#: _Span instances ever constructed — the zero-alloc-when-disabled pin
_SPANS_CREATED = 0


def _truthy(raw: Optional[str]) -> bool:
    return (raw or "").strip().lower() not in ("", "0", "false", "no", "off")


def configure(enabled: Optional[bool] = None, trace_dir: Optional[str] = None,
              jsonl: Optional[str] = None) -> None:
    """Explicit configuration (tests / bench legs). Overrides the environment
    until :func:`reset`."""
    global _ENABLED, _EXPLICIT, _TRACE_DIR, _JSONL_PATH
    with _lock:
        _EXPLICIT = True
        if trace_dir is not None:
            _TRACE_DIR = trace_dir
        if enabled is not None:
            _ENABLED = bool(enabled)
        if jsonl is not None:
            _set_jsonl(jsonl)
        elif _ENABLED and _JSONL_PATH is None:
            _set_jsonl(os.path.join(_dir_locked(), f"events-{os.getpid()}.jsonl"))


def configure_from_env() -> None:
    """Re-read ``BIGDL_TRACE`` / ``BIGDL_TRACE_DIR`` / ``BIGDL_OBS_LOG``.
    Called at the top of every training run (cheap); a prior explicit
    :func:`configure` sticks."""
    global _ENABLED, _TRACE_DIR, _JSONL_PATH
    if _EXPLICIT:
        return
    with _lock:
        if _EXPLICIT:
            return
        _ENABLED = _truthy(os.environ.get("BIGDL_TRACE"))
        env_dir = os.environ.get("BIGDL_TRACE_DIR")
        if env_dir:
            _TRACE_DIR = env_dir
        env_log = os.environ.get("BIGDL_OBS_LOG")
        if env_log:
            _set_jsonl(env_log)
        elif _ENABLED and _JSONL_PATH is None:
            _set_jsonl(os.path.join(_dir_locked(), f"events-{os.getpid()}.jsonl"))


def _dir_locked() -> str:
    global _TRACE_DIR
    if _TRACE_DIR is None:
        _TRACE_DIR = os.environ.get("BIGDL_TRACE_DIR") or "bigdl-trace"
    return _TRACE_DIR


def _set_jsonl(path: str) -> None:
    global _JSONL_PATH, _JSONL_FILE
    if path == _JSONL_PATH:
        return
    if _JSONL_FILE is not None:
        try:
            _JSONL_FILE.close()
        except Exception:
            pass
    _JSONL_PATH = path
    _JSONL_FILE = None  # opened lazily on first event


def enabled() -> bool:
    return _ENABLED


def trace_dir() -> Optional[str]:
    return _TRACE_DIR


def jsonl_path() -> Optional[str]:
    return _JSONL_PATH


def chrome_path() -> Optional[str]:
    if not _ENABLED:
        return None
    return os.path.join(_dir_locked(), f"trace-{os.getpid()}.json")


# ------------------------------------------------------------------- spans
class _NullSpan:
    """Shared no-op context manager — the whole disabled hot path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "args", "_t0", "_tid")

    def __init__(self, name: str, args):
        global _SPANS_CREATED
        _SPANS_CREATED += 1
        self.name = name
        self.args = args

    def __enter__(self):
        tid = threading.get_ident()
        self._tid = tid
        stack = _open_stacks.get(tid)
        if stack is None:
            # first span on this thread: register its name for the trace
            _open_stacks[tid] = stack = []
            _threads[tid] = threading.current_thread().name
        self._t0 = time.perf_counter()
        stack.append((self.name, self._t0))
        return self

    def __exit__(self, *exc):
        global _dropped
        t1 = time.perf_counter()
        stack = _open_stacks.get(self._tid)
        if stack:
            stack.pop()
        dur = t1 - self._t0
        with _lock:
            tot = _totals.get(self.name)
            if tot is None:
                _totals[self.name] = [1, dur]
            else:
                tot[0] += 1
                tot[1] += dur
            if len(_finished) < _MAX_SPANS:
                _finished.append((self.name, self._tid, self._t0, dur,
                                  self.args))
            else:
                _dropped += 1
        return False


def span(name: str, args: Optional[dict] = None):
    """Context manager timing a named span on the current thread. When
    tracing is disabled this returns a module singleton — no allocation, no
    bookkeeping (``args`` must be passed as a dict, not ``**kwargs``, so the
    disabled call builds nothing)."""
    if not _ENABLED:
        return _NULL
    return _Span(name, args)


def span_totals() -> dict:
    """{name: {"count": n, "total_ms": ms}} aggregated over every finished
    span (survives :func:`export_chrome`; empty when tracing was off)."""
    with _lock:
        return {name: {"count": c, "total_ms": round(t * 1e3, 3)}
                for name, (c, t) in _totals.items()}


def open_spans() -> dict:
    """Live per-thread open-span stacks (outermost first) with ages — the
    watchdog's view of what every thread is in the middle of."""
    now = time.perf_counter()
    out = {}
    for tid, stack in list(_open_stacks.items()):
        entries = [{"name": n, "age_ms": round((now - t0) * 1e3, 1)}
                   for n, t0 in list(stack)]
        if entries:
            out[f"{_threads.get(tid, '?')} ({tid})"] = entries
    return out


# ------------------------------------------------------------- JSONL events
def event(kind: str, **payload) -> None:
    """Append one structured record to the JSONL event log (no-op when no
    log is configured). Flushed immediately: watchdog dumps and run reports
    must be on disk even if the process never exits cleanly."""
    global _JSONL_FILE
    if _JSONL_PATH is None:
        return
    rec = {"ts": time.time(), "kind": kind}
    rec.update(payload)
    line = json.dumps(rec, default=str) + "\n"
    with _lock:
        if _JSONL_FILE is None:
            d = os.path.dirname(_JSONL_PATH)
            if d:
                os.makedirs(d, exist_ok=True)
            _JSONL_FILE = open(_JSONL_PATH, "a")
        _JSONL_FILE.write(line)
        _JSONL_FILE.flush()


def read_events(path: str) -> list:
    """Decode a JSONL event log back into a list of dicts (the ``diag``
    subcommand's input; blank/truncated tail lines are skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail line (crash mid-write)
    return out


# ------------------------------------------------------------ chrome export
def export_chrome(path: Optional[str] = None) -> Optional[str]:
    """Write every finished span as a Chrome-trace JSON file (``X`` complete
    events, per-thread ``tid``, thread-name metadata). Returns the path, or
    None when tracing is disabled. Idempotent — the span buffer is kept."""
    if not _ENABLED:
        return None
    path = path or chrome_path()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    pid = os.getpid()
    with _lock:
        spans = list(_finished)
        threads = dict(_threads)
        dropped = _dropped
    events = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
               "args": {"name": "bigdl-tpu"}}]
    for tid, name in threads.items():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})
    for name, tid, t0, dur, args in spans:
        ev = {"name": name, "ph": "X", "cat": "bigdl",
              "ts": round(t0 * 1e6, 3), "dur": round(dur * 1e6, 3),
              "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        events.append(ev)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    event("trace_exported", path=path, spans=len(spans), dropped=dropped)
    return path


def reset() -> None:
    """Drop all recorded state and configuration (tests)."""
    global _ENABLED, _EXPLICIT, _TRACE_DIR, _JSONL_PATH, _JSONL_FILE, _dropped
    with _lock:
        _ENABLED = False
        _EXPLICIT = False
        _TRACE_DIR = None
        if _JSONL_FILE is not None:
            try:
                _JSONL_FILE.close()
            except Exception:
                pass
        _JSONL_PATH = None
        _JSONL_FILE = None
        _finished.clear()
        _totals.clear()
        _threads.clear()
        _open_stacks.clear()
        _dropped = 0


# initial configuration from the process environment (BIGDL_TRACE=1 runs)
configure_from_env()
