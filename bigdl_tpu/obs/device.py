"""Device-memory accounting — HBM gauges, live-buffer census, program memory.

Every scale claim the roadmap makes (paged-KV residency, multi-host curves)
is HBM-bound, yet nothing in the obs plane measured device memory. This
module closes that gap with three rails, all absent-not-wrong (a backend
that won't report memory yields no gauges, never fake ones):

- :func:`sample_device_memory` — one poll of ``device.memory_stats()`` per
  local device, published as ``device/hbm_bytes_in_use`` /
  ``device/hbm_peak_bytes`` (sums over local devices) and
  ``device/hbm_headroom`` (the WORST device's free fraction) registry
  gauges, plus per-device gauges ``device/<i>/hbm_bytes_in_use``;
- :func:`live_buffer_census` — count + bytes of every live jax array by
  dtype (``jax.live_arrays()``), the leak-hunting view;
- :func:`program_memory` — per-compiled-program attribution from XLA's
  ``memory_analysis()`` (temp/argument/output/code bytes), the memory twin
  of :func:`bigdl_tpu.obs.mfu.program_flops`. Costs one lowering+compile,
  so callers memoize per program-cache key exactly as they do for FLOPs.

:class:`DeviceMonitor` is the daemon that polls the first two on an
interval, mirrors serving occupancy (paged-KV ``free_page_ratio``, page /
prefix pool bytes) from registered engines into plain registry gauges, and
fires an ``hbm_pressure`` event (JSONL + robustness rail + counter) when
the worst device's headroom drops below ``BIGDL_HBM_PRESSURE_PCT`` percent.
The latest sample is registered as a watchdog context provider, so a stall
dump carries the memory picture of the moment the step wedged.

jax is imported lazily: the obs package must stay importable without it.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from bigdl_tpu.obs import trace
from bigdl_tpu.obs import watchdog as obs_watchdog
from bigdl_tpu.obs.registry import registry

#: memory_stats() keys accepted for "bytes in use" / "peak" / "limit" —
#: backends disagree on naming (PJRT: bytes_in_use / peak_bytes_in_use /
#: bytes_limit; some report num_allocs only, which is useless here)
_IN_USE_KEYS = ("bytes_in_use",)
_PEAK_KEYS = ("peak_bytes_in_use", "largest_alloc_size")
_LIMIT_KEYS = ("bytes_limit", "bytes_reservable_limit")

_lock = threading.Lock()
_last_sample: Optional[list] = None   # latest sample_device_memory() result
_MONITOR: Optional["DeviceMonitor"] = None
_MONITOR_LOCK = threading.Lock()


def _pick(stats: dict, keys) -> Optional[int]:
    for k in keys:
        v = stats.get(k)
        if isinstance(v, (int, float)) and v >= 0:
            return int(v)
    return None


def sample_device_memory(publish: bool = True) -> list:
    """Poll ``memory_stats()`` on every local device.

    Returns ``[{"id", "kind", "bytes_in_use", "peak_bytes", "bytes_limit",
    "headroom"}]`` — entries only for devices that actually report; an empty
    list when the backend won't say (CPU without allocator stats). With
    ``publish`` the aggregate and per-device registry gauges are updated.
    """
    devices = []
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return []
    out = []
    for d in devices:
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        if not st:
            continue
        in_use = _pick(st, _IN_USE_KEYS)
        if in_use is None:
            continue
        peak = _pick(st, _PEAK_KEYS)
        limit = _pick(st, _LIMIT_KEYS)
        headroom = (max(0.0, 1.0 - in_use / limit)
                    if limit else None)
        out.append({"id": int(getattr(d, "id", len(out))),
                    "kind": getattr(d, "device_kind", "?"),
                    "bytes_in_use": in_use, "peak_bytes": peak,
                    "bytes_limit": limit, "headroom": headroom})
    global _last_sample
    with _lock:
        _last_sample = out
    if publish and out:
        registry.gauge("device/hbm_bytes_in_use").set(
            sum(e["bytes_in_use"] for e in out))
        peaks = [e["peak_bytes"] for e in out if e["peak_bytes"] is not None]
        if peaks:
            registry.gauge("device/hbm_peak_bytes").set(sum(peaks))
        rooms = [e["headroom"] for e in out if e["headroom"] is not None]
        if rooms:
            registry.gauge("device/hbm_headroom").set(min(rooms))
        for e in out:
            registry.gauge(
                "device/%d/hbm_bytes_in_use" % e["id"]).set(e["bytes_in_use"])
    return out


def last_sample() -> Optional[list]:
    """The most recent poll (None before the first), for /statusz and the
    watchdog context provider."""
    with _lock:
        return _last_sample


def live_buffer_census(publish: bool = True) -> dict:
    """Count + bytes of every live jax array, split by dtype:
    ``{"count", "bytes", "by_dtype": {dtype: {"count", "bytes"}}}``.
    Empty-shaped dict (zero counts) when jax is absent."""
    out = {"count": 0, "bytes": 0, "by_dtype": {}}
    try:
        import jax
        arrays = jax.live_arrays()
    except Exception:
        return out
    for a in arrays:
        try:
            nbytes = int(a.dtype.itemsize)
            for dim in a.shape:
                nbytes *= int(dim)
            key = str(a.dtype)
        except Exception:
            continue
        out["count"] += 1
        out["bytes"] += nbytes
        slot = out["by_dtype"].setdefault(key, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += nbytes
    if publish:
        registry.gauge("device/live_buffers").set(out["count"])
        registry.gauge("device/live_buffer_bytes").set(out["bytes"])
    return out


def program_memory(fn, *args) -> Optional[dict]:
    """Per-program memory attribution from XLA ``memory_analysis()``:
    ``{"temp_bytes", "argument_bytes", "output_bytes",
    "generated_code_bytes"}`` (fields the backend reports; None when it
    reports nothing). ``fn`` is a jitted callable; only arg shapes/dtypes
    are used (ShapeDtypeStruct avals — donation-safe, same contract as
    :func:`~bigdl_tpu.obs.mfu.program_flops`). Costs one compile: callers
    memoize per program-cache key."""
    try:
        from bigdl_tpu.obs.mfu import avals_of
        ma = fn.lower(*avals_of(args)).compile().memory_analysis()
        if ma is None:
            return None
        out = {}
        for field, attr in (("temp_bytes", "temp_size_in_bytes"),
                            ("argument_bytes", "argument_size_in_bytes"),
                            ("output_bytes", "output_size_in_bytes"),
                            ("generated_code_bytes",
                             "generated_code_size_in_bytes")):
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)) and v >= 0:
                out[field] = int(v)
        return out or None
    except Exception:
        return None


def _pressure_pct() -> Optional[float]:
    raw = os.environ.get("BIGDL_HBM_PRESSURE_PCT", "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if 0 < v < 100 else None


class DeviceMonitor:
    """Daemon polling device memory + live buffers into registry gauges.

    One instance per process (:func:`start_from_env`). Each poll also
    mirrors serving occupancy from registered engines — the paged-KV
    ``free_page_ratio`` (worst engine), total page-pool and prefix-pool
    bytes — into ``serve/*`` registry gauges so memory and occupancy sit
    on the same scrape. Below ``BIGDL_HBM_PRESSURE_PCT`` percent headroom
    an ``hbm_pressure`` event fires (once per excursion, re-armed when
    headroom recovers)."""

    def __init__(self, interval_s: float = 5.0,
                 pressure_pct: Optional[float] = None):
        self.interval_s = max(float(interval_s), 0.05)
        self.pressure_pct = pressure_pct
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._in_pressure = False
        self.polls = 0

    # one poll, callable synchronously from tests and from the daemon loop
    def poll_once(self) -> None:
        sample = sample_device_memory()
        live_buffer_census()
        self._mirror_serving()
        self.polls += 1
        self._check_pressure(sample)

    def _mirror_serving(self) -> None:
        from bigdl_tpu.obs import exporter
        ratios, page_bytes, prefix_bytes = [], 0, 0
        for eng in exporter.engines():
            try:
                st = eng.stats()
            except Exception:
                continue
            r = st.get("free_page_ratio")
            if isinstance(r, (int, float)):
                ratios.append(float(r))
            pb = st.get("page_pool_bytes")
            if isinstance(pb, (int, float)):
                page_bytes += int(pb)
            xb = st.get("prefix_bytes")
            if isinstance(xb, (int, float)):
                prefix_bytes += int(xb)
        if ratios:
            registry.gauge("serve/free_page_ratio").set(min(ratios))
        if page_bytes:
            registry.gauge("serve/page_pool_bytes").set(page_bytes)
        if prefix_bytes:
            registry.gauge("serve/prefix_pool_bytes").set(prefix_bytes)

    def _check_pressure(self, sample: list) -> None:
        pct = self.pressure_pct
        if pct is None:
            return
        rooms = [e["headroom"] for e in sample
                 if e.get("headroom") is not None]
        if not rooms:
            return
        worst = min(rooms)
        if worst * 100.0 < pct:
            if not self._in_pressure:
                self._in_pressure = True
                registry.counter("device/hbm_pressure_events").inc()
                trace.event("hbm_pressure", headroom=round(worst, 4),
                            threshold_pct=pct, devices=sample)
                from bigdl_tpu.utils.robustness import events
                events.record("hbm_pressure", headroom=round(worst, 4),
                              threshold_pct=pct)
        else:
            self._in_pressure = False

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:
                pass  # a flaky backend must never kill the monitor

    def start(self) -> "DeviceMonitor":
        if self._thread is None:
            self.poll_once()   # gauges exist before the first interval
            self._thread = threading.Thread(
                target=self._run, name="bigdl-device-monitor", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def _watchdog_context() -> dict:
    """Latest device-memory picture for watchdog stall dumps (empty when
    the backend reports nothing — absent, not fabricated)."""
    sample = last_sample()
    if not sample:
        return {}
    return {"device_memory": sample}


def monitor() -> Optional[DeviceMonitor]:
    return _MONITOR


def start_from_env(interval_s: Optional[float] = None) -> Optional[DeviceMonitor]:
    """Start (once per process) the monitor — always-on like the MFU rail:
    the daemon costs one memory_stats() + live_arrays() round per interval.
    Interval from ``BIGDL_DEVICE_POLL_S`` (default 5s; ``0`` disables);
    pressure threshold from ``BIGDL_HBM_PRESSURE_PCT`` (unset = no
    pressure events)."""
    global _MONITOR
    with _MONITOR_LOCK:
        if _MONITOR is not None:
            return _MONITOR
        if interval_s is None:
            try:
                interval_s = float(
                    os.environ.get("BIGDL_DEVICE_POLL_S", "5") or "5")
            except ValueError:
                interval_s = 5.0
            if interval_s <= 0:
                return None
        _MONITOR = DeviceMonitor(interval_s,
                                 pressure_pct=_pressure_pct()).start()
        obs_watchdog.add_context_provider(_watchdog_context)
        return _MONITOR


def stats() -> dict:
    """Device-memory block for /statusz and bench records."""
    return {"devices": last_sample() or [],
            "live_buffers": live_buffer_census(publish=False)}


def reset() -> None:
    """Test isolation: stop the daemon, forget the last sample."""
    global _MONITOR, _last_sample
    with _MONITOR_LOCK:
        if _MONITOR is not None:
            _MONITOR.stop()
        _MONITOR = None
    with _lock:
        _last_sample = None
