"""Unified observability subsystem — one rail for traces, metrics, hangs.

The framework grew four disjoint observability rails (``optim/metrics.py``
phase timings, ``dataset/profiling.py`` feed-stage stats,
``utils/robustness.py`` recovery events, and the ``TrainSummary`` curves),
each with its own accumulator and consumer glue. This package unifies them:

- :mod:`bigdl_tpu.obs.trace` — thread-aware span tracer with Chrome-trace /
  Perfetto JSON export and a structured JSONL event log, gated by
  ``BIGDL_TRACE`` with a near-zero-cost disabled path;
- :mod:`bigdl_tpu.obs.registry` — process-wide metric registry (counters /
  gauges / histograms with p50/p95/p99) that the legacy rails publish
  through, so every consumer reads ONE source;
- :mod:`bigdl_tpu.obs.watchdog` — hang watchdog: a step/window exceeding
  N× the rolling median (or a hard ``BIGDL_WATCHDOG_S`` timeout) dumps all
  Python thread stacks plus the open-span tree to stderr and the JSONL log;
- :mod:`bigdl_tpu.obs.report` — the end-of-run report (step-time
  percentiles, feed-stage attribution, robustness counters, span totals),
  rendered identically by the trainer and ``bigdl-tpu diag``;
- :mod:`bigdl_tpu.obs.exporter` — live ``/metrics`` (Prometheus text) +
  ``/healthz`` + ``/statusz`` endpoint on ``BIGDL_METRICS_PORT`` (stdlib
  http.server; zero-alloc no-op when the port is unset);
- :mod:`bigdl_tpu.obs.mfu` — always-on MFU accounting: per-compiled-program
  XLA cost-analysis FLOPs feeding live ``train/mfu`` and
  ``serve/model_flops_per_sec`` gauges against a peak-FLOPs table;
- :mod:`bigdl_tpu.obs.slo` — SLO monitor over windowed registry percentiles
  (p99 TTFT, feed-stall rate, throughput floor) whose breach events flip
  serving health to ``degraded``;
- :mod:`bigdl_tpu.obs.device` — device-memory accounting: HBM gauges from
  ``memory_stats()``, live-buffer census, per-program ``memory_analysis()``
  attribution, ``hbm_pressure`` events (``BIGDL_HBM_PRESSURE_PCT``);
- :mod:`bigdl_tpu.obs.cluster` — multi-host aggregation: per-process
  snapshot spools (``BIGDL_OBS_SPOOL_DIR``) merged into one ``/metrics``
  scrape with ``{host=}`` labels;
- :mod:`bigdl_tpu.obs.access_log` — opt-in structured request log
  (``BIGDL_ACCESS_LOG``) with the ``to_bdlrec`` flywheel converter.

Dependency-free by design: nothing here imports ``optim``/``dataset``/
``nn``, so every layer of the framework may publish into it (``mfu``
imports jax lazily; ``slo`` reaches the robustness event rail lazily).
"""

from __future__ import annotations

import os

from bigdl_tpu.obs import access_log, cluster, device, exporter, mfu, \
    registry, report, slo, trace, watchdog
from bigdl_tpu.obs.registry import registry as metric_registry


def describe_config() -> str:
    """One human-readable block of the active observability configuration
    (printed by the CLI at startup when ``BIGDL_TRACE`` is set)."""
    trace.configure_from_env()
    wd = os.environ.get("BIGDL_WATCHDOG_S", "")
    lines = [
        "observability:",
        f"  trace      = {'on' if trace.enabled() else 'off'}"
        f" (BIGDL_TRACE={os.environ.get('BIGDL_TRACE', '')!r})",
        f"  trace dir  = {trace.trace_dir() or '-'}",
        f"  chrome out = {trace.chrome_path() or '-'}",
        f"  event log  = {trace.jsonl_path() or '-'}"
        f" (BIGDL_OBS_LOG={os.environ.get('BIGDL_OBS_LOG', '')!r})",
        f"  watchdog   = {wd + 's hard timeout' if wd else 'off'}"
        f" (BIGDL_WATCHDOG_S)",
        f"  metrics    = "
        f"{'port ' + os.environ.get('BIGDL_METRICS_PORT') if os.environ.get('BIGDL_METRICS_PORT', '').strip() else 'off'}"
        f" (BIGDL_METRICS_PORT)",
    ]
    return "\n".join(lines)


__all__ = ["trace", "registry", "watchdog", "report", "exporter", "mfu",
           "slo", "device", "cluster", "access_log", "metric_registry",
           "describe_config"]
