"""Multi-host metric aggregation — spool snapshots, merge on the scraper.

PR 11's exporter is strictly per-process: under ``jax.distributed`` each
host runs its own registry and nothing ever joins them, so a multi-host
scaling claim needs N scrapes of N processes. This module makes one pane:

- every process with ``BIGDL_OBS_SPOOL_DIR`` set runs a :class:`SpoolWriter`
  daemon appending periodic registry snapshots to its own
  ``host-<id>.jsonl`` in that (shared) directory. Each line is
  ``<json>\\t<crc32 hex>`` — the utils/file.py integrity discipline in
  newline form — and every append lands via write+flush on an O_APPEND
  handle, so a torn tail line is detectable and skippable, never fatal.
  The file is compacted in place (atomic rewrite of the last line) when it
  outgrows ``_MAX_SPOOL_BYTES``: the merge only ever wants the newest
  snapshot, the history is a crash-forensics convenience.
- the exporter (any process, in practice process 0 — the one operators
  scrape) merges the spools: :func:`read_spools` returns the newest valid
  snapshot per host, stamped ``stale`` when its age exceeds
  ``BIGDL_OBS_STALE_S`` (a dead host degrades to a stamped row, the merge
  and the scrape never fail), and ``render_host_lines`` turns them into
  Prometheus rows carrying a ``{host="<id>"}`` label.

Spool writes run through the ``obs_spool_write`` fault site: a scripted
(or real) write failure flips the writer to local-only mode with a loud
``obs_spool_degraded`` event — metrics keep flowing, only the aggregation
narrows.

Host identity: ``BIGDL_OBS_HOST_ID`` if set, else ``jax.process_index()``
when jax.distributed is live, else the OS pid. jax stays a lazy import.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Optional

from bigdl_tpu.obs import trace
from bigdl_tpu.obs.registry import registry

#: compact the per-host spool when it outgrows this (the merge reads only
#: the newest line; older lines are forensics, not state)
_MAX_SPOOL_BYTES = 256 * 1024

_WRITER: Optional["SpoolWriter"] = None
_WRITER_LOCK = threading.Lock()


def host_id() -> str:
    """Stable identity for this process's spool and its ``{host=}`` label."""
    raw = os.environ.get("BIGDL_OBS_HOST_ID", "").strip()
    if raw:
        return raw
    try:
        import jax
        if jax.process_count() > 1:
            return str(jax.process_index())
    except Exception:
        pass
    return str(os.getpid())


def spool_dir() -> Optional[str]:
    raw = os.environ.get("BIGDL_OBS_SPOOL_DIR", "").strip()
    return raw or None


def stale_s() -> float:
    try:
        return float(os.environ.get("BIGDL_OBS_STALE_S", "15") or "15")
    except ValueError:
        return 15.0


def _encode_line(rec: dict) -> bytes:
    body = json.dumps(rec, separators=(",", ":"), default=str).encode()
    return body + b"\t%08x\n" % zlib.crc32(body)


def _decode_line(line: bytes) -> Optional[dict]:
    """One spool line → record, or None for a torn/corrupt line."""
    line = line.rstrip(b"\n")
    body, sep, crc = line.rpartition(b"\t")
    if not sep or len(crc) != 8:
        return None
    try:
        if zlib.crc32(body) != int(crc, 16):
            return None
        return json.loads(body)
    except (ValueError, UnicodeDecodeError):
        return None


class SpoolWriter:
    """Daemon appending this process's registry snapshots to its spool."""

    def __init__(self, directory: str, host: Optional[str] = None,
                 interval_s: float = 2.0):
        self.directory = directory
        self.host = host if host is not None else host_id()
        self.path = os.path.join(directory, "host-%s.jsonl" % self.host)
        self.interval_s = max(float(interval_s), 0.05)
        self.degraded = False
        self.writes = 0
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def write_once(self) -> bool:
        """Append one snapshot line now. Returns False (and degrades to
        local-only mode, loudly, exactly once) on any write failure —
        telemetry must never crash the process it observes."""
        if self.degraded:
            return False
        from bigdl_tpu.utils.faults import SITE_OBS_SPOOL_WRITE, fault_point
        self._seq += 1
        rec = {"host": self.host, "ts": time.time(), "seq": self._seq,
               "snapshot": registry.snapshot()}
        try:
            fault_point(SITE_OBS_SPOOL_WRITE)
            os.makedirs(self.directory, exist_ok=True)
            data = _encode_line(rec)
            if (os.path.exists(self.path)
                    and os.path.getsize(self.path) > _MAX_SPOOL_BYTES):
                # compact: atomically rewrite the spool as just this line
                tmp = self.path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                    f.flush()
                os.replace(tmp, self.path)
            else:
                with open(self.path, "ab") as f:
                    f.write(data)
                    f.flush()
            self.writes += 1
            return True
        except Exception as exc:
            self.degraded = True
            registry.counter("obs/spool_write_failures").inc()
            trace.event("obs_spool_degraded", host=self.host,
                        path=self.path, error=str(exc))
            from bigdl_tpu.utils.robustness import events
            events.record("obs_spool_degraded", host=self.host,
                          error=str(exc))
            import logging
            logging.getLogger("bigdl_tpu.obs").error(
                "metric spool write to %s failed (%s); this host degrades "
                "to local-only metrics", self.path, exc)
            return False

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.write_once()
            if self.degraded:
                return

    def start(self) -> "SpoolWriter":
        if self._thread is None:
            self.write_once()
            self._thread = threading.Thread(
                target=self._run, name="bigdl-obs-spool", daemon=True)
            self._thread.start()
        return self

    def stop(self, final_write: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_write:
            self.write_once()


def read_spools(directory: Optional[str] = None,
                stale_after_s: Optional[float] = None) -> dict:
    """Newest valid snapshot per host:
    ``{host: {"snapshot", "ts", "seq", "age_s", "stale"}}``.

    A file whose every line is torn is skipped; a host whose newest
    snapshot is older than ``stale_after_s`` is STAMPED stale but still
    returned — the merge degrades, it never throws."""
    directory = directory if directory is not None else spool_dir()
    if not directory or not os.path.isdir(directory):
        return {}
    if stale_after_s is None:
        stale_after_s = stale_s()
    out = {}
    now = time.time()
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return {}
    for name in names:
        if not (name.startswith("host-") and name.endswith(".jsonl")):
            continue
        path = os.path.join(directory, name)
        rec = None
        try:
            with open(path, "rb") as f:
                for line in f:
                    decoded = _decode_line(line)
                    if decoded is not None and "snapshot" in decoded:
                        rec = decoded   # last valid line wins
        except OSError:
            continue
        if rec is None:
            continue
        host = str(rec.get("host", name[len("host-"):-len(".jsonl")]))
        age = max(0.0, now - float(rec.get("ts", 0.0)))
        out[host] = {"snapshot": rec["snapshot"], "ts": rec.get("ts"),
                     "seq": rec.get("seq"), "age_s": round(age, 3),
                     "stale": age > stale_after_s}
    return out


def render_host_lines(hosts: Optional[dict] = None) -> list:
    """Prometheus text rows for every spooled host, each series labelled
    ``{host="<id>"}``, plus ``bigdl_obs_host_up`` (0 = stale-stamped) and
    ``bigdl_obs_host_age_seconds`` liveness rows. Returns ``[]`` when no
    spool dir is configured — the exporter's zero-cost default."""
    from bigdl_tpu.obs.exporter import _fmt, _san
    if hosts is None:
        hosts = read_spools()
    if not hosts:
        return []
    lines = []
    for host in sorted(hosts):
        info = hosts[host]
        up = 0 if info["stale"] else 1
        lines.append('bigdl_obs_host_up{host="%s"} %d' % (host, up))
        lines.append('bigdl_obs_host_age_seconds{host="%s"} %s'
                     % (host, _fmt(info["age_s"])))
        snap = info["snapshot"] or {}
        for name, v in sorted((snap.get("counters") or {}).items()):
            lines.append('%s_total{host="%s"} %s'
                         % (_san(name), host, _fmt(v)))
        for name, v in sorted((snap.get("gauges") or {}).items()):
            if v is None:
                continue
            lines.append('%s{host="%s"} %s' % (_san(name), host, _fmt(v)))
        for name, h in sorted((snap.get("histograms") or {}).items()):
            m = _san(name)
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                if h.get(key) is not None:
                    lines.append('%s{host="%s",quantile="%s"} %s'
                                 % (m, host, q, _fmt(h[key])))
            lines.append('%s_sum{host="%s"} %s' % (m, host, _fmt(h["total"])))
            lines.append('%s_count{host="%s"} %s'
                         % (m, host, _fmt(h["count"])))
    return lines


def host_table(hosts: Optional[dict] = None) -> dict:
    """Per-host summary for /statusz: liveness + headline gauges."""
    if hosts is None:
        hosts = read_spools()
    table = {}
    for host, info in sorted(hosts.items()):
        gauges = (info["snapshot"] or {}).get("gauges") or {}
        table[host] = {
            "stale": info["stale"], "age_s": info["age_s"],
            "seq": info["seq"],
            "throughput": gauges.get("train/throughput"),
            "mfu": gauges.get("train/mfu"),
            "hbm_bytes_in_use": gauges.get("device/hbm_bytes_in_use"),
            "hbm_headroom": gauges.get("device/hbm_headroom"),
        }
    return table


def writer() -> Optional[SpoolWriter]:
    return _WRITER


def start_from_env() -> Optional[SpoolWriter]:
    """Start (once per process) the spool writer when
    ``BIGDL_OBS_SPOOL_DIR`` is set; None — allocating nothing — when not.
    Interval from ``BIGDL_OBS_SPOOL_S`` (default 2s)."""
    d = spool_dir()
    if not d:
        return None
    global _WRITER
    with _WRITER_LOCK:
        if _WRITER is not None:
            return _WRITER
        try:
            interval = float(os.environ.get("BIGDL_OBS_SPOOL_S", "2") or "2")
        except ValueError:
            interval = 2.0
        _WRITER = SpoolWriter(d, interval_s=interval).start()
        return _WRITER


def reset() -> None:
    """Test isolation: stop and forget the active writer."""
    global _WRITER
    with _WRITER_LOCK:
        if _WRITER is not None:
            _WRITER.stop(final_write=False)
        _WRITER = None
