"""Live operational endpoint — /metrics, /healthz, /statusz over stdlib http.

One daemon ``ThreadingHTTPServer`` (no third-party deps) turns the passive
in-process telemetry rails into a scrapeable plane:

- ``/metrics`` — Prometheus text exposition rendered from ONE
  :meth:`MetricRegistry.snapshot` (counters as ``_total``, histograms as
  summaries with p50/p95/p99 ``quantile`` labels + ``_sum``/``_count``,
  gauges as-is), plus per-tenant serving gauges labelled
  ``{tenant="<engine name>"}`` fed live from each registered
  ``ServingEngine.stats()`` — the feed the fleet router dispatches off —
  and, per registered :class:`~bigdl_tpu.serving.fleet.FleetRouter`,
  router counters ``{fleet=...}`` plus per-replica load/health gauges
  ``{fleet=...,replica=...}``.
- ``/healthz`` — the serving health state machine per engine, watchdog arm
  state (armed / disarmed, dump count), SLO breach state, and per-fleet
  replica health. HTTP 503 when any engine is ``dead``, 200 otherwise —
  load-balancer-pollable. A dead REPLICA whose fleet still has a healthy
  peer degrades the fleet instead of 503ing the process: the router is
  routing around it, which is the design working, not an outage.
- ``/statusz`` — JSON status: the latest run report (published by the
  trainer at end of run), MFU accounting, full engine ledgers, SLO state,
  the device-memory picture, and — under ``BIGDL_OBS_SPOOL_DIR`` — a
  per-host table merged from the cluster spools (``obs/cluster.py``).
- ``/profilez?seconds=N`` — on-demand ``jax.profiler.trace`` capture into
  ``BIGDL_TRACE_DIR``; responds with the artifact path when the capture
  completes, 409 while another capture runs (``bigdl-tpu prof`` is the
  CLI form).

Under ``BIGDL_OBS_SPOOL_DIR`` the ``/metrics`` body additionally carries
every spooled host's snapshot with a ``{host="<id>"}`` label — one scrape
of process 0 sees the whole job (stale hosts are stamped
``bigdl_obs_host_up 0``, never dropped).

The exporter is strictly opt-in: :func:`start_from_env` returns ``None``
without allocating ANYTHING when ``BIGDL_METRICS_PORT`` is unset — the
zero-alloc pin is :data:`_SERVERS_CREATED`, mirroring the tracer's
``_SPANS_CREATED``. Port ``0`` binds an ephemeral port (tests).

Engines register themselves (``register_engine`` on start, ``unregister``
on supervisor exit); ``SnapshotServer`` registers all its tenants up front
so the per-tenant rows exist before first traffic. Registration holds weak
references only — a dropped engine disappears from the endpoint.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
import urllib.parse
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from bigdl_tpu.obs import cluster, mfu
from bigdl_tpu.obs import watchdog as obs_watchdog
from bigdl_tpu.obs.registry import registry

#: exporter instances ever constructed — pins the zero-alloc disabled path
#: (start_from_env with no BIGDL_METRICS_PORT must leave this untouched)
_SERVERS_CREATED = 0

_ENGINES: "weakref.WeakSet" = weakref.WeakSet()
_FLEETS: "weakref.WeakSet" = weakref.WeakSet()
_STATUS: dict = {}
_STATUS_LOCK = threading.Lock()
_ACTIVE: Optional["MetricsExporter"] = None
_ACTIVE_LOCK = threading.Lock()

#: mirror of the serving health state machine (obs must not import serving)
_HEALTH_CODE = {"starting": 0, "ready": 1, "degraded": 2, "draining": 3,
                "dead": 4}

#: numeric ServingEngine.stats() fields exported per tenant
_TENANT_FIELDS = ("backlog", "queued", "active_slots", "submitted",
                  "completed", "timeouts", "shed", "respawns",
                  "poisoned_slots", "slot_recycles", "decode_tps",
                  "queue_depth", "decode_rate", "est_wait_ms",
                  "prefix_hits", "prefix_tokens_saved", "prefix_bytes",
                  "spec_acceptance", "model_version", "pages_used",
                  "pages_free", "free_page_ratio", "page_evictions")

#: numeric per-replica fields exported under {fleet=...,replica=...} — the
#: router's own dispatch signal, scrapeable by external load balancers
_REPLICA_FIELDS = ("queue_depth", "active_slots", "est_wait_ms",
                   "decode_rate", "completed", "shed", "pages_free",
                   "free_page_ratio", "prefill_inflight")

#: numeric FleetRouter.stats() counters exported under {fleet=...}
_FLEET_FIELDS = ("healthy_replicas", "dispatched", "retries",
                 "replica_downs", "rejected", "handoffs",
                 "handoff_failures")


def register_engine(engine) -> None:
    """Expose an engine's stats() on /metrics and /healthz (weakly held)."""
    _ENGINES.add(engine)


def unregister_engine(engine) -> None:
    _ENGINES.discard(engine)


def engines() -> list:
    return list(_ENGINES)


def register_fleet(fleet) -> None:
    """Expose a FleetRouter's stats() — router counters and per-replica
    gauges — on /metrics, /healthz, /statusz (weakly held)."""
    _FLEETS.add(fleet)


def unregister_fleet(fleet) -> None:
    _FLEETS.discard(fleet)


def fleets() -> list:
    return list(_FLEETS)


def publish_status(key: str, value) -> None:
    """Publish a JSON-able blob under /statusz (e.g. the end-of-run report)."""
    with _STATUS_LOCK:
        _STATUS[key] = value


def _san(name: str) -> str:
    """Registry name → Prometheus metric name: train/step_wall →
    bigdl_train_step_wall."""
    return "bigdl_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def render_metrics() -> str:
    """The /metrics body: one registry snapshot + per-tenant engine gauges."""
    snap = registry.snapshot()
    lines = []
    for name, v in sorted(snap["counters"].items()):
        m = _san(name) + "_total"
        lines.append("# TYPE %s counter" % m)
        lines.append("%s %s" % (m, _fmt(v)))
    for name, v in sorted(snap["gauges"].items()):
        m = _san(name)
        lines.append("# TYPE %s gauge" % m)
        lines.append("%s %s" % (m, _fmt(v)))
    for name, h in sorted(snap["histograms"].items()):
        m = _san(name)
        lines.append("# TYPE %s summary" % m)
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            if h.get(key) is not None:
                lines.append('%s{quantile="%s"} %s' % (m, q, _fmt(h[key])))
        lines.append("%s_sum %s" % (m, _fmt(h["total"])))
        lines.append("%s_count %s" % (m, _fmt(h["count"])))
    # per-tenant serving gauges: group by field so each metric name carries
    # exactly one TYPE line with all tenant label rows under it
    per_field: dict = {}
    health_rows = []
    for eng in engines():
        try:
            st = eng.stats()
        except Exception:
            continue
        tenant = str(st.get("name", "?"))
        for field in _TENANT_FIELDS:
            v = st.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                per_field.setdefault(field, []).append((tenant, v))
        health_rows.append((tenant, _HEALTH_CODE.get(st.get("health"), -1),
                            bool(st.get("slo_degraded"))))
    for field in sorted(per_field):
        m = "bigdl_serving_tenant_" + field
        lines.append("# TYPE %s gauge" % m)
        for tenant, v in per_field[field]:
            lines.append('%s{tenant="%s"} %s' % (m, tenant, _fmt(v)))
    if health_rows:
        lines.append("# TYPE bigdl_serving_tenant_health gauge")
        for tenant, code, _ in health_rows:
            lines.append('bigdl_serving_tenant_health{tenant="%s"} %d'
                         % (tenant, code))
        lines.append("# TYPE bigdl_serving_tenant_slo_degraded gauge")
        for tenant, _, slo in health_rows:
            lines.append('bigdl_serving_tenant_slo_degraded{tenant="%s"} %d'
                         % (tenant, 1 if slo else 0))
    # fleet router counters {fleet=...} + per-replica gauges
    # {fleet=...,replica=...}: same group-by-field layout as tenants
    fleet_rows: dict = {}
    rep_rows: dict = {}
    rep_health: list = []
    for fl in fleets():
        try:
            st = fl.stats()
        except Exception:
            continue
        fname = str(st.get("name", "?"))
        for field in _FLEET_FIELDS:
            v = st.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                fleet_rows.setdefault(field, []).append((fname, v))
        for rname, rst in sorted(st.get("replicas", {}).items()):
            for field in _REPLICA_FIELDS:
                v = rst.get(field)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    rep_rows.setdefault(field, []).append((fname, rname, v))
            rep_health.append(
                (fname, rname, _HEALTH_CODE.get(rst.get("health"), -1)))
    for field in sorted(fleet_rows):
        m = "bigdl_fleet_" + field
        lines.append("# TYPE %s gauge" % m)
        for fname, v in fleet_rows[field]:
            lines.append('%s{fleet="%s"} %s' % (m, fname, _fmt(v)))
    for field in sorted(rep_rows):
        m = "bigdl_fleet_replica_" + field
        lines.append("# TYPE %s gauge" % m)
        for fname, rname, v in rep_rows[field]:
            lines.append('%s{fleet="%s",replica="%s"} %s'
                         % (m, fname, rname, _fmt(v)))
    if rep_health:
        lines.append("# TYPE bigdl_fleet_replica_health gauge")
        for fname, rname, code in rep_health:
            lines.append('bigdl_fleet_replica_health{fleet="%s",'
                         'replica="%s"} %d' % (fname, rname, code))
    # cluster merge: every spooled host's snapshot rides the same scrape
    # with a {host=} label ([] when BIGDL_OBS_SPOOL_DIR is unset — and a
    # corrupt/stale spool degrades to a stamped row, never a failed scrape)
    try:
        lines.extend(cluster.render_host_lines())
    except Exception:
        pass
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------- profiler capture
class ProfilerBusy(RuntimeError):
    """A /profilez capture is already running (HTTP 409)."""


_PROFILE_LOCK = threading.Lock()
_PROFILE_BUSY = False
_PROFILE_SEQ = 0
#: upper bound on one capture (a typo'd ?seconds= must not wedge the server
#: thread pool for an hour)
_PROFILE_MAX_S = 120.0


def profilez_capture(seconds: float) -> str:
    """Run one ``jax.profiler.trace`` capture of ``seconds`` and return the
    artifact directory (under ``BIGDL_TRACE_DIR``, else a tmpdir). Raises
    :class:`ProfilerBusy` while another capture runs — captures serialize,
    they never stack."""
    global _PROFILE_BUSY, _PROFILE_SEQ
    seconds = min(max(float(seconds), 0.01), _PROFILE_MAX_S)
    with _PROFILE_LOCK:
        if _PROFILE_BUSY:
            raise ProfilerBusy("a profiler capture is already running")
        _PROFILE_BUSY = True
        _PROFILE_SEQ += 1
        seq = _PROFILE_SEQ
    try:
        from bigdl_tpu.utils.faults import SITE_PROFILEZ_CAPTURE, fault_point
        fault_point(SITE_PROFILEZ_CAPTURE)
        base = os.environ.get("BIGDL_TRACE_DIR", "").strip() or os.path.join(
            tempfile.gettempdir(), "bigdl-profilez")
        out = os.path.join(base, "profilez-%d-%d" % (os.getpid(), seq))
        os.makedirs(out, exist_ok=True)
        import jax
        jax.profiler.start_trace(out)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        registry.counter("obs/profilez_captures").inc()
        return out
    finally:
        with _PROFILE_LOCK:
            _PROFILE_BUSY = False


def _render_profilez(path: str) -> "tuple[int, bytes, str]":
    """(status, body, content-type) for GET /profilez?seconds=N."""
    query = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
    try:
        seconds = float(query.get("seconds", ["1"])[0])
    except ValueError:
        return (400, b'{"error": "seconds must be a number"}\n',
                "application/json")
    try:
        artifact = profilez_capture(seconds)
    except ProfilerBusy as exc:
        return (409, json.dumps({"error": str(exc)}).encode() + b"\n",
                "application/json")
    except Exception as exc:
        # fault-injected or real capture failure: loud, but the endpoint
        # (and the process it observes) keeps serving
        registry.counter("obs/profilez_failures").inc()
        return (503, json.dumps(
            {"error": "profiler capture failed: %s" % exc}).encode() + b"\n",
            "application/json")
    body = json.dumps({"artifact": artifact,
                       "seconds": min(max(seconds, 0.01), _PROFILE_MAX_S)})
    return 200, body.encode() + b"\n", "application/json"


def parse_metrics(text: str) -> dict:
    """Prometheus text → ``{"name" or 'name{labels}': float}``. The inverse
    of :func:`render_metrics` for the round-trip test and ``cli top``."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        out[key] = float(val)
    return out


def render_healthz() -> "tuple[int, dict]":
    """(http status, payload) for /healthz. A dead engine 503s the process
    UNLESS it is a fleet replica with a healthy peer — the router is
    routing around it (the fleet block below shows which), so the process
    still serves."""
    engs = {}
    for eng in engines():
        try:
            st = eng.stats()
        except Exception:
            continue
        engs[str(st.get("name", "?"))] = {
            "health": st.get("health"),
            "backlog": st.get("backlog"),
            "active_slots": st.get("active_slots"),
            "slo_degraded": bool(st.get("slo_degraded")),
        }
    fleet_block = {}
    covered: set = set()   # replica names whose fleet still has a healthy peer
    for fl in fleets():
        try:
            st = fl.stats()
        except Exception:
            continue
        reps = {rn: rs.get("health")
                for rn, rs in st.get("replicas", {}).items()}
        healthy = int(st.get("healthy_replicas", 0))
        fleet_block[str(st.get("name", "?"))] = {
            "replicas": reps, "healthy_replicas": healthy}
        if healthy > 0:
            covered.update(reps)
    states = [(name, e["health"]) for name, e in engs.items()]
    # fleet replicas count even when the engine never started (lazy start
    # means it never self-registered) — the fleet block is the only place
    # such a replica's death is visible
    fleet_states = [(rn, h) for fb in fleet_block.values()
                    for rn, h in fb["replicas"].items()]
    status = "ok"
    code = 200
    if any(s == "dead" and name not in covered for name, s in states):
        status, code = "dead", 503
    elif any(s in ("dead", "degraded", "draining")
             for _, s in states + fleet_states):
        status = "degraded"
    watchdogs = [{"armed": wd.armed, "dumps": wd.dumps, "hard_s": wd.hard_s}
                 for wd in obs_watchdog.active_watchdogs()]
    with _STATUS_LOCK:
        slo = _STATUS.get("slo")
    return code, {"status": status, "engines": engs, "fleets": fleet_block,
                  "watchdogs": watchdogs, "slo": slo, "pid": os.getpid()}


def render_statusz() -> dict:
    """The /statusz payload: run report + MFU + engine ledgers + SLO."""
    with _STATUS_LOCK:
        status = dict(_STATUS)
    engs = {}
    for eng in engines():
        try:
            st = eng.stats()
        except Exception:
            continue
        engs[str(st.get("name", "?"))] = st
    fls = {}
    for fl in fleets():
        try:
            st = fl.stats()
        except Exception:
            continue
        fls[str(st.get("name", "?"))] = st
    hosts = {}
    try:
        hosts = cluster.host_table()
    except Exception:
        pass
    device_block = None
    try:
        from bigdl_tpu.obs import device as obs_device
        if obs_device.monitor() is not None or obs_device.last_sample():
            device_block = obs_device.stats()
    except Exception:
        pass
    return {"run_report": status.get("run_report"),
            "slo": status.get("slo"),
            "status": status,
            "mfu": mfu.stats(),
            "device_memory": device_block,
            "hosts": hosts,
            "engines": engs,
            "fleets": fls}


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (http.server API)
        try:
            if self.path.startswith("/metrics"):
                code = 200
                body = render_metrics().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.startswith("/healthz"):
                code, payload = render_healthz()
                body = json.dumps(payload, default=str).encode("utf-8")
                ctype = "application/json"
            elif self.path.startswith("/statusz"):
                code = 200
                body = json.dumps(render_statusz(),
                                  default=str).encode("utf-8")
                ctype = "application/json"
            elif self.path.startswith("/profilez"):
                code, body, ctype = _render_profilez(self.path)
            else:
                code, body = 404, b"not found\n"
                ctype = "text/plain"
        except Exception as exc:  # render must never kill the server thread
            code = 500
            body = ("exporter error: %s\n" % exc).encode("utf-8")
            ctype = "text/plain"
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper hung up mid-response

    def log_message(self, fmt, *args):  # silence per-request stderr lines
        pass


class MetricsExporter:
    """The endpoint server. ``port=0`` binds an ephemeral port (read back
    from :attr:`port` after :meth:`start`)."""

    def __init__(self, port: int, host: str = "0.0.0.0"):
        global _SERVERS_CREATED
        _SERVERS_CREATED += 1
        self.port = int(port)
        self.host = host
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsExporter":
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="bigdl-metrics",
            kwargs={"poll_interval": 0.2}, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None

    @property
    def url(self) -> str:
        return "http://127.0.0.1:%d" % self.port


def start_from_env() -> Optional[MetricsExporter]:
    """Start (once per process) the endpoint when ``BIGDL_METRICS_PORT`` is
    set; return ``None`` — allocating nothing — when it is not. Safe to call
    from every entry point (trainer, engine start, cli): idempotent."""
    raw = os.environ.get("BIGDL_METRICS_PORT", "").strip()
    if not raw:
        return None
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            return _ACTIVE
        try:
            port = int(raw)
        except ValueError:
            raise ValueError(
                "BIGDL_METRICS_PORT=%r is not an integer port" % raw)
        _ACTIVE = MetricsExporter(port).start()
        return _ACTIVE


def active() -> Optional[MetricsExporter]:
    return _ACTIVE


def reset() -> None:
    """Test isolation: stop the active server, drop registrations/status."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            _ACTIVE.stop()
        _ACTIVE = None
    _ENGINES.clear()
    _FLEETS.clear()
    with _STATUS_LOCK:
        _STATUS.clear()
