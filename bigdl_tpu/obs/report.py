"""The unified end-of-run report — one merged view of a training run.

:func:`build_report` reduces a registry snapshot delta (step-time
percentiles, feed-stage attribution, feed stalls, phase means, robustness
counters) plus the tracer's span totals into one plain-data dict; the
Optimizer stores it in ``state["run_report"]``, logs :func:`format_report`,
and appends it to the JSONL event log, where ``bigdl-tpu diag <jsonl>``
re-renders the IDENTICAL text — the on-call engineer reads the same report
whether the process is still alive or all that's left is the log.
"""

from __future__ import annotations

from typing import Optional


def _hist_delta(snap0: dict, snap1: dict, name: str) -> Optional[dict]:
    """Per-run (count, mean) delta for one histogram; window percentiles come
    from the newer snapshot (recent observations ≈ this run)."""
    h1 = snap1.get("histograms", {}).get(name)
    if h1 is None:
        return None
    h0 = snap0.get("histograms", {}).get(name, {})
    dc = h1["count"] - h0.get("count", 0)
    dt = h1["total"] - h0.get("total", 0.0)
    if dc <= 0:
        return None
    return {"count": dc, "mean": dt / dc,
            "p50": h1["p50"], "p95": h1["p95"], "p99": h1["p99"]}


def _counter_deltas(snap0: dict, snap1: dict, prefix: str) -> dict:
    out = {}
    c0 = snap0.get("counters", {})
    for name, n in snap1.get("counters", {}).items():
        if not name.startswith(prefix):
            continue
        d = n - c0.get(name, 0)
        if d > 0:
            out[name[len(prefix):]] = d
    return out


def build_report(snap0: dict, snap1: dict,
                 span_totals: Optional[dict] = None,
                 robustness: Optional[dict] = None,
                 watchdog_dumps: int = 0) -> dict:
    """Merge a run's registry delta + span totals into the report dict.
    Everything is JSON-plain (ints/floats/strings) so the dict survives the
    JSONL round trip bit-for-bit and ``diag`` re-renders identical text."""
    rep: dict = {}
    step = _hist_delta(snap0, snap1, "train/step_wall")
    if step is not None:
        rep["steps"] = {
            "count": step["count"],
            "mean_ms": round(step["mean"] * 1e3, 3),
            "p50_ms": round(step["p50"] * 1e3, 3),
            "p95_ms": round(step["p95"] * 1e3, 3),
            "p99_ms": round(step["p99"] * 1e3, 3),
        }
    thr = snap1.get("gauges", {}).get("train/throughput")
    if thr is not None:
        rep["throughput_records_per_sec"] = round(thr, 1)
    stages = {}
    for name in snap1.get("histograms", {}):
        if name.startswith("feed/"):
            stage = name[len("feed/"):]
        elif name == "phase/put_batch":
            stage = "h2d"
        else:
            continue
        d = _hist_delta(snap0, snap1, name)
        if d is not None:
            stages[stage] = {"mean_ms": round(d["mean"] * 1e3, 3),
                             "count": d["count"]}
    if stages:
        rep["feed_stages"] = stages
    stalls = _counter_deltas(snap0, snap1, "train/").get("feed_stall", 0)
    rep["feed_stalls"] = stalls
    phases = {}
    for name in snap1.get("histograms", {}):
        if not name.startswith("phase/"):
            continue
        d = _hist_delta(snap0, snap1, name)
        if d is not None:
            phases[name[len("phase/"):]] = round(d["mean"] * 1e3, 3)
    if phases:
        rep["phases_mean_ms"] = phases
    rob = robustness if robustness is not None \
        else _counter_deltas(snap0, snap1, "robustness/")
    if rob:
        rep["robustness"] = dict(rob)
    if span_totals:
        top = sorted(span_totals.items(),
                     key=lambda kv: kv[1]["total_ms"], reverse=True)[:12]
        rep["spans"] = {name: dict(v) for name, v in top}
    if watchdog_dumps:
        rep["watchdog_dumps"] = int(watchdog_dumps)
    return rep


def format_report(rep: dict) -> str:
    """Deterministic text rendering — the trainer's end-of-run log and the
    ``diag`` subcommand produce byte-identical output from the same dict."""
    lines = ["=== bigdl-tpu run report ==="]
    steps = rep.get("steps")
    if steps:
        lines.append(
            f"steps: {steps['count']}  "
            f"mean {steps['mean_ms']:.3f} ms  "
            f"p50 {steps['p50_ms']:.3f}  p95 {steps['p95_ms']:.3f}  "
            f"p99 {steps['p99_ms']:.3f}")
    thr = rep.get("throughput_records_per_sec")
    if thr is not None:
        lines.append(f"throughput: {thr:.1f} records/s")
    stages = rep.get("feed_stages")
    if stages:
        parts = ", ".join(
            f"{s} {d['mean_ms']:.3f} (x{d['count']})"
            for s, d in sorted(stages.items()))
        lines.append(f"feed stages (mean ms): {parts}")
    lines.append(f"feed stalls: {rep.get('feed_stalls', 0)}")
    phases = rep.get("phases_mean_ms")
    if phases:
        parts = ", ".join(f"{k} {v:.3f}" for k, v in sorted(phases.items()))
        lines.append(f"phases (mean ms): {parts}")
    rob = rep.get("robustness")
    if rob:
        parts = "; ".join(f"{k}={v}" for k, v in sorted(rob.items()))
        lines.append(f"robustness: {parts}")
    else:
        lines.append("robustness: no events")
    spans = rep.get("spans")
    if spans:
        parts = ", ".join(
            f"{name} {d['total_ms']:.1f}ms (x{d['count']})"
            for name, d in spans.items())
        lines.append(f"span totals: {parts}")
    if rep.get("watchdog_dumps"):
        lines.append(f"watchdog dumps: {rep['watchdog_dumps']}")
    return "\n".join(lines)
