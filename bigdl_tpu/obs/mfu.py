"""Always-on MFU accounting — XLA cost-analysis FLOPs per compiled program.

Every compiled program the trainer and the serving engine dispatch (train
step, fused window, prefill buckets, decode step) self-reports its model
FLOPs once via ``jitted.lower(*avals).cost_analysis()`` (~ms, paid once per
program — callers memoize per program-cache key). Each dispatch then feeds
:func:`note`, which maintains an EWMA FLOPs/s per domain and publishes two
live gauges into the metric registry:

- ``<domain>/model_flops_per_sec`` — achieved model FLOPs per second
- ``<domain>/mfu``                 — the same divided by the backend's peak

so every run — not just bench legs — carries the MFU number, and the
``/metrics`` endpoint exposes it to scrapers. The peak-FLOPs table below is
the single source for ``bench.py`` too; ``BIGDL_PEAK_FLOPS`` overrides it
(e.g. on backends the table does not know).

Lowering for cost analysis uses ``jax.ShapeDtypeStruct`` avals built from
the call's argument trees — never live buffers — so it composes with
``donate_argnums`` (the trainer donates params/state into each step; the
avals here are shapes only, nothing is retained or re-donated).

jax is imported lazily inside the functions that need it: the obs package
stays importable (and the registry/tracer usable) without jax present.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

#: peak dense (non-sparse) FLOPs/s per chip, matched by substring against
#: ``jax.devices()[0].device_kind.lower()``. Order matters: first match wins
#: ("v5 lite" before "v5"). bench.py re-exports this table.
PEAK_FLOPS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

#: EWMA weight for new FLOPs/s samples (matches the serving decode_tps EWMA)
_EW_ALPHA = 0.2

_lock = threading.Lock()
_ewma: dict = {}           # domain -> EWMA FLOPs/s
_UNSET = object()
_peak_cache = _UNSET       # cached table lookup for this process's backend


def peak_flops_for(device_kind: Optional[str]) -> Optional[float]:
    """Peak FLOPs/s for a device kind string, or None when unknown.

    ``BIGDL_PEAK_FLOPS`` (a float, FLOPs/s) wins over the table — the escape
    hatch for backends the table does not know, and how tests pin a peak on
    CPU."""
    raw = os.environ.get("BIGDL_PEAK_FLOPS", "").strip()
    if raw:
        try:
            v = float(raw)
            if v > 0:
                return v
        except ValueError:
            pass
    if not device_kind:
        return None
    kind = device_kind.lower()
    for sub, peak in PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


def device_peak() -> Optional[float]:
    """Peak FLOPs/s of this process's backend (None on CPU/unknown unless
    ``BIGDL_PEAK_FLOPS`` overrides). The table lookup is cached; the env
    override is consulted live so tests can flip it per-case."""
    global _peak_cache
    if os.environ.get("BIGDL_PEAK_FLOPS", "").strip():
        return peak_flops_for(None)
    if _peak_cache is _UNSET:
        kind = None
        try:
            import jax
            kind = jax.devices()[0].device_kind
        except Exception:
            kind = None
        _peak_cache = peak_flops_for(kind)
    return _peak_cache


def avals_of(args) -> tuple:
    """Argument tree → ShapeDtypeStruct avals: the donation-safe lowering
    inputs shared by :func:`program_flops` and
    :func:`bigdl_tpu.obs.device.program_memory` (shapes/dtypes only — live
    or donated buffers are never touched)."""
    import jax

    def _aval(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(_aval, args)


def program_flops(fn, *args) -> Optional[float]:
    """Model FLOPs of one compiled program, from XLA cost analysis.

    ``fn`` is a jitted callable, ``args`` the (or representative) call
    arguments — only their shapes/dtypes are used, via ShapeDtypeStruct
    avals, so donated buffers are never touched. Returns None when the
    backend provides no cost analysis (callers memoize either way: this
    re-traces, ~ms per program)."""
    try:
        ca = fn.lower(*avals_of(args)).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = ca.get("flops") if hasattr(ca, "get") else None
        if f is not None and f > 0:
            return float(f)
    except Exception:
        pass
    return None


def note(domain: str, flops: Optional[float], wall_s: float) -> None:
    """Record one dispatch: ``flops`` model FLOPs retired in ``wall_s``.

    Publishes ``<domain>/model_flops_per_sec`` (EWMA) always, and
    ``<domain>/mfu`` when the backend peak is known. No-op when the program's
    FLOPs are unknown — accounting degrades to absent, never to wrong."""
    if not flops or wall_s <= 0:
        return
    inst = flops / wall_s
    with _lock:
        prev = _ewma.get(domain)
        cur = inst if prev is None else (1.0 - _EW_ALPHA) * prev + _EW_ALPHA * inst
        _ewma[domain] = cur
    from bigdl_tpu.obs.registry import registry
    registry.gauge(domain + "/model_flops_per_sec").set(cur)
    peak = device_peak()
    if peak:
        registry.gauge(domain + "/mfu").set(cur / peak)


def stats() -> dict:
    """Current MFU accounting state for ``/statusz`` and bench records."""
    with _lock:
        fps = dict(_ewma)
    peak = device_peak()
    out = {"peak_flops": peak, "flops_per_sec": fps}
    if peak:
        out["mfu"] = {d: v / peak for d, v in fps.items()}
    return out


def reset() -> None:
    """Test isolation: forget EWMAs and the cached backend peak."""
    global _peak_cache
    with _lock:
        _ewma.clear()
        _peak_cache = _UNSET
