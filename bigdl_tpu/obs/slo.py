"""SLO monitor — windowed-percentile breach detection that gates health.

Watches the metric registry the way an external alerting rule would, but
in-process and fast enough to flip the serving health state machine before
a load balancer notices:

- **p99 TTFT** (``serving/ttft_ms`` window p99) > ``BIGDL_SLO_TTFT_MS``
- **feed-stall rate** (``train/feed_stall`` / ``train/step_wall`` count)
  > ``BIGDL_SLO_STALL_RATE``
- **throughput floor** (``train/throughput`` gauge) < ``BIGDL_SLO_MIN_TPS``

Each rule needs a minimum sample count before it can fire (one
compile-polluted observation must not page anyone). A breach emits a
``Robustness``-style event (``events.record("slo_breach", ...)`` + the
``slo/breaches`` counter + a ``trace.event``) and flips every registered
serving engine to ``degraded`` via ``set_slo_degraded(True)``; when all
rules recover, the flag clears and engines return to ``ready`` on their
next health update. ``/healthz`` and ``/statusz`` surface the state via
:func:`bigdl_tpu.obs.exporter.publish_status`.

Rules are opt-in per knob (unset = off); :meth:`SLOMonitor.check` is pure
polling logic (tests drive it directly), :meth:`start` runs it on a daemon
thread every ``BIGDL_SLO_INTERVAL_S`` seconds. The scripted fault site
``slo_breach`` (``BIGDL_FAULT_PLAN=slo_breach@1``) injects a synthetic
breach deterministically — the drill switch for the degrade path.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from bigdl_tpu.obs import exporter, trace
from bigdl_tpu.obs.registry import registry


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}") from None
    return v if v > 0 else None


class SLOMonitor:
    """Breach detector over the process registry. Explicit limits win over
    the ``BIGDL_SLO_*`` environment; a limit of ``None`` disables its rule."""

    def __init__(self, ttft_p99_ms: Optional[float] = None,
                 stall_rate: Optional[float] = None,
                 min_tps: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 min_count: int = 8):
        self.ttft_p99_ms = (ttft_p99_ms if ttft_p99_ms is not None
                            else _env_float("BIGDL_SLO_TTFT_MS"))
        self.stall_rate = (stall_rate if stall_rate is not None
                           else _env_float("BIGDL_SLO_STALL_RATE"))
        self.min_tps = (min_tps if min_tps is not None
                        else _env_float("BIGDL_SLO_MIN_TPS"))
        self.interval_s = (interval_s if interval_s is not None
                           else (_env_float("BIGDL_SLO_INTERVAL_S") or 5.0))
        self.min_count = min_count
        self.active: dict = {}      # rule -> current breach dict
        self.breaches = 0           # total breach transitions (ok -> firing)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_env(cls) -> Optional["SLOMonitor"]:
        """A monitor when any ``BIGDL_SLO_*`` rule is configured, else
        None."""
        mon = cls()
        if mon.enabled:
            return mon
        return None

    @property
    def enabled(self) -> bool:
        return any(v is not None
                   for v in (self.ttft_p99_ms, self.stall_rate, self.min_tps))

    # ------------------------------------------------------------- checking
    def _evaluate(self) -> list:
        """Current rule violations, as ``{rule, value, limit}`` dicts."""
        snap = registry.snapshot()
        hists = snap["histograms"]
        breaches = []
        if self.ttft_p99_ms is not None:
            h = hists.get("serving/ttft_ms")
            if (h and h["count"] >= self.min_count
                    and h["p99"] is not None and h["p99"] > self.ttft_p99_ms):
                breaches.append({"rule": "ttft_p99_ms",
                                 "value": round(h["p99"], 3),
                                 "limit": self.ttft_p99_ms})
        if self.stall_rate is not None:
            steps = hists.get("train/step_wall", {}).get("count", 0)
            stalls = snap["counters"].get("train/feed_stall", 0)
            if steps >= self.min_count:
                rate = stalls / steps
                if rate > self.stall_rate:
                    breaches.append({"rule": "feed_stall_rate",
                                     "value": round(rate, 4),
                                     "limit": self.stall_rate})
        if self.min_tps is not None:
            tps = snap["gauges"].get("train/throughput")
            if tps is not None and tps < self.min_tps:
                breaches.append({"rule": "throughput_floor",
                                 "value": round(tps, 2),
                                 "limit": self.min_tps})
        # scripted drill: BIGDL_FAULT_PLAN=slo_breach@N forces a synthetic
        # breach on the Nth check — exercises the degrade/recover path
        # deterministically (lazy import: obs must not import utils eagerly)
        try:
            from bigdl_tpu.utils import faults
            if faults.check_fault(faults.SITE_SLO_BREACH) is not None:
                breaches.append({"rule": "injected", "value": 1,
                                 "limit": 0})
        except ImportError:
            pass
        return breaches

    def check(self) -> list:
        """One evaluation round: detect transitions, emit breach events,
        flip/clear engine SLO degradation, publish state. Returns the rules
        currently in breach."""
        current = {b["rule"]: b for b in self._evaluate()}
        for rule, b in current.items():
            if rule not in self.active:
                self.breaches += 1
                registry.counter("slo/breaches").inc()
                trace.event("slo_breach", **b)
                try:  # Robustness-style breach record (lazy: no obs->utils
                    # import cycle at module load)
                    from bigdl_tpu.utils.robustness import events
                    events.record("slo_breach", **b)
                except Exception:
                    pass
        recovered = [r for r in self.active if r not in current]
        for rule in recovered:
            trace.event("slo_recovered", rule=rule)
        self.active = current
        degraded = bool(current)
        for eng in exporter.engines():
            set_flag = getattr(eng, "set_slo_degraded", None)
            if set_flag is not None:
                try:
                    set_flag(degraded)
                except Exception:
                    pass
        exporter.publish_status("slo", self.state())
        return list(current.values())

    def state(self) -> dict:
        return {"enabled": self.enabled,
                "active": list(self.active.values()),
                "breaches": self.breaches,
                "limits": {"ttft_p99_ms": self.ttft_p99_ms,
                           "stall_rate": self.stall_rate,
                           "min_tps": self.min_tps},
                "interval_s": self.interval_s}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "SLOMonitor":
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(target=self._run,
                                            name="bigdl-slo", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.check()
            except Exception:
                pass  # the monitor must never take the process down


_ACTIVE: Optional[SLOMonitor] = None
_ACTIVE_LOCK = threading.Lock()


def start_from_env() -> Optional[SLOMonitor]:
    """Start (once per process) the background monitor when any
    ``BIGDL_SLO_*`` rule is configured; ``None`` — allocating nothing —
    otherwise. Idempotent, called from every entry point (trainer start,
    serving-engine start) the same way the exporter is."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            return _ACTIVE
        mon = SLOMonitor.from_env()
        if mon is None:
            return None
        _ACTIVE = mon.start()
        return _ACTIVE


def active() -> Optional[SLOMonitor]:
    return _ACTIVE


def reset() -> None:
    """Test isolation: stop and drop the process-wide monitor."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        mon, _ACTIVE = _ACTIVE, None
    if mon is not None:
        mon.stop()
