"""Hang watchdog — turn silent TPU/feed stalls into actionable reports.

A background monitor thread watches the training loop's heartbeat (one beat
per completed step or fused window). When the gap since the last beat exceeds
the limit — ``BIGDL_WATCHDOG_FACTOR`` × the rolling median step time (default
10×), or the hard ``BIGDL_WATCHDOG_S`` timeout, whichever is smaller — it
dumps every Python thread's stack plus the tracer's open-span tree to stderr
and the JSONL event log, once per stall. A later heartbeat re-arms it.

The watchdog arms at the FIRST heartbeat: the initial step absorbs XLA
compilation, whose duration says nothing about a steady-state hang, so the
interval before any step completes is never flagged. Enabled by setting
``BIGDL_WATCHDOG_S`` (> 0); constructed per training run by the Optimizer.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
import weakref
from collections import deque
from typing import Callable, Optional

from bigdl_tpu.obs import trace

#: ratio-rule floor — a sub-ms median must not make a 10 ms hiccup "a hang"
_MIN_LIMIT_S = 0.25

#: running watchdogs (weakly held) — /healthz reads arm state from these
_ACTIVE: "weakref.WeakSet" = weakref.WeakSet()

#: callables returning a context dict merged into every stall dump; the
#: serving engine registers one so dumps carry the trace IDs and span trees
#: of its in-flight requests, not just thread stacks
_CONTEXT_PROVIDERS: list = []


def active_watchdogs() -> list:
    """Watchdogs whose monitor thread is currently running."""
    return [wd for wd in _ACTIVE if wd._thread is not None]


def add_context_provider(fn: Callable[[], dict]) -> None:
    """Register a zero-arg callable whose dict is appended to stall dumps
    (idempotent; provider errors are swallowed at dump time)."""
    if fn not in _CONTEXT_PROVIDERS:
        _CONTEXT_PROVIDERS.append(fn)


def remove_context_provider(fn: Callable[[], dict]) -> None:
    try:
        _CONTEXT_PROVIDERS.remove(fn)
    except ValueError:
        pass


def clear_context_providers() -> None:
    """Test isolation."""
    _CONTEXT_PROVIDERS.clear()


def from_env() -> Optional["HangWatchdog"]:
    """Build a watchdog from ``BIGDL_WATCHDOG_S`` / ``BIGDL_WATCHDOG_FACTOR``,
    or None when unset/non-positive."""
    raw = os.environ.get("BIGDL_WATCHDOG_S", "").strip()
    if not raw:
        return None
    try:
        hard = float(raw)
    except ValueError:
        raise ValueError(
            f"BIGDL_WATCHDOG_S must be a number of seconds, got {raw!r}"
        ) from None
    if hard <= 0:
        return None
    factor = float(os.environ.get("BIGDL_WATCHDOG_FACTOR", "10"))
    return HangWatchdog(hard_s=hard, factor=factor)


class HangWatchdog:
    """Monitor thread + heartbeat API. ``sink`` (tests) receives the dump
    text in addition to stderr and the JSONL log."""

    def __init__(self, hard_s: Optional[float] = None, factor: float = 10.0,
                 poll_s: Optional[float] = None,
                 sink: Optional[Callable[[str], None]] = None):
        if hard_s is None and factor <= 0:
            raise ValueError("watchdog needs a hard timeout or a factor")
        self.hard_s = hard_s
        self.factor = factor
        self.sink = sink
        self.dumps = 0
        self._durs: deque = deque(maxlen=64)
        self._last: Optional[float] = None  # None = not yet armed
        self._dumped = False
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        bound = hard_s if hard_s is not None else 1.0
        self._poll_s = poll_s if poll_s is not None else max(0.05, bound / 8)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="bigdl-watchdog", daemon=True)
        self._thread.start()
        _ACTIVE.add(self)

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None
        _ACTIVE.discard(self)

    @property
    def armed(self) -> bool:
        """True once a heartbeat has landed and no disarm() since — i.e. the
        monitor would flag prolonged silence right now. /healthz surfaces
        this so "engine idle (disarmed)" and "engine watched" are
        distinguishable from outside the process."""
        return self._last is not None

    def heartbeat(self, duration_s: Optional[float] = None) -> None:
        """Mark a completed step/window (optionally recording its wall time
        into the rolling-median window) and re-arm the dump."""
        if duration_s is not None:
            self._durs.append(float(duration_s))
        self._last = time.perf_counter()
        self._dumped = False

    def disarm(self) -> None:
        """Return to the not-yet-armed state. The serving engine disarms
        while idle (no active slots): a quiet engine waiting on arrivals is
        not a hang — only decode-loop silence with work in flight is."""
        self._last = None
        self._dumped = False

    # ------------------------------------------------------------- monitor
    def _limit(self) -> Optional[float]:
        limits = []
        if self.hard_s is not None:
            limits.append(self.hard_s)
        if self.factor > 0 and len(self._durs) >= 5:
            med = sorted(self._durs)[len(self._durs) // 2]
            limits.append(max(self.factor * med, _MIN_LIMIT_S))
        return min(limits) if limits else None

    def _run(self) -> None:
        while not self._stop_evt.wait(self._poll_s):
            last = self._last
            if last is None or self._dumped:
                continue
            limit = self._limit()
            if limit is None:
                continue
            elapsed = time.perf_counter() - last
            if elapsed > limit:
                self._dumped = True
                self.dumps += 1
                try:
                    self.dump(elapsed, limit)
                except Exception:
                    traceback.print_exc(file=sys.stderr)

    # ---------------------------------------------------------------- dump
    @staticmethod
    def thread_stacks() -> dict:
        """{thread name (tid): formatted stack} for every live Python
        thread."""
        names = {t.ident: t.name for t in threading.enumerate()}
        out = {}
        for tid, frame in sys._current_frames().items():
            label = f"{names.get(tid, '?')} ({tid})"
            out[label] = "".join(traceback.format_stack(frame))
        return out

    def dump(self, elapsed: float, limit: float) -> None:
        """Write the stall report to stderr, the JSONL event log, and the
        optional sink: what every thread is executing plus the tracer's
        open-span tree (empty unless ``BIGDL_TRACE`` is on)."""
        stacks = self.thread_stacks()
        spans = trace.open_spans()
        contexts = []
        for provider in list(_CONTEXT_PROVIDERS):
            try:
                ctx = provider()
            except Exception:
                continue
            if ctx:
                contexts.append(ctx)
        lines = [
            "=" * 70,
            f"BIGDL WATCHDOG: no step completed for {elapsed:.1f}s "
            f"(limit {limit:.1f}s, median of last {len(self._durs)} steps: "
            + (f"{sorted(self._durs)[len(self._durs) // 2] * 1e3:.1f} ms)"
               if self._durs else "n/a)"),
            "possible hang — dumping all thread stacks and open spans",
        ]
        for label, entries in spans.items():
            chain = " > ".join(
                f"{e['name']} ({e['age_ms']:.0f}ms)" for e in entries)
            lines.append(f"open spans [{label}]: {chain}")
        if not spans:
            lines.append("open spans: none recorded (BIGDL_TRACE off?)")
        for ctx in contexts:
            who = ctx.get("engine", ctx.get("name", "?"))
            lines.append(f"in-flight [{who}] "
                         f"(health {ctx.get('health', '?')}):")
            flights = ctx.get("in_flight") or []
            for f in flights:
                lines.append(
                    f"  trace {f.get('trace_id')} request "
                    f"{f.get('request_id')} slot {f.get('slot')} "
                    f"generated {f.get('generated')} "
                    f"age {f.get('age_ms')}ms")
            if not flights:
                lines.append("  (no requests in flight)")
        for label, stack in stacks.items():
            lines.append(f"--- thread {label} ---")
            lines.append(stack.rstrip())
        lines.append("=" * 70)
        text = "\n".join(lines)
        print(text, file=sys.stderr, flush=True)
        trace.event("watchdog_dump", elapsed_s=round(elapsed, 3),
                    limit_s=round(limit, 3), threads=stacks,
                    open_spans=spans, contexts=contexts)
        if self.sink is not None:
            self.sink(text)
