"""Structured serving access log — one JSONL record per finished request.

The serving engine completes thousands of requests and keeps only
aggregates; nothing records the individual requests, so the roadmap's
serving-logs→trainer flywheel has no input edge. This module is that edge:

- :class:`AccessLog` — an opt-in, size-rotated JSONL writer. Every
  completed OR failed request appends one record::

      {trace_id, tenant, phase, prompt_tokens, output_tokens,
       ttft_ms, e2e_ms, flops, outcome}

  ``outcome`` is ``ok`` / ``timeout`` / ``poisoned`` / ``aborted``;
  ``phase`` is where the request ended (``queue`` before admission,
  ``decode`` after). Enabled by pointing ``BIGDL_ACCESS_LOG`` at a
  directory; files rotate at ``BIGDL_ACCESS_LOG_ROTATE_MB`` megabytes
  (default 64) to ``access-<pid>-<k>.jsonl`` so a long-lived server never
  grows one unbounded file. Writes are append+flush under a lock from the
  engine thread; a write failure disables the log loudly (one event)
  rather than failing requests — the log observes serving, it must never
  become serving's failure mode.

- :func:`to_bdlrec` — the flywheel converter: re-shards every record in a
  log directory into ``.bdlrec`` shards (payload = the JSON line, CRC per
  record courtesy of the container format) that
  :class:`~bigdl_tpu.dataset.streaming.StreamingDataSet` replays with
  :func:`access_record_decoder`.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from bigdl_tpu.obs import trace

_LOG: Optional["AccessLog"] = None
_LOG_LOCK = threading.Lock()
_ENV_SEEN: Optional[str] = None

#: record fields, in pinned order (the replay test asserts fidelity)
FIELDS = ("trace_id", "tenant", "phase", "prompt_tokens", "output_tokens",
          "ttft_ms", "e2e_ms", "flops", "outcome")


class AccessLog:
    """Size-rotated JSONL request log rooted at one directory."""

    def __init__(self, directory: str, rotate_mb: float = 64.0):
        self.directory = directory
        self.rotate_bytes = max(int(rotate_mb * 1024 * 1024), 4096)
        self.path = os.path.join(directory,
                                 "access-%d.jsonl" % os.getpid())
        self.records = 0
        self.rotations = 0
        self.disabled = False
        self._lock = threading.Lock()
        self._f = None

    def log(self, **fields) -> None:
        """Append one request record (missing FIELDS become None; extra
        kwargs ride along). Never raises."""
        if self.disabled:
            return
        rec = {k: fields.pop(k, None) for k in FIELDS}
        rec.update(fields)
        line = json.dumps(rec, separators=(",", ":"), default=str) + "\n"
        try:
            with self._lock:
                if self._f is None:
                    os.makedirs(self.directory, exist_ok=True)
                    self._f = open(self.path, "a")
                self._f.write(line)
                self._f.flush()
                self.records += 1
                if self._f.tell() >= self.rotate_bytes:
                    self._rotate_locked()
        except Exception as exc:
            self.disabled = True
            trace.event("access_log_disabled", path=self.path,
                        error=str(exc))
            import logging
            logging.getLogger("bigdl_tpu.obs").error(
                "access log write to %s failed (%s); request logging "
                "disabled for this process", self.path, exc)

    def _rotate_locked(self) -> None:
        self._f.close()
        self._f = None
        self.rotations += 1
        rotated = self.path[:-len(".jsonl")] + "-%d.jsonl" % self.rotations
        os.replace(self.path, rotated)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def from_env() -> Optional[AccessLog]:
    """The process-wide log when ``BIGDL_ACCESS_LOG`` names a directory
    (``BIGDL_ACCESS_LOG_ROTATE_MB`` sizes the rotation); None — allocating
    nothing — when unset. Re-reads the env when its value changes so tests
    can re-point the log."""
    global _LOG, _ENV_SEEN
    raw = os.environ.get("BIGDL_ACCESS_LOG", "").strip()
    with _LOG_LOCK:
        if raw != _ENV_SEEN:
            if _LOG is not None:
                _LOG.close()
            _ENV_SEEN = raw
            if raw:
                try:
                    mb = float(os.environ.get(
                        "BIGDL_ACCESS_LOG_ROTATE_MB", "64") or "64")
                except ValueError:
                    mb = 64.0
                _LOG = AccessLog(raw, rotate_mb=mb)
            else:
                _LOG = None
        return _LOG


def log_request(**fields) -> None:
    """Engine-side entry point: record one finished request when the log
    is enabled, free when it is not."""
    log = from_env()
    if log is not None:
        log.log(**fields)


def reset() -> None:
    """Test isolation: close and forget the process-wide log."""
    global _LOG, _ENV_SEEN
    with _LOG_LOCK:
        if _LOG is not None:
            _LOG.close()
        _LOG = None
        _ENV_SEEN = None


# ------------------------------------------------------------- the flywheel
def access_record_decoder(payload: bytes) -> dict:
    """``.bdlrec`` payload → the original access-log record (dict)."""
    return json.loads(payload.decode("utf-8"))


def to_bdlrec(log_dir: str, out_dir: str, shards: int = 1,
              prefix: str = "access") -> "tuple[list, int]":
    """Re-shard every access-log record under ``log_dir`` (all
    ``*.jsonl`` files, rotated generations included) into ``shards``
    ``.bdlrec`` files under ``out_dir``. Returns ``(shard_paths, count)``.
    Blank / torn tail lines are skipped; a record's payload is its exact
    JSON line, so the round trip is byte-faithful."""
    from bigdl_tpu.dataset.recordio import RecordWriter

    shards = max(int(shards), 1)
    names = sorted(n for n in os.listdir(log_dir) if n.endswith(".jsonl"))
    os.makedirs(out_dir, exist_ok=True)
    paths = [os.path.join(out_dir, "%s-%05d.bdlrec" % (prefix, s))
             for s in range(shards)]
    writers = [RecordWriter(p) for p in paths]
    n = 0
    try:
        for name in names:
            with open(os.path.join(log_dir, name), "rb") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        json.loads(line.decode("utf-8"))
                    except (ValueError, UnicodeDecodeError):
                        continue   # torn tail of a crashed writer
                    writers[n % shards].write(line)
                    n += 1
    finally:
        for w in writers:
            w.close()
    return paths, n
