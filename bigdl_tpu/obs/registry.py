"""Process-wide metric registry — counters, gauges, histograms, one source.

The legacy rails (``optim/metrics.Metrics``, ``dataset/profiling.feed_stats``,
``utils/robustness.events``) keep their public APIs but publish through this
registry, so the end-of-run report, the ``TrainSummary`` curves, and the bench
legs all read ONE accumulator instead of merging three bespoke snapshots.

Naming conventions in use:

- ``phase/<name>``       — trainer phase timings (histogram, seconds)
- ``feed/<stage>``       — input-pipeline stage timings (histogram, seconds)
- ``robustness/<kind>``  — recovery-action counts (counter)
- ``train/step_wall``    — per-step wall time incl. feed wait (histogram)
- ``train/feed_stall``   — steps whose feed wait dominated (counter)
- ``train/throughput``   — latest records/s (gauge)

Consumers diff :meth:`MetricRegistry.snapshot` values, the same protocol the
legacy rails used — the registry is process-wide and outlives individual runs.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

#: histogram percentile window (recent observations; percentiles are over
#: this window, sums/counts are exact over the process lifetime)
_WINDOW = 4096


class Counter:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Exact (sum, count, min, max) plus a bounded recent-value window for
    p50/p95/p99 and the watchdog's rolling median."""

    __slots__ = ("_lock", "count", "total", "min", "max", "_window")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._window: deque = deque(maxlen=_WINDOW)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            self._window.append(v)

    def percentiles(self, qs=(50, 95, 99)) -> dict:
        """{q: value} over the recent window (empty dict when no data)."""
        with self._lock:
            vals = sorted(self._window)
        if not vals:
            return {}
        n = len(vals)
        return {q: vals[min(n - 1, int(round(q / 100.0 * (n - 1))))]
                for q in qs}

    def median(self, min_count: int = 8) -> Optional[float]:
        """Rolling median over the window, or None with fewer than
        ``min_count`` observations (the watchdog must not extrapolate from
        one compile-polluted sample)."""
        with self._lock:
            if len(self._window) < min_count:
                return None
            vals = sorted(self._window)
        return vals[len(vals) // 2]


class MetricRegistry:
    """Get-or-create registry of named metrics. Thread-safe; one instance
    per process (:data:`registry`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(self._lock))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(self._lock))
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(self._lock))
        return h

    def snapshot(self) -> dict:
        """Plain-data view for delta math and the run report:
        ``{"counters": {name: n}, "gauges": {name: v}, "histograms":
        {name: {count, total, min, max, mean, p50, p95, p99}}}``."""
        # Every histogram field is captured under the registry lock so a
        # concurrent observe() can never tear (count, total, min, max, window)
        # against each other — a snapshot's mean is always total/count of the
        # SAME instant. Sorting the window copies happens outside the lock.
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {k: g.value for k, g in self._gauges.items()
                      if g.value is not None}
            hists = [(name, h.count, h.total, h.min, h.max, tuple(h._window))
                     for name, h in self._histograms.items() if h.count]
        out_h = {}
        for name, count, total, mn, mx, window in hists:
            vals = sorted(window)
            n = len(vals)
            ps = {q: vals[min(n - 1, int(round(q / 100.0 * (n - 1))))]
                  for q in (50, 95, 99)} if n else {}
            out_h[name] = {
                "count": count, "total": total,
                "min": mn, "max": mx,
                "mean": total / count,
                "p50": ps.get(50), "p95": ps.get(95), "p99": ps.get(99),
            }
        return {"counters": counters, "gauges": gauges, "histograms": out_h}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


#: the process-wide registry every rail publishes into
registry = MetricRegistry()
