"""UDF-predictor example (reference parity: ``<dl>/example/udfpredictor`` —
registering a trained text classifier as a Spark-SQL UDF, unverified).

TPU-native redesign: there is no SQL engine in the loop — the analog of
"register a UDF" is ``make_predict_udf``, which closes a trained model +
tokenizer into a plain callable usable in any Python data pipeline (pandas
``apply``, a web handler, a stream consumer). The example trains a temporal-CNN
text classifier on synthetic labeled sentences, builds the udf, and maps it
over a batch of "rows".
``python -m bigdl_tpu.examples.udfpredictor.main``
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="serve a text classifier as a UDF")
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument("--max-epoch", type=int, default=6)
    return p


_TOPICS = {
    0: ["stock", "market", "shares", "profit", "bank", "trade"],
    1: ["match", "team", "score", "league", "coach", "goal"],
}


def _synthetic_rows(n: int, rng):
    rows = []
    for i in range(n):
        label = i % 2
        words = list(rng.choice(_TOPICS[label], size=6)) \
            + list(rng.choice(["the", "a", "of", "and"], size=3))
        rng.shuffle(words)
        rows.append({"id": i, "text": " ".join(words), "label": label})
    return rows


def make_predict_udf(model, dictionary, seq_len: int):
    """Close model + vocab into a row-wise callable — the UDF-registration
    analog. Batching callers should stack texts and call ``model.predict``."""
    import jax.numpy as jnp

    from bigdl_tpu.dataset.text import SentenceTokenizer

    tok = SentenceTokenizer()

    def udf(text: str) -> int:
        tokens = next(iter(tok(iter([text]))))
        ids = [dictionary.get_index(w) for w in tokens][:seq_len]
        ids = ids + [0] * (seq_len - len(ids))
        out = model.forward(jnp.asarray(np.asarray(ids, np.int32)[None]))
        return int(np.argmax(np.asarray(out), axis=-1)[0])

    return udf


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.text import Dictionary, SentenceTokenizer
    from bigdl_tpu.dataset.sample import SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.textclassifier import TextClassifier
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random_generator import RandomGenerator

    if not Engine.is_initialized():
        Engine.init()
    RandomGenerator.set_seed(0)
    rng = np.random.default_rng(0)

    rows = _synthetic_rows(128, rng)
    tok = SentenceTokenizer()
    all_tokens = [t for r in rows for t in next(iter(tok(iter([r["text"]]))))]
    vocab = Dictionary(all_tokens, vocab_size=200)

    def encode(text):
        tokens = next(iter(tok(iter([text]))))
        ids = [vocab.get_index(w) for w in tokens][:args.seq_len]
        return np.asarray(ids + [0] * (args.seq_len - len(ids)), np.int32)

    samples = [Sample(encode(r["text"]), np.int32(r["label"])) for r in rows]
    ds = DataSet.array(samples) >> SampleToMiniBatch(16)
    model = TextClassifier(vocab_size=vocab.vocab_size(), class_num=2,
                           seq_len=args.seq_len)
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(SGD(learningrate=0.2))
    opt.set_end_when(Trigger.max_epoch(args.max_epoch))
    opt.optimize()

    udf = make_predict_udf(model.evaluate(), vocab, args.seq_len)
    test_rows = _synthetic_rows(32, np.random.default_rng(1))
    preds = [{"id": r["id"], "pred": udf(r["text"])} for r in test_rows]
    acc = float(np.mean([p["pred"] == r["label"]
                         for p, r in zip(preds, test_rows)]))
    print(f"udf mapped over {len(test_rows)} rows; accuracy {acc:.3f}; "
          f"first rows: {preds[:4]}")
    return acc


if __name__ == "__main__":
    main()
