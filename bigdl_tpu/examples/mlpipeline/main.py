"""ML-pipeline example (reference parity: ``<dl>/example/MLPipeline`` — the
Spark-ML ``DLClassifier`` pipeline demo, unverified). TPU-native redesign:
the sklearn-compatible estimators (``bigdl_tpu.dlframes``) compose with
``sklearn.pipeline.Pipeline`` and ``GridSearchCV`` exactly where the reference
composed with ``org.apache.spark.ml.Pipeline``.
``python -m bigdl_tpu.examples.mlpipeline.main``
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="sklearn pipeline with DLClassifier")
    p.add_argument("--samples", type=int, default=400)
    p.add_argument("--features", type=int, default=8)
    p.add_argument("--classes", type=int, default=3)
    p.add_argument("--grid-search", action="store_true",
                   help="also run a small GridSearchCV over hidden width")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from sklearn.model_selection import train_test_split
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler

    from bigdl_tpu import nn
    from bigdl_tpu.dlframes import DLClassifier
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    rng = np.random.default_rng(0)
    centers = rng.normal(0, 3, size=(args.classes, args.features))
    y = rng.integers(0, args.classes, size=args.samples)
    X = (centers[y] + rng.normal(0, 1.0, size=(args.samples, args.features))
         ).astype(np.float32)
    X_tr, X_te, y_tr, y_te = train_test_split(X, y, test_size=0.25,
                                              random_state=0)

    def model_fn(hidden=16):
        return (nn.Sequential()
                .add(nn.Linear(args.features, hidden)).add(nn.ReLU())
                .add(nn.Linear(hidden, args.classes)).add(nn.LogSoftMax()))

    pipe = Pipeline([
        ("scale", StandardScaler()),
        ("clf", DLClassifier(model_fn=model_fn,
                             criterion_fn=nn.ClassNLLCriterion,
                             batch_size=32, max_epoch=12,
                             learning_rate=0.1)),
    ])
    pipe.fit(X_tr, y_tr)
    acc = float((pipe.predict(X_te) == y_te).mean())
    print(f"pipeline test accuracy: {acc:.3f}")

    if args.grid_search:
        from sklearn.model_selection import GridSearchCV
        gs = GridSearchCV(pipe, {"clf__max_epoch": [4, 12]}, cv=2, n_jobs=1)
        gs.fit(X_tr, y_tr)
        print(f"grid search best: {gs.best_params_} "
              f"(cv score {gs.best_score_:.3f})")
    return acc


if __name__ == "__main__":
    main()
