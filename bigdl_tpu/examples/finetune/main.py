"""Fine-tuning example: the transfer-learning flow, both classic and LoRA.

Reference parity: the reference's ``loadmodel`` example demonstrates reusing
a saved model; this example completes the story with the two fine-tuning
disciplines this framework supports:

- ``--mode head``  (classic): freeze the pretrained trunk, swap and train a
  fresh classifier head (``freeze()`` + per-layer trainability);
- ``--mode lora``  (modern): keep the whole architecture, train only rank-r
  adapters (``nn.apply_lora``) and optionally ``merge_lora`` for serving.

With no ``--model`` it first pretrains a small CNN on synthetic "shapes" data
so the example runs offline end-to-end; the fine-tune task is a shifted
label set over the same inputs. ``python -m bigdl_tpu.examples.finetune.main``
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="fine-tuning (head or LoRA)")
    p.add_argument("--model", default=None, help="pretrained archive (.bigdl)")
    p.add_argument("--mode", default="lora", choices=["head", "lora"])
    p.add_argument("--rank", type=int, default=4, help="LoRA rank")
    p.add_argument("--merge", action="store_true",
                   help="bake the adapters after training (serving form)")
    p.add_argument("-b", "--batch-size", type=int, default=16)
    p.add_argument("--max-epoch", type=int, default=20)
    p.add_argument("--learning-rate", type=float, default=0.01)
    p.add_argument("--save", default=None, help="save the fine-tuned model")
    return p


def _data(n, rng, shifted=False):
    """Synthetic 3-class task; ``shifted`` permutes the labels (the 'new
    task' the fine-tune adapts to)."""
    from bigdl_tpu.dataset.sample import Sample
    xs = rng.normal(size=(n, 1, 12, 12)).astype(np.float32)
    base = (xs.mean(axis=(1, 2, 3)) > 0).astype(np.int32) \
        + 2 * (xs[:, 0, :6].mean(axis=(1, 2)) > 0).astype(np.int32)
    ys = np.clip(base, 0, 2)
    if shifted:
        ys = (ys + 1) % 3
    return [Sample(x, np.int32(y)) for x, y in zip(xs, ys)]


def _build_cnn(n_classes=3):
    from bigdl_tpu import nn
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(1, 8, 3, 3, pad_w=1, pad_h=1).set_name("conv1"))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(2, 2))
    m.add(nn.Reshape([8 * 6 * 6]))
    m.add(nn.Linear(8 * 6 * 6, 32).set_name("fc1"))
    m.add(nn.ReLU())
    m.add(nn.Linear(32, n_classes).set_name("head"))
    m.add(nn.LogSoftMax())
    return m


def _train(model, samples, batch, epochs, lr):
    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger
    data = DataSet.array(samples) >> SampleToMiniBatch(batch)
    opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(Adam(learningrate=lr))
           .set_end_when(Trigger.max_epoch(epochs)))
    opt.optimize()
    return float(opt.state["loss"])


def _accuracy(model, samples):
    import jax.numpy as jnp
    model.evaluate()
    xs = np.stack([s.feature[0] for s in samples])
    ys = np.asarray([int(s.label[0]) for s in samples])
    pred = np.asarray(model.forward(jnp.asarray(xs))).argmax(-1)
    return float((pred == ys).mean())


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()
    from bigdl_tpu.utils.random_generator import RandomGenerator
    RandomGenerator.set_seed(7)   # deterministic weight init for the example
    rng = np.random.default_rng(0)

    if args.model:
        model = nn.AbstractModule.load(args.model)
        print(f"loaded pretrained model from {args.model}")
    else:
        model = _build_cnn()
        loss = _train(model, _data(256, rng), args.batch_size, 12, 0.01)
        print(f"pretrained offline (loss {loss:.3f})")

    tune = _data(256, rng, shifted=True)
    held = _data(64, np.random.default_rng(1), shifted=True)
    print(f"accuracy on the NEW task before fine-tuning: "
          f"{_accuracy(model, held):.3f}")

    if args.mode == "head":
        # classic transfer learning: frozen trunk, fresh trainable head
        model.freeze()
        for m in _iter(model):
            if m.name == "head":
                m.reset()
                m.unfreeze()
        n_trained = sum(1 for m in _iter(model) if not m.is_frozen()
                        and m.get_params())
        print(f"head mode: trunk frozen, {n_trained} module(s) train")
    else:
        n = nn.apply_lora(model, rank=args.rank)
        print(f"lora mode: {n} modules adapted at rank {args.rank}, "
              f"base frozen")

    model.training()
    loss = _train(model, tune, args.batch_size, args.max_epoch,
                  args.learning_rate)
    acc = _accuracy(model, held)
    print(f"fine-tuned: loss {loss:.3f}, held-out accuracy {acc:.3f}")

    if args.mode == "lora" and args.merge:
        nn.merge_lora(model)
        merged_acc = _accuracy(model, held)
        print(f"adapters merged; accuracy unchanged: {merged_acc:.3f}")
        acc = merged_acc   # return the SERVED (merged) model's accuracy
    if args.save:
        model.save_module(args.save)
        print(f"saved to {args.save}")
    return acc


def _iter(model):
    from bigdl_tpu.nn.incremental import iter_modules
    return iter_modules(model)


if __name__ == "__main__":
    main()
