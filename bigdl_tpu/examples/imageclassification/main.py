"""Image-classification inference example (reference parity:
``<dl>/example/imageclassification`` — unverified, mount empty): load or train
a model, push an ImageFrame through the vision-transformer chain
(Resize → CenterCrop → ChannelNormalize → MatToTensor), and predict with
``model.predict_image``. With no --folder/--model it trains a small CNN on
synthetic two-class images so the example runs offline end-to-end.
``python -m bigdl_tpu.examples.imageclassification.main``
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="image classification inference")
    p.add_argument("--model", default=None, help="saved model path (.bigdl)")
    p.add_argument("--folder", default=None,
                   help="image folder (root/<class>/<img>); synthetic if unset")
    p.add_argument("-b", "--batch-size", type=int, default=16)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--topk", type=int, default=1)
    return p


def _synthetic_frame(n: int, size: int):
    """Two visually distinct classes: bright blobs vs dark gradients (HWC uint8)."""
    from bigdl_tpu.transform.vision.image import ImageFrame

    rng = np.random.default_rng(0)
    images, labels = [], []
    for i in range(n):
        label = i % 2
        if label == 0:
            img = rng.normal(180, 30, size=(size, size, 3))
        else:
            ramp = np.linspace(0, 80, size, dtype=np.float32)
            img = ramp[None, :, None] + rng.normal(20, 10, size=(size, size, 3))
        images.append(np.clip(img, 0, 255).astype(np.uint8))
        labels.append(label)
    return ImageFrame.from_arrays(images, labels), np.asarray(labels)


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.folder is not None and args.model is None:
        raise SystemExit(
            "--folder requires --model: the offline fallback trains on "
            "synthetic two-class blobs, which says nothing about your data")

    from bigdl_tpu import nn
    from bigdl_tpu.transform.vision.image import (
        CenterCrop, ChannelNormalize, ImageFrame, MatToTensor, Resize,
    )
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    crop = args.image_size
    chain = (Resize(crop + 8, crop + 8) >> CenterCrop(crop, crop)
             >> ChannelNormalize([127.5] * 3, [127.5] * 3)
             >> MatToTensor())

    if args.folder is not None:
        import glob
        import os
        paths = sorted(glob.glob(os.path.join(args.folder, "*", "*")))
        classes = sorted({os.path.basename(os.path.dirname(p)) for p in paths})
        labels = {p: classes.index(os.path.basename(os.path.dirname(p)))
                  for p in paths}
        frame = ImageFrame.read(paths, with_labels=labels)
        truth = np.asarray([labels[p] for p in paths])
    else:
        frame, truth = _synthetic_frame(64, crop)
    frame = frame.transform(chain)

    if args.model is not None:
        model = nn.AbstractModule.load(args.model)
    else:
        # offline path: train a small CNN on the same synthetic distribution
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import SampleToMiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        model = (nn.Sequential()
                 .add(nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1))
                 .add(nn.ReLU())
                 .add(nn.SpatialAveragePooling(crop // 2, crop // 2, 1, 1))
                 .add(nn.Flatten()).add(nn.Linear(8, 2)).add(nn.LogSoftMax()))
        train_frame, _ = _synthetic_frame(128, crop)
        ds = (DataSet.array(train_frame.transform(chain).to_samples())
              >> SampleToMiniBatch(args.batch_size))
        opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_epoch(3))
        opt.optimize()

    out = model.predict_image(frame, batch_size=args.batch_size)
    pred = np.argmax(out, axis=-1)
    acc = float((pred == truth).mean())
    topk = np.argsort(-out, axis=-1)[:, :args.topk]
    print(f"predicted {len(pred)} images; top-{args.topk} classes for the "
          f"first 5: {topk[:5].tolist()}; accuracy vs labels: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
