"""End-to-end example mains (reference parity: ``<dl>/example/`` — SURVEY.md §2.5
Examples). Each example is a self-contained ``main(argv)`` runnable offline on
synthetic data; pass your own data paths for real runs."""
