"""Sample and MiniBatch.

Reference parity (SURVEY.md §2.2, expected ``<dl>/dataset/Sample.scala``, ``MiniBatch.scala``
— unverified): a ``Sample`` is (feature tensors, label tensors) with contiguous storage; a
``MiniBatch`` stacks samples with optional padding; ``SampleToMiniBatch`` is the batching
transformer.

TPU-native: host-side numpy until the trainer's device put; batches keep STATIC shapes
(fixed batch size — the final partial batch is padded up and carries an explicit valid-count
so jit never sees a new shape; the reference padded too, for a different reason).

Zero-alloc assembly: ``SampleToMiniBatch`` stacks into a small RING of
preallocated output buffers (``BIGDL_BATCH_RING`` slots, default 4) instead of
fresh allocations every batch. A batch's buffers return to the ring when the
consumer calls ``MiniBatch.recycle()`` — the trainer's feed path does, right
after ``device_put`` has copied the bytes out. Consumers that never recycle
(tests, ad-hoc iteration) simply drain the ring and fall back to fresh
allocations — identical behavior to the pre-ring code, never a deadlock and
never an aliased buffer.
"""

from __future__ import annotations

import os
import queue
import time
from typing import Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.profiling import STAGE_STACK, feed_stats
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.obs import trace


class Sample:
    def __init__(self, feature, label=None):
        self.feature = (tuple(np.asarray(f) for f in feature)
                        if isinstance(feature, (tuple, list))
                        else (np.asarray(feature),))
        if label is None:
            self.label = ()
        else:
            self.label = (tuple(np.asarray(l) for l in label)
                          if isinstance(label, (tuple, list))
                          else (np.asarray(label),))

    @property
    def features(self):
        return self.feature

    @property
    def labels(self):
        return self.label

    def __repr__(self):
        fs = ",".join(str(f.shape) for f in self.feature)
        ls = ",".join(str(l.shape) for l in self.label)
        return f"Sample(feature={fs}, label={ls})"


class MiniBatch:
    """Stacked batch. ``size`` is the padded batch size; ``valid`` the real sample count."""

    def __init__(self, input, target=None, valid: Optional[int] = None):
        self.input = input
        self.target = target
        self.valid = valid if valid is not None else _batch_dim(input)
        self._ring_slot = None

    def size(self) -> int:
        return _batch_dim(self.input)

    def recycle(self) -> None:
        """Return this batch's buffers to the assembly ring (no-op for
        non-ring batches). Only the consumer that has finished reading
        ``input``/``target`` may call this — afterwards the arrays may be
        overwritten by a later batch. Scalar metadata (``valid``) stays
        usable."""
        slot = self._ring_slot
        if slot is not None:
            self._ring_slot = None
            slot.release()

    def __repr__(self):
        return f"MiniBatch(size={self.size()}, valid={self.valid})"


def _batch_dim(x) -> int:
    if isinstance(x, (tuple, list)):
        return _batch_dim(x[0])
    return int(np.asarray(x).shape[0])


def batch_ring_depth(default: int = 4) -> int:
    """``BIGDL_BATCH_RING``: preallocated output-buffer slots per
    SampleToMiniBatch (0 disables the ring — every batch allocates fresh)."""
    raw = os.environ.get("BIGDL_BATCH_RING", "").strip()
    if raw == "":
        return default
    try:
        v = int(raw)
        if v < 0:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"BIGDL_BATCH_RING must be a non-negative integer, got {raw!r}"
        ) from None
    return v


class _RingSlot:
    """One preallocated output buffer set: per-feature and per-label arrays of
    shape (batch_size, *sample_shape). Arrays materialize on first fill (the
    sample shapes are unknown until then) and are reused verbatim afterwards."""

    __slots__ = ("feats", "labels", "_free")

    def __init__(self, free: "queue.SimpleQueue"):
        self.feats: Optional[tuple] = None
        self.labels: Optional[tuple] = None
        self._free = free

    def release(self) -> None:
        self._free.put(self)

    def compatible(self, samples: Sequence[Sample]) -> bool:
        if self.feats is None:
            return True
        s = samples[0]
        return (len(self.feats) == len(s.feature)
                and len(self.labels) == len(s.label)
                and all(b.shape[1:] == a.shape and b.dtype == a.dtype
                        for b, a in zip(self.feats, s.feature))
                and all(b.shape[1:] == a.shape and b.dtype == a.dtype
                        for b, a in zip(self.labels, s.label)))


class _BufferRing:
    """Fixed set of ``depth`` slots handed out through a thread-safe free
    queue. ``acquire`` never blocks: an exhausted ring (consumer not
    recycling) degrades to fresh allocations at the call site."""

    def __init__(self, depth: int):
        self._free: queue.SimpleQueue = queue.SimpleQueue()
        for _ in range(depth):
            self._free.put(_RingSlot(self._free))

    def acquire(self) -> Optional[_RingSlot]:
        try:
            return self._free.get_nowait()
        except queue.Empty:
            return None


class SampleToMiniBatch(Transformer):
    """Group Samples into fixed-size MiniBatches.

    ``pad_last=True`` (default) repeats trailing samples so every batch has exactly
    ``batch_size`` rows (static shapes for XLA) and records ``valid`` for correct metrics;
    ``pad_last=False`` drops the final partial batch (training-loop default).

    ``ring_depth`` (default from ``BIGDL_BATCH_RING``) sizes the preallocated
    output-buffer ring; 0 stacks into fresh arrays every batch. Samples whose
    shapes vary from batch to batch disable the ring automatically (static
    slot shapes can't serve them).
    """

    def __init__(self, batch_size: int, pad_last: bool = True,
                 ring_depth: Optional[int] = None):
        assert batch_size > 0
        self.batch_size = batch_size
        self.pad_last = pad_last
        depth = batch_ring_depth() if ring_depth is None else int(ring_depth)
        self._ring = _BufferRing(depth) if depth > 0 else None

    def __call__(self, prev: Iterator) -> Iterator:
        return self._gen(prev)

    def _gen(self, prev: Iterator):
        buf: list[Sample] = []
        for s in prev:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._stack(buf, self.batch_size)
                buf = []
        if buf and self.pad_last:
            valid = len(buf)
            while len(buf) < self.batch_size:
                buf.append(buf[valid - 1])
            yield self._stack(buf, self.batch_size, valid)

    # ------------------------------------------------------------- stacking
    def _stack(self, samples: Sequence[Sample], batch_size: int,
               valid: Optional[int] = None) -> MiniBatch:
        t0 = time.perf_counter()
        with trace.span("feed/stack"):
            slot = self._ring.acquire() if self._ring is not None else None
            if slot is not None and not slot.compatible(samples):
                # variable-shape stream: the ring's static buffers can't
                # serve it
                slot.release()
                slot = None
                self._ring = None
            if slot is not None:
                batch = self._stack_into(slot, samples, batch_size, valid)
            else:
                batch = self._stack_fresh(samples, batch_size, valid)
        feed_stats.add(STAGE_STACK, time.perf_counter() - t0)
        return batch

    @staticmethod
    def _stack_into(slot: _RingSlot, samples: Sequence[Sample],
                    batch_size: int, valid: Optional[int]) -> MiniBatch:
        s0 = samples[0]
        if slot.feats is None:
            slot.feats = tuple(
                np.empty((batch_size,) + a.shape, a.dtype) for a in s0.feature)
            slot.labels = tuple(
                np.empty((batch_size,) + a.shape, a.dtype) for a in s0.label)
        # np.stack(out=...) copies straight into the preallocated slot — the
        # steady-state feed allocates nothing per batch
        for j, out in enumerate(slot.feats):
            np.stack([s.feature[j] for s in samples], out=out)
        for j, out in enumerate(slot.labels):
            np.stack([s.label[j] for s in samples], out=out)
        n_f, n_l = len(slot.feats), len(slot.labels)
        input = slot.feats[0] if n_f == 1 else slot.feats
        target = (slot.labels[0] if n_l == 1 else slot.labels) if n_l else None
        batch = MiniBatch(input, target,
                          valid if valid is not None else len(samples))
        batch._ring_slot = slot
        return batch

    @staticmethod
    def _stack_fresh(samples: Sequence[Sample], batch_size: int,
                     valid: Optional[int] = None) -> MiniBatch:
        # native GIL-free copy when available (runs in the prefetch producer
        # thread — overlap with the main thread is the point); numpy otherwise
        from bigdl_tpu.native import pack_batch
        n_f = len(samples[0].feature)
        feats = tuple(pack_batch([s.feature[i] for s in samples]) for i in range(n_f))
        n_l = len(samples[0].label)
        labels = tuple(pack_batch([s.label[i] for s in samples]) for i in range(n_l))
        input = feats[0] if n_f == 1 else feats
        target = (labels[0] if n_l == 1 else labels) if n_l else None
        return MiniBatch(input, target, valid if valid is not None else len(samples))
