"""DataSet abstraction.

Reference parity (SURVEY.md §2.2, expected ``<dl>/dataset/DataSet.scala`` — unverified):
``LocalDataSet`` (in-memory array + transformer chain) and ``DistributedDataSet`` (cached
per-partition RDD with in-place shuffle); factories ``DataSet.array``, ``DataSet.rdd``.

TPU-native: data preparation is host-side; the *distribution* concern moves out of the
dataset and into the trainer (which shards each MiniBatch over the mesh's data axis).
``DistributedDataSet`` here is a thin marker wrapper telling ``Optimizer`` to pick the
distributed training path, mirroring the reference's factory dispatch (SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.utils.random_generator import RandomGenerator


class AbstractDataSet:
    def size(self) -> int:
        raise NotImplementedError

    def shuffle(self) -> None:
        raise NotImplementedError

    def data(self, train: bool) -> Iterator:
        """One pass over the (transformed) data. Trainer handles epoch looping."""
        raise NotImplementedError

    def transform(self, transformer: Transformer) -> "AbstractDataSet":
        return TransformedDataSet(self, transformer)

    def __rshift__(self, transformer: Transformer) -> "AbstractDataSet":
        """``dataset >> transformer`` — the reference's ``dataset -> transformer``."""
        return self.transform(transformer)


class LocalDataSet(AbstractDataSet):
    def __init__(self, data: Sequence):
        self._data = list(data)
        self._order = np.arange(len(self._data))

    def size(self) -> int:
        return len(self._data)

    def shuffle(self) -> None:
        perm = RandomGenerator.numpy().permutation(len(self._data))
        self._order = self._order[perm]

    def data(self, train: bool) -> Iterator:
        for i in self._order:
            yield self._data[i]


class TransformedDataSet(AbstractDataSet):
    """Dataset + transformer chain. ``data()`` routes through the parallel
    transform engine when ``BIGDL_DATA_WORKERS`` > 0: the whole
    TransformedDataSet spine is collapsed into one chain, consecutive
    element-wise stages fuse into single per-sample callables, and each fused
    run executes across a bounded worker pool with ordered delivery and
    per-sample deterministic randomness (``dataset/parallel.py``). With the
    knob unset (0), the classic serial generator chain runs unchanged."""

    def __init__(self, base: AbstractDataSet, transformer: Transformer):
        self.base = base
        self.transformer = transformer
        self._plan = None  # (workers, stage list) — executors persist across epochs

    def size(self) -> int:
        return self.base.size()

    def shuffle(self) -> None:
        self.base.shuffle()

    def _chain(self):
        """(innermost base, [transformers outward-in order]) — `ds >> a >> b`
        nests TransformedDataSets one transformer deep, so the whole spine
        must be gathered before fusion can see the full chain."""
        transformers, ds = [], self
        while isinstance(ds, TransformedDataSet):
            transformers.append(ds.transformer)
            ds = ds.base
        return ds, list(reversed(transformers))

    def data(self, train: bool) -> Iterator:
        from bigdl_tpu.dataset.parallel import data_workers, plan_stages
        workers = data_workers()
        if workers <= 0:
            return self.transformer(self.base.data(train))
        if self._plan is None or self._plan[0] != workers:
            base, chain = self._chain()
            self._plan = (workers, base, plan_stages(chain, workers))
        _, base, stages = self._plan
        it = base.data(train)
        for stage in stages:
            it = stage(it)
        return it

    def is_distributed(self) -> bool:
        return is_distributed(self.base)


class DistributedDataSet(LocalDataSet):
    """Marker dataset: train with DistriOptimizer over the device mesh."""


class DataSet:
    """Factory namespace (reference ``DataSet.array`` / ``DataSet.rdd`` /
    ``DataSet.imageFolder``)."""

    @staticmethod
    def array(data: Iterable, distributed: bool = False) -> AbstractDataSet:
        return DistributedDataSet(list(data)) if distributed else LocalDataSet(list(data))

    @staticmethod
    def image_folder(root: str, num_workers: int = 8, one_based: bool = False,
                     distributed: bool = False) -> AbstractDataSet:
        """On-disk ``root/<class>/<image>`` source streaming ImageFeatures
        (dataset/image_folder.py) — compose vision transformers + SampleToMiniBatch."""
        from bigdl_tpu.dataset.image_folder import ImageFolderDataSet
        return ImageFolderDataSet(root, num_workers=num_workers,
                                  one_based=one_based, distributed=distributed)

    @staticmethod
    def record_files(paths, decoder=None, num_workers: int = 8,
                     distributed: bool = False) -> AbstractDataSet:
        """Packed ``.bdlrec`` shards (dataset/recordio.py — the SeqFileFolder
        analog). ``decoder`` maps payload bytes → record; defaults to the
        image decoder (ImageFeature records)."""
        from bigdl_tpu.dataset.recordio import (
            RecordFileDataSet, image_record_decoder,
        )
        return RecordFileDataSet(paths, decoder or image_record_decoder,
                                 num_workers=num_workers,
                                 distributed=distributed)

    @staticmethod
    def stream_shards(paths, decoder=None, shuffle_window=None,
                      num_workers: int = 8, cache: Optional[bool] = None,
                      cache_dir: Optional[str] = None,
                      distributed: bool = False) -> AbstractDataSet:
        """Sharded record stream (dataset/streaming.py): ``.bdlrec`` or
        uncompressed ``.tar`` shard lists with deterministic window shuffle,
        a checkpointable iterator position, per-host ``shard()`` assignment,
        and the decoded-sample mmap cache."""
        from bigdl_tpu.dataset.streaming import StreamingDataSet
        return StreamingDataSet(paths, decoder=decoder,
                                shuffle_window=shuffle_window,
                                num_workers=num_workers, cache=cache,
                                cache_dir=cache_dir, distributed=distributed)


def is_distributed(dataset: AbstractDataSet) -> bool:
    if isinstance(dataset, DistributedDataSet):
        return True
    if isinstance(dataset, TransformedDataSet):
        return dataset.is_distributed()
    return bool(getattr(dataset, "distributed", False))
