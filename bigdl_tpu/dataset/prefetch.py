"""Background batch pipeline — overlap host data work with device compute.

Reference parity (SURVEY.md §7.4): the reference leans on Spark to materialise partitions
ahead of the training loop; its per-iteration cost hides batch assembly behind cluster
scheduling. On TPU the analog is a host-side producer thread: while the chip executes step
``k`` (dispatch is async), the producer decodes/stacks batch ``k+1`` **and** starts its
host→device transfer, so the step loop never waits on the feed in steady state. This is
SURVEY §7.4's named "most likely real-world bottleneck" for the ResNet-50 north star.

Design:
- ``PrefetchingFeed`` wraps a fresh dataset iterator per epoch. A daemon producer thread
  pulls ``MiniBatch``es, calls ``put_fn`` (the trainer's sharding-aware ``device_put``)
  and parks up to ``depth`` placed batches in a bounded queue. ``device_put`` only
  *enqueues* a DMA, so the producer is never blocked on the device — the queue depth
  bounds device-memory overcommit to ``depth`` batches.
- ``window > 1`` assembles fused-dispatch training windows: the producer groups
  ``window`` consecutive batches and hands the LIST to ``put_fn`` (the trainer stacks
  them into a device super-batch with a leading scan axis). The trailing partial group
  at epoch end is delivered as a shorter list — the trainer falls back to per-step
  dispatch for it. Queue items are ``(batches, placed)`` either way; with windowing,
  ``batches`` is a list.
- ``train=False`` selects eval-window semantics: the trailing partial group is split
  into SINGLE-batch groups instead of one shorter list. An eval consumer then sees
  exactly two static shapes — the full K-window (fused scan program) and the single
  batch (per-batch program) — so a ragged tail never forces a fresh XLA compile per
  distinct tail length the way stacking a variable-K remainder would.
- Exceptions in the producer surface in the consumer (training loop) with their original
  traceback as ``__cause__``.
- ``close()`` (also on ``__exit__`` / generator abandonment) stops the producer promptly —
  mid-epoch breaks (endWhen triggers) must not leak threads. The hand-off queue is
  condition-based (``utils.queues.ClosableQueue``, shared with the serving
  request plane): a producer blocked on a full queue wakes the
  instant ``close()`` fires instead of busy-polling a 100 ms put-timeout, so close()
  latency is microseconds and an idle full queue burns zero wakeups. A producer that
  fails to join within the timeout is logged loudly and remembered, so the NEXT
  ``__iter__`` can say which earlier epoch leaked it.
- ``depth=0`` degrades to fully synchronous iteration (debug / determinism studies).
"""

from __future__ import annotations

import itertools
import logging
import threading
from typing import Callable, Iterator

from bigdl_tpu.obs import trace
from bigdl_tpu.utils.queues import CLOSED as _CLOSED
from bigdl_tpu.utils.queues import ClosableQueue as _ClosableQueue

logger = logging.getLogger("bigdl_tpu.dataset")

_END = object()


class PrefetchingFeed:
    """Iterate ``(batch, placed)`` pairs with a background producer.

    ``make_iter``: zero-arg callable returning the epoch's batch iterator.
    ``put_fn``: MiniBatch → device-placed pytree (e.g. trainer's ``_put_batch``);
    with ``window > 1`` it receives a LIST of up to ``window`` MiniBatches instead.
    ``depth``: producer queue bound (placed batches in flight); 0 = synchronous.
    ``window``: fused-dispatch group size; 1 (default) feeds single batches.
    ``train``: window-tail policy — True delivers the trailing partial group as
    one shorter list (trainer falls back per-step); False (eval mode) splits it
    into single-batch groups so eval programs keep exactly two static shapes.
    """

    #: close() waits this long for the producer before declaring it leaked
    JOIN_TIMEOUT = 5.0

    def __init__(self, make_iter: Callable[[], Iterator], put_fn: Callable,
                 depth: int = 2, window: int = 1, train: bool = True):
        if depth < 0:
            raise ValueError(f"depth must be >= 0, got {depth}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.make_iter = make_iter
        self.put_fn = put_fn
        self.depth = depth
        self.window = window
        self.train = train
        self._queue: _ClosableQueue | None = None
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        self._leaked_thread: threading.Thread | None = None
        #: groups handed to the consumer THIS epoch — the feed-position the
        #: streamed-resume machinery records (items parked in the queue or
        #: in flight in the producer are deliberately NOT counted: resume
        #: replays from what the training loop actually consumed)
        self.delivered = 0

    # ------------------------------------------------------------- producer
    def _grouped(self, it):
        """Group the epoch iterator into ``window``-sized lists (trailing
        partial list included) when windowing; pass through otherwise. Eval
        mode (``train=False``) splits the partial tail into singleton groups
        instead — two static shapes total for the consumer's programs."""
        if self.window == 1:
            return it
        groups = iter(lambda: list(itertools.islice(it, self.window)), [])
        if self.train:
            return groups

        def eval_groups():
            for group in groups:
                if len(group) == self.window:
                    yield group
                else:
                    for batch in group:
                        yield [batch]

        return eval_groups()

    def _produce(self, it, q: _ClosableQueue, stop: threading.Event) -> None:
        try:
            for batch in self._grouped(it):
                if stop.is_set():
                    return
                # producer-thread span: batch assembly + device placement
                # (h2d nests inside via the trainer's feed/h2d span)
                with trace.span("feed/put_batch"):
                    placed = self.put_fn(batch)
                # a False put means close() fired — the consumer is gone, so
                # dropping the item is the only non-deadlocking option
                if not q.put((batch, placed)) or stop.is_set():
                    return
            q.put(_END)
        except BaseException as e:  # surfaced in the consumer
            q.put(e)

    # ------------------------------------------------------------- consumer
    def __iter__(self):
        leaked = self._leaked_thread
        if leaked is not None and leaked.is_alive():
            # breadcrumb from an earlier close() that timed out: the producer
            # is still running (likely wedged in put_fn / dataset IO) and its
            # queue references are gone — say so instead of silently stacking
            # another thread on top of it
            logger.warning(
                "PrefetchingFeed: previously leaked producer thread %r is "
                "still alive; a prior close() timed out. Starting a new "
                "producer anyway — if this recurs, the put_fn or dataset "
                "iterator is blocking indefinitely.", leaked.name)
        elif leaked is not None:
            self._leaked_thread = None  # it eventually finished; forget it
        self.delivered = 0
        if self.depth == 0:
            for batch in self._grouped(self.make_iter()):
                placed = self.put_fn(batch)
                self.delivered += 1
                yield batch, placed
            return
        self._stop = threading.Event()
        self._queue = _ClosableQueue(maxsize=self.depth)
        self._thread = threading.Thread(
            target=self._produce, args=(self.make_iter(), self._queue, self._stop),
            name="bigdl-prefetch" if self.train else "bigdl-prefetch-eval",
            daemon=True)
        self._thread.start()
        try:
            while True:
                item = self._queue.get()
                if item is _END or item is _CLOSED:
                    return
                if isinstance(item, BaseException):
                    # re-raise the producer's exception with its original type
                    # (trainer retry/divisibility contracts depend on it); the
                    # producer traceback is already attached to the object
                    raise item
                self.delivered += 1
                yield item
        finally:
            self.close()

    def position(self) -> dict:
        """Feed position for checkpoint payloads / diagnostics: how many
        groups (batches, or ``window``-sized lists) the consumer pulled this
        epoch. Multiply by ``window`` for a batch-granular upper bound."""
        return {"delivered": self.delivered, "window": self.window}

    def close(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._queue is not None:
            # wakes a producer blocked on put() immediately (no poll interval)
            self._queue.close()
        if self._thread is not None:
            self._thread.join(timeout=self.JOIN_TIMEOUT)
            if self._thread.is_alive():
                # the producer did not stop: it is wedged somewhere that
                # ignores the stop event (device_put, dataset IO). Leaking a
                # daemon thread is survivable but must not be silent.
                logger.warning(
                    "PrefetchingFeed.close: producer thread %r did not join "
                    "within %.1fs and was leaked (daemon). It is likely "
                    "blocked in put_fn or the dataset iterator.",
                    self._thread.name, self.JOIN_TIMEOUT)
                self._leaked_thread = self._thread
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
