from bigdl_tpu.dataset.dataset import (
    AbstractDataSet, DataSet, DistributedDataSet, LocalDataSet, TransformedDataSet,
    is_distributed,
)
from bigdl_tpu.dataset.parallel import ParallelTransformer, data_workers, plan_stages
from bigdl_tpu.dataset.profiling import feed_stats, stage_deltas_ms
from bigdl_tpu.dataset.sample import MiniBatch, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.sample_cache import CacheCorruptError, SampleCache
from bigdl_tpu.dataset.streaming import StreamingDataSet
from bigdl_tpu.dataset.transformer import (
    ChainedTransformer, FusedTransformer, Identity, MapTransformer, Transformer,
    flatten_chain, fuse_chain, sample_index_scope,
)
from bigdl_tpu.dataset.text import (
    Dictionary, LabeledSentenceToSample, SentenceTokenizer, TextToLabeledSentence,
)
