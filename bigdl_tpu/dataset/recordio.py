"""Packed record files — the SeqFileFolder analog for dataset-scale IO.

Reference parity (SURVEY.md §2.2, expected ``<dl>/dataset/DataSet.scala``
``SeqFileFolder`` — unverified): the reference feeds ImageNet from Hadoop
sequence files — few large contiguous files instead of a million tiny JPEGs —
because sequential reads of packed records are the only way the feed keeps up
at cluster scale. Same physics on a TPU pod host: this module is that packed
format without the Hadoop dependency.

Format (``.bdlrec``): ``BDLR`` magic + u32 version, then per record
``u32 payload_len | u32 crc32(payload) | payload``. The reader scans offsets
once at open (sequential, cheap), shuffles at RECORD granularity via the
index permutation, verifies CRCs on read (fail loudly on truncation/bit-rot),
and decodes through a caller-supplied ``decoder(bytes) -> Sample/record``
off-thread with a bounded in-order window — the same decode-parallelism
pattern as the image-folder source. Shard a dataset over several ``.bdlrec``
files and pass them all; multi-host runs give each process its own file
subset (the reference's partition-per-executor layout).

``write_image_records`` / the default ``image_record_decoder`` pack
(label, encoded-image bytes) pairs so an ImageFolder tree converts to packed
shards once and streams fast forever after.
"""

from __future__ import annotations

import io
import os
import struct
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.profiling import STAGE_DECODE, feed_stats
from bigdl_tpu.dataset.resilience import run_guarded
from bigdl_tpu.obs import trace
from bigdl_tpu.utils.faults import SITE_DECODE, fault_point
from bigdl_tpu.utils.random_generator import RandomGenerator

_MAGIC = b"BDLR"
_VERSION = 1
_HEADER = struct.Struct("<4sI")
_REC = struct.Struct("<II")


class RecordIOError(Exception):
    pass


class RecordWriter:
    """Append-only writer for one ``.bdlrec`` shard."""

    def __init__(self, path: str):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "wb")
        self._f.write(_HEADER.pack(_MAGIC, _VERSION))
        self.count = 0

    def write(self, payload: bytes) -> None:
        self._f.write(_REC.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self.count += 1

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, payloads: Iterable[bytes]) -> int:
    with RecordWriter(path) as w:
        for p in payloads:
            w.write(p)
        return w.count


def _scan_index(path: str) -> list[tuple[int, int]]:
    """One sequential pass → [(offset, length)] of every record payload."""
    index = []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        head = f.read(_HEADER.size)
        if len(head) < _HEADER.size:
            raise RecordIOError(f"{path}: truncated header")
        magic, version = _HEADER.unpack(head)
        if magic != _MAGIC:
            raise RecordIOError(f"{path}: not a .bdlrec file")
        if version > _VERSION:
            raise RecordIOError(
                f"{path}: written by newer format version {version}")
        pos = _HEADER.size
        while pos < size:
            rec = f.read(_REC.size)
            if len(rec) < _REC.size:
                raise RecordIOError(f"{path}: truncated record header @ {pos}")
            length, _ = _REC.unpack(rec)
            payload_pos = pos + _REC.size
            if payload_pos + length > size:
                raise RecordIOError(f"{path}: truncated payload @ {pos}")
            index.append((pos, length))
            f.seek(length, os.SEEK_CUR)
            pos = payload_pos + length
    return index


class RecordFileDataSet(AbstractDataSet):
    """Streams decoded records from one or more ``.bdlrec`` shards."""

    def __init__(self, paths: Sequence[str] | str,
                 decoder: Callable[[bytes], object],
                 num_workers: int = 8, distributed: bool = False,
                 cache: Optional[bool] = None,
                 cache_dir: Optional[str] = None):
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        if not self.paths:
            raise ValueError("no record files given")
        self.decoder = decoder
        self.num_workers = max(int(num_workers), 1)
        self.distributed = distributed
        # decoded-sample cache (dataset/sample_cache.py): None defers to
        # BIGDL_SAMPLE_CACHE; instance persists across epochs
        self._cache_enabled = cache
        self._cache_dir = cache_dir
        self._cache = None
        # global index: (file idx, offset, length)
        self._index: list[tuple[int, int, int]] = []
        for fi, p in enumerate(self.paths):
            for off, ln in _scan_index(p):
                self._index.append((fi, off, ln))
        if not self._index:
            raise RecordIOError(f"no records in {self.paths}")
        self._order = np.arange(len(self._index))
        self._fds: dict[int, int] = {}
        self._ex: Optional[ThreadPoolExecutor] = None

    def size(self) -> int:
        return len(self._index)

    def _executor(self) -> ThreadPoolExecutor:
        """One decode pool per dataset, reused across epochs (see
        ``ImageFolderDataSet._executor`` — same per-epoch-leak fix)."""
        if self._ex is None:
            self._ex = ThreadPoolExecutor(self.num_workers,
                                          thread_name_prefix="bigdl-recordio")
        return self._ex

    def close(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=False, cancel_futures=True)
            self._ex = None

    def shuffle(self) -> None:
        perm = RandomGenerator.numpy().permutation(len(self._index))
        self._order = self._order[perm]

    def _fd(self, fi: int) -> int:
        fd = self._fds.get(fi)
        if fd is None:
            fd = os.open(self.paths[fi], os.O_RDONLY)
            self._fds[fi] = fd
        return fd

    def _read(self, i: int) -> bytes:
        # os.pread on a shared fd: positioned reads are thread-safe (no seek
        # state), so the decode pool reads concurrently without re-opening
        fi, off, ln = self._index[i]
        rec = os.pread(self._fd(fi), _REC.size + ln, off)
        length, crc = _REC.unpack(rec[:_REC.size])
        payload = rec[_REC.size:]
        if len(payload) != length or zlib.crc32(payload) != crc:
            raise RecordIOError(
                f"{self.paths[fi]}: corrupt record @ {off} (crc mismatch)")
        return payload

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
        for fd in getattr(self, "_fds", {}).values():
            try:
                os.close(fd)
            except OSError:
                pass

    def _load_one(self, i: int):
        fault_point(SITE_DECODE)  # scripted decode failure, if any
        t0 = time.perf_counter()
        with trace.span("feed/decode"):
            out = self.decoder(self._read(i))
        feed_stats.add(STAGE_DECODE, time.perf_counter() - t0)
        return out

    def _load(self, i: int):
        # corrupt-sample policy (BIGDL_BAD_SAMPLE_POLICY): a CRC-failing or
        # undecodable record can skip/retry instead of killing the feed
        return run_guarded("decode", self._load_one, i)

    def _cache_obj(self):
        from bigdl_tpu.dataset import sample_cache
        if self._cache is None and self._cache_enabled is not False:
            enabled = (sample_cache.cache_enabled()
                       if self._cache_enabled is None else True)
            if enabled:
                default_dir = os.path.join(
                    os.path.dirname(os.path.abspath(self.paths[0])),
                    ".bigdl-sample-cache")
                material = ("recordio.v1", tuple(self.paths),
                            tuple(os.path.getsize(p) for p in self.paths),
                            len(self._index),
                            getattr(self.decoder, "__qualname__",
                                    type(self.decoder).__name__))
                self._cache = sample_cache.SampleCache(
                    sample_cache.cache_dir(self._cache_dir or default_dir),
                    sample_cache.fingerprint(material), len(self._index))
        return self._cache

    def data(self, train: bool) -> Iterator:
        # cache-aware iteration (dataset/sample_cache.py): a committed cache
        # serves the epoch via mmap without touching the decode pool;
        # otherwise the sliding-window decode path builds the cache
        from bigdl_tpu.dataset.sample_cache import cached_data_iter

        def submit(i):
            return self._executor().submit(self._load, int(i))

        yield from cached_data_iter((int(i) for i in self._order), submit,
                                    self._cache_obj(), self.num_workers * 2)


# ------------------------------------------------------------- image packing
def encode_image_record(label: int, image_bytes: bytes) -> bytes:
    """(label, encoded image) → record payload (i32 label | image bytes)."""
    return struct.pack("<i", int(label)) + image_bytes


def image_record_decoder(payload: bytes):
    """Record payload → ImageFeature (HWC uint8 RGB + int label) — the same
    record type the image-folder source yields, so the vision transformer
    chain composes unchanged."""
    from PIL import Image as PILImage

    from bigdl_tpu.transform.vision.image import ImageFeature

    (label,) = struct.unpack("<i", payload[:4])
    with PILImage.open(io.BytesIO(payload[4:])) as img:
        arr = np.asarray(img.convert("RGB"))
    return ImageFeature(arr, label)


def write_image_records(image_folder_root: str, out_path: str,
                        shards: int = 1, one_based: bool = False) -> list[str]:
    """Pack an ImageFolder tree (class subdirs of images) into ``shards``
    ``.bdlrec`` files — the offline conversion the reference does with its
    Hadoop sequence-file generator. Returns the shard paths."""
    from bigdl_tpu.dataset.image_folder import ImageFolderDataSet

    src = ImageFolderDataSet(image_folder_root, one_based=one_based)
    paths = [out_path if shards == 1 else f"{out_path}.{s:05d}"
             for s in range(shards)]
    writers = [RecordWriter(p) for p in paths]
    try:
        for n, (path, label) in enumerate(src._items):
            with open(path, "rb") as f:
                writers[n % shards].write(encode_image_record(label, f.read()))
    finally:
        for w in writers:
            w.close()
    return paths
