"""Composable data transformers.

Reference parity (SURVEY.md §2.2, expected ``<dl>/dataset/Transformer.scala`` — unverified):
a ``Transformer[A, B]`` maps ``Iterator[A] → Iterator[B]`` and composes with ``->``.

TPU-native: plain Python iterator stages on the host (input pipelines stay off-device, as
upstream's stayed off-JVM-heap); composition uses ``>>`` (closest Python analog of ``->``)
or ``.chain``.

Chain fusion (the parallel-pipeline groundwork): most stages are ELEMENT-WISE —
one input record maps to exactly one output record with no cross-record state.
Such a stage can expose its per-element callable via :meth:`Transformer.element_fn`,
and :func:`fuse_chain` flattens a ``ChainedTransformer`` tree into maximal runs
of element-wise stages collapsed into ONE :class:`FusedTransformer` — a sample
then crosses the worker pool once instead of threading through N generator
layers. Stages that genuinely need the stream (``SampleToMiniBatch`` grouping)
return ``None`` from ``element_fn`` and stay serial stream stages.

Deterministic parallel randomness rides on :func:`sample_index_scope`: the
parallel engine tags each element with its position in the epoch stream, and
randomized transforms (``transform/vision/image.py``) derive a per-sample
``np.random.Generator`` from (pipeline seed, sample index) — so W workers are
bitwise-identical to one, regardless of completion order.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Callable, Iterable, Iterator, Optional


class Transformer:
    """Base: override ``__call__`` mapping an iterator to an iterator."""

    def __call__(self, prev: Iterator) -> Iterator:
        raise NotImplementedError

    def __rshift__(self, other: "Transformer") -> "ChainedTransformer":
        """``a >> b`` — the reference's ``a -> b`` composition."""
        return ChainedTransformer(self, other)

    def chain(self, other: "Transformer") -> "ChainedTransformer":
        return self >> other

    def apply(self, data: Iterable) -> Iterator:
        return self(iter(data))

    def element_fn(self) -> Optional[Callable[[Any], Any]]:
        """Per-element callable when this stage is element-wise (one record in,
        one record out, no cross-record state); ``None`` for stream stages
        (grouping/batching). Element-wise stages are eligible for chain fusion
        and parallel execution (``dataset/parallel.py``)."""
        return None


class ChainedTransformer(Transformer):
    def __init__(self, first: Transformer, second: Transformer):
        self.first, self.second = first, second

    def __call__(self, prev: Iterator) -> Iterator:
        return self.second(self.first(prev))

    def element_fn(self):
        f, g = self.first.element_fn(), self.second.element_fn()
        if f is None or g is None:
            return None
        return lambda x: g(f(x))


class MapTransformer(Transformer):
    """Lift an element-wise function into a Transformer."""

    def __init__(self, fn: Callable[[Any], Any]):
        self.fn = fn

    def __call__(self, prev: Iterator) -> Iterator:
        return (self.fn(x) for x in prev)

    def element_fn(self):
        return self.fn


class Identity(Transformer):
    def __call__(self, prev: Iterator) -> Iterator:
        return prev

    def element_fn(self):
        return lambda x: x


# ------------------------------------------------------------- chain fusion
def flatten_chain(transformer: Transformer) -> list:
    """Flatten a ``ChainedTransformer`` tree into its leaf stages, in order."""
    if isinstance(transformer, ChainedTransformer):
        return flatten_chain(transformer.first) + flatten_chain(transformer.second)
    return [transformer]


class FusedTransformer(Transformer):
    """Maximal run of element-wise stages collapsed into one per-element call.

    The fused callable applies every stage's element function in sequence, so
    a record crosses the (pool / generator) boundary ONCE per run instead of
    once per stage — the tf.data-style fused map (PAPERS.md 2101.12127)."""

    def __init__(self, stages: list):
        if not stages:
            raise ValueError("FusedTransformer needs at least one stage")
        self.stages = list(stages)
        fns = []
        for s in self.stages:
            fn = s.element_fn()
            if fn is None:
                raise ValueError(
                    f"stage {type(s).__name__} is not element-wise and "
                    f"cannot be fused")
            fns.append(fn)
        self._fns = fns

    def element_fn(self):
        fns = self._fns
        if len(fns) == 1:
            return fns[0]

        def fused(x):
            for fn in fns:
                x = fn(x)
            return x

        return fused

    def __call__(self, prev: Iterator) -> Iterator:
        fn = self.element_fn()
        return (fn(x) for x in prev)


def fuse_chain(transformer: Transformer) -> list:
    """Flatten ``transformer`` and collapse consecutive element-wise stages
    into :class:`FusedTransformer` runs. Returns the ordered stage list —
    stream stages (``element_fn() is None``) pass through unfused."""
    stages: list = []
    run: list = []

    def flush():
        if run:
            stages.append(run[0] if len(run) == 1 else FusedTransformer(run))
            run.clear()

    for stage in flatten_chain(transformer):
        if isinstance(stage, Identity):
            continue  # no-op stage: fusing it would only add a call frame
        if stage.element_fn() is not None:
            run.append(stage)
        else:
            flush()
            stages.append(stage)
    flush()
    return stages or [Identity()]


# ------------------------------------------- per-sample randomness context
_sample_ctx = threading.local()


def current_sample_index() -> Optional[int]:
    """Index of the sample being transformed in the current thread, when the
    parallel engine (or an explicit :func:`sample_index_scope`) set one."""
    return getattr(_sample_ctx, "index", None)


def current_sample_rng_cache() -> Optional[dict]:
    """Per-(thread, sample) generator cache — one ``np.random.Generator`` per
    transformer instance per sample, so multiple draws inside one
    ``transform_feature`` advance ONE stream instead of re-deriving it."""
    return getattr(_sample_ctx, "cache", None)


@contextmanager
def sample_index_scope(index: int):
    """Tag the current thread's transform work with ``index`` (position in the
    epoch stream). Randomized transforms then derive their draws from
    (pipeline seed, index) — deterministic regardless of worker count."""
    prev_index = getattr(_sample_ctx, "index", None)
    prev_cache = getattr(_sample_ctx, "cache", None)
    _sample_ctx.index = int(index)
    _sample_ctx.cache = {}
    try:
        yield
    finally:
        _sample_ctx.index = prev_index
        _sample_ctx.cache = prev_cache
