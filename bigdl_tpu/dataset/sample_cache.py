"""On-disk decoded-sample cache — decode once, mmap forever after.

The per-stage feed attribution (``dataset/profiling.py``) shows decode as the
dominant stage on real image workloads, and tf.data's production lesson
(PAPERS.md 2101.12127) is that host input work must disappear from the
critical path or the accelerator starves. This module removes the recurring
half of that work: the FIRST epoch writes every decoded record to a cache
file pair as it streams past, and every later epoch ``np.memmap``\\ s the
cache and never touches the decode pool at all — the ``decode`` stage drops
out of ``feed_stats`` and a ``cache`` stage (mmap read + copy) takes its
place.

Layout (one pair per dataset fingerprint, under ``BIGDL_SAMPLE_CACHE_DIR``
or a ``.bigdl-sample-cache/`` directory next to the source data):

- ``<key>.data`` — the raw little-endian array bytes of every record,
  concatenated. Written sequentially to a ``.tmp``, fsynced, atomically
  renamed (the ``utils/file.py`` durability protocol), whole-file CRC32
  recorded in the index and verified on first open.
- ``<key>.idx``  — ``utils.file.save()`` pickle (CRC32-footered, fsynced):
  record-id → (offset, per-array shape/dtype table, small meta dict), plus
  the data file's byte count and CRC.

Integrity is never trusted silently: a CRC mismatch, short mmap, or
unreadable index **quarantines** the pair as ``*.corrupt`` and the epoch
falls back to live decode with a loud ``cache_fallback`` robustness event —
never a crash. The ``cache_read`` / ``cache_write`` fault sites
(``utils/faults.py``) fire these paths deterministically in tests.

Cache completeness is all-or-nothing: the build commits only when every
record of the dataset was written this epoch (a preempted or corrupt-sample-
skipping epoch leaves no half-cache behind; the next full epoch rebuilds).
"""

from __future__ import annotations

import hashlib
import itertools
import logging
import os
import time
import zlib
from collections import deque
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from bigdl_tpu.dataset.profiling import STAGE_CACHE, feed_stats
from bigdl_tpu.dataset.resilience import SKIPPED
from bigdl_tpu.obs import trace
from bigdl_tpu.obs.registry import registry as _obs_registry
from bigdl_tpu.utils import file as ckpt_file
from bigdl_tpu.utils.faults import (
    SITE_CACHE_READ, SITE_CACHE_WRITE, check_fault, fault_point,
)
from bigdl_tpu.utils.robustness import events

logger = logging.getLogger("bigdl_tpu.dataset")

_IDX_VERSION = 1


class CacheCorruptError(RuntimeError):
    """A cache file pair failed an integrity check (CRC mismatch, short
    mmap, version skew, or an unreadable index)."""


# ------------------------------------------------------------------- knobs
def cache_enabled(default: bool = False) -> bool:
    """``BIGDL_SAMPLE_CACHE``: 1 enables the decoded-sample cache for every
    cache-aware dataset source (streaming / image folder / recordio)."""
    raw = os.environ.get("BIGDL_SAMPLE_CACHE", "").strip()
    if raw == "":
        return default
    return raw not in ("0", "false", "no")


def cache_dir(default_dir: str) -> str:
    """``BIGDL_SAMPLE_CACHE_DIR`` overrides the per-dataset default (a
    ``.bigdl-sample-cache/`` directory next to the source data)."""
    return os.environ.get("BIGDL_SAMPLE_CACHE_DIR", "").strip() or default_dir


def fingerprint(material) -> str:
    """Stable cache key from dataset identity material (shard paths, sizes,
    record counts, decoder name...). Anything repr-stable works."""
    h = hashlib.sha1()
    h.update(repr(material).encode())
    return h.hexdigest()[:16]


# ------------------------------------------------------------------- codec
def encode_record(rec) -> tuple[list[np.ndarray], dict]:
    """Record → (arrays, small picklable meta). Supports the record types
    the cache-aware sources yield: ``ImageFeature`` (decoded image + label),
    ``Sample`` (feature/label tensors), and bare ndarrays."""
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.transform.vision.image import ImageFeature

    if isinstance(rec, ImageFeature):
        return [np.asarray(rec.image)], {
            "k": "imf", "label": rec.get(ImageFeature.LABEL),
            "uri": rec.get(ImageFeature.URI)}
    if isinstance(rec, Sample):
        return [np.asarray(a) for a in (*rec.feature, *rec.label)], {
            "k": "smp", "nf": len(rec.feature)}
    if isinstance(rec, np.ndarray):
        return [rec], {"k": "arr"}
    raise TypeError(
        f"record type {type(rec).__name__} is not cacheable (ImageFeature, "
        f"Sample, or ndarray)")


def decode_record(arrays: list[np.ndarray], meta: dict):
    """Inverse of :func:`encode_record` — reconstructs a record equal to the
    freshly-decoded one."""
    kind = meta["k"]
    if kind == "imf":
        from bigdl_tpu.transform.vision.image import ImageFeature
        return ImageFeature(arrays[0], meta.get("label"), uri=meta.get("uri"))
    if kind == "smp":
        from bigdl_tpu.dataset.sample import Sample
        nf = int(meta["nf"])
        return Sample(list(arrays[:nf]), list(arrays[nf:]) or None)
    if kind == "arr":
        return arrays[0]
    raise CacheCorruptError(f"unknown cache record kind {kind!r}")


# ------------------------------------------------------------------- cache
class SampleCache:
    """One dataset's decoded-record cache: a committed pair serves warm
    epochs via mmap; an uncommitted one accepts a single-epoch build."""

    def __init__(self, directory: str, key: str, n_records: int):
        self.dir = directory
        self.key = key
        self.n_records = int(n_records)
        self.data_path = os.path.join(directory, f"{key}.data")
        self.idx_path = os.path.join(directory, f"{key}.idx")
        self._entries: Optional[dict] = None   # gid -> (offset, specs, meta)
        self._mm: Optional[np.memmap] = None
        self._verified = False
        self._dead = False        # quarantined/unusable for this process

    # ---------------------------------------------------------------- open
    def try_open(self) -> bool:
        """True when a committed, integrity-verified cache is mmapped and
        ready to serve. A failed check quarantines the pair (loudly) and
        returns False — the caller decodes live instead."""
        if self._dead:
            return False
        if self._mm is not None:
            return True
        if not (os.path.exists(self.idx_path)
                and os.path.exists(self.data_path)):
            return False
        try:
            idx = ckpt_file.load(self.idx_path)
            if idx.get("version") != _IDX_VERSION:
                raise CacheCorruptError(
                    f"{self.idx_path}: cache index version "
                    f"{idx.get('version')!r} != {_IDX_VERSION}")
            if idx.get("n_records") != self.n_records:
                raise CacheCorruptError(
                    f"{self.idx_path}: cache built for {idx.get('n_records')} "
                    f"records, dataset has {self.n_records}")
            size = os.path.getsize(self.data_path)
            if size != idx["data_bytes"]:
                raise CacheCorruptError(
                    f"{self.data_path}: short mmap — {size} bytes on disk, "
                    f"index says {idx['data_bytes']}")
            mm = np.memmap(self.data_path, dtype=np.uint8, mode="r")
            if not self._verified:
                actual = zlib.crc32(mm)
                if actual != idx["data_crc"]:
                    raise CacheCorruptError(
                        f"{self.data_path}: CRC mismatch (expected "
                        f"{idx['data_crc']:#010x}, got {actual:#010x})")
                self._verified = True
            self._entries = idx["entries"]
            self._mm = mm
            return True
        except (OSError, ckpt_file.CheckpointCorruptError, CacheCorruptError,
                KeyError, TypeError, ValueError) as e:
            self.quarantine(str(e))
            return False

    @property
    def complete(self) -> bool:
        """A committed pair exists on disk (not yet necessarily verified)."""
        return (not self._dead and os.path.exists(self.idx_path)
                and os.path.exists(self.data_path))

    # ---------------------------------------------------------------- read
    def read(self, gid: int):
        """One record from the mmap. Raises :class:`CacheCorruptError` on
        any inconsistency (including a scripted ``cache_read`` fault) — the
        iteration driver answers with quarantine-and-redecode."""
        t0 = time.perf_counter()
        with trace.span("feed/cache_read"):
            # non-raising poll: ANY scripted action at this site models a
            # corrupt read, which must route through quarantine, not crash
            action = check_fault(SITE_CACHE_READ)
            if action is not None:
                raise CacheCorruptError(
                    f"{self.data_path}: injected cache_read fault "
                    f"({action})")
            entry = self._entries.get(int(gid)) if self._entries else None
            if entry is None:
                raise CacheCorruptError(
                    f"{self.data_path}: record {gid} missing from cache index")
            offset, specs, meta = entry
            arrays = []
            nbytes_total = 0
            for shape, dtype_str, nbytes in specs:
                if offset + nbytes > self._mm.size:
                    raise CacheCorruptError(
                        f"{self.data_path}: record {gid} extends past end of "
                        f"data file")
                # copy out of the mmap: downstream transforms may mutate
                # in place, and a copy keeps the page-in cost while freeing
                # the read-only constraint
                arr = np.frombuffer(self._mm, dtype=np.dtype(dtype_str),
                                    count=int(np.prod(shape, dtype=np.int64))
                                    if shape else 1,
                                    offset=offset).reshape(shape).copy()
                arrays.append(arr)
                offset += nbytes
                nbytes_total += nbytes
            rec = decode_record(arrays, meta)
        feed_stats.add(STAGE_CACHE, time.perf_counter() - t0)
        _obs_registry.counter("feed/cache_hit").inc()
        _obs_registry.counter("feed/cache_bytes").inc(nbytes_total)
        return rec

    # ---------------------------------------------------------------- build
    def start_build(self) -> Optional["_CacheWriter"]:
        """A writer for this epoch's build, or None when building is not
        possible (already complete, quarantined, or the directory is not
        writable)."""
        if self._dead or self.complete:
            return None
        try:
            os.makedirs(self.dir, exist_ok=True)
            return _CacheWriter(self)
        except OSError as e:
            logger.warning("sample cache: cannot build under %s (%s); "
                           "continuing uncached", self.dir, e)
            return None

    # ----------------------------------------------------------- quarantine
    def quarantine(self, reason: str) -> None:
        """Move the pair aside as ``*.corrupt`` and mark the cache unusable
        for this process. The epoch that hit this falls back to live decode;
        the NEXT process/run rebuilds from scratch."""
        self._dead = True
        self._mm = None
        self._entries = None
        moved = []
        for p in (self.data_path, self.idx_path):
            if os.path.exists(p):
                try:
                    os.replace(p, p + ".corrupt")
                    moved.append(p + ".corrupt")
                except OSError:
                    pass
        events.record("cache_fallback", reason=reason[:200], files=moved)
        logger.error(
            "sample cache corrupt — quarantined %s and falling back to live "
            "decode for this run: %s", moved or [self.data_path], reason)

    def close(self) -> None:
        self._mm = None
        self._entries = None


class _CacheWriter:
    """Single-epoch cache build: append records as they stream past, commit
    only when every record landed. Never raises into the feed — any write
    failure (including a scripted ``cache_write`` fault) abandons the build
    with a ``cache_write_failed`` event and training continues uncached."""

    def __init__(self, cache: SampleCache):
        self.cache = cache
        self.tmp_path = cache.data_path + ".tmp"
        self._f = open(self.tmp_path, "wb")
        self._entries: dict = {}
        self._offset = 0
        self._crc = 0
        self._dead_reason: Optional[str] = None

    def put(self, gid: int, rec) -> None:
        if self._dead_reason is not None:
            return
        try:
            with trace.span("feed/cache_write"):
                fault_point(SITE_CACHE_WRITE)
                arrays, meta = encode_record(rec)
                specs = []
                offset = self._offset
                for a in arrays:
                    buf = np.ascontiguousarray(a).tobytes()
                    self._f.write(buf)
                    self._crc = zlib.crc32(buf, self._crc)
                    specs.append((tuple(a.shape), a.dtype.str, len(buf)))
                    self._offset += len(buf)
                self._entries[int(gid)] = (offset, specs, meta)
        except Exception as e:  # build is best-effort; the feed must not die
            self._fail(f"{type(e).__name__}: {e}")

    def _fail(self, reason: str) -> None:
        self._dead_reason = reason
        events.record("cache_write_failed", reason=reason[:200])
        logger.warning("sample cache build abandoned (%s); training "
                       "continues uncached", reason)
        self._discard()

    def _discard(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
        try:
            os.remove(self.tmp_path)
        except OSError:
            pass

    def commit(self) -> bool:
        """Finalize IF the build is complete (every record written). The
        data file is fsynced before the atomic rename and the index rides
        ``utils.file.save`` (CRC footer + fsync + dir fsync), so a torn
        commit can never present a half-cache as valid."""
        if self._dead_reason is not None:
            return False
        if len(self._entries) != self.cache.n_records:
            # a skip-policy drop or a partial epoch: no half-caches
            logger.info(
                "sample cache build incomplete (%d/%d records); discarding — "
                "the next full epoch rebuilds", len(self._entries),
                self.cache.n_records)
            self._discard()
            return False
        try:
            self._f.flush()
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()
            os.replace(self.tmp_path, self.cache.data_path)
            ckpt_file.save({
                "version": _IDX_VERSION,
                "n_records": self.cache.n_records,
                "data_bytes": self._offset,
                "data_crc": self._crc,
                "entries": self._entries,
            }, self.cache.idx_path)
            logger.info("sample cache committed: %d records, %.1f MB → %s",
                        self.cache.n_records, self._offset / 2 ** 20,
                        self.cache.data_path)
            return True
        except OSError as e:
            self._fail(f"commit failed: {e}")
            return False

    def abort(self) -> None:
        if self._dead_reason is None:
            self._discard()
            self._dead_reason = "aborted"


# -------------------------------------------------------------- iteration
def cached_data_iter(indices: Iterable[int],
                     decode_submit: Callable,
                     cache: Optional[SampleCache],
                     depth: int) -> Iterator:
    """Drive one epoch over ``indices`` (global record ids) through the
    cache when possible, the decode pool otherwise — the shared iteration
    engine behind every cache-aware source.

    Warm path (committed cache): inline mmap reads, the decode pool is never
    touched. Any integrity failure mid-epoch quarantines the cache and the
    CURRENT record plus everything after it falls back to live decode —
    records already yielded stay valid, nothing crashes.

    Cold path: the classic ordered sliding window of decode futures
    (bounded memory, preserved order), building the cache when a writer is
    available. ``decode_submit(gid)`` returns a Future resolving to the
    record or :data:`~bigdl_tpu.dataset.resilience.SKIPPED`.
    """
    it = iter(indices)
    if cache is not None and cache.try_open():
        for gid in it:
            try:
                rec = cache.read(gid)
            except CacheCorruptError as e:
                cache.quarantine(str(e))
                it = itertools.chain([gid], it)  # redecode from right here
                break
            yield rec
        else:
            return  # whole epoch served warm
    writer = cache.start_build() if cache is not None else None
    window: deque = deque()
    clean = False

    def resolve(gid, fut):
        out = fut.result()
        if out is SKIPPED:
            if writer is not None:
                writer._fail("record skipped by corrupt-sample policy")
        elif writer is not None:
            writer.put(gid, out)
        return out

    try:
        for gid in it:
            window.append((gid, decode_submit(gid)))
            if len(window) >= depth:
                out = resolve(*window.popleft())
                if out is not SKIPPED:
                    yield out
        while window:
            out = resolve(*window.popleft())
            if out is not SKIPPED:
                yield out
        clean = True
    finally:
        for _, f in window:
            f.cancel()
        if writer is not None:
            if clean:
                writer.commit()
            else:
                writer.abort()
