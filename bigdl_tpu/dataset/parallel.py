"""Parallel host transform execution — multi-worker element-wise pipelines.

SURVEY §7.4 names the host input pipeline the "most likely real-world
bottleneck" for the ResNet-50 north star: every vision FeatureTransformer and
``SampleToMiniBatch`` stack used to run serially inside the single
``PrefetchingFeed`` producer thread, while the reference leaned on Spark
partitions for host parallelism. This module is the TPU-native replacement:

- :func:`plan_stages` takes a transformer chain, fuses consecutive
  element-wise stages (``transformer.fuse_chain``) and wraps each fused run in
  a :class:`ParallelTransformer` — a bounded thread-pool map with ORDERED
  delivery. Threads, not processes: PIL decode/resize and numpy ufuncs release
  the GIL, so the heavy per-image work genuinely overlaps.
- Deterministic parallel randomness: each element is executed under
  ``sample_index_scope(i)`` so randomized transforms draw from a per-sample
  generator derived from (pipeline seed, sample index) — W workers are
  bitwise-identical to 1 regardless of completion order.
- Exceptions raised in a worker surface at the consuming ``next()`` with the
  worker's original traceback (concurrent.futures preserves it), mirroring the
  PrefetchingFeed producer contract.
- ``BIGDL_DATA_WORKERS`` selects the worker count process-wide: ``0``
  (default) keeps the classic serial generator chain byte-for-byte, ``auto``
  sizes to the host CPUs, N >= 1 runs the parallel engine with N workers.

Stream stages (``element_fn() is None`` — batching) stay serial between the
parallel runs, preserving stream semantics exactly.
"""

from __future__ import annotations

import logging
import os
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional, Sequence

from bigdl_tpu.dataset.profiling import STAGE_AUGMENT, feed_stats
from bigdl_tpu.dataset.resilience import SKIPPED, run_guarded
from bigdl_tpu.obs import trace
from bigdl_tpu.dataset.transformer import (
    FusedTransformer, Transformer, fuse_chain, sample_index_scope,
)
from bigdl_tpu.utils.faults import (
    SITE_TRANSFORM_WORKER, WorkerDeathError, fault_point,
)
from bigdl_tpu.utils.robustness import events

logger = logging.getLogger("bigdl_tpu.dataset")

#: upper bound for BIGDL_DATA_WORKERS=auto — beyond this the GIL'd fraction of
#: the per-image work dominates and extra threads only add contention
_AUTO_CAP = 8


def worker_crash_budget(default: int = 2) -> int:
    """``BIGDL_WORKER_CRASH_BUDGET``: transform-worker deaths absorbed per
    :class:`ParallelTransformer` (pool respawn + in-place re-execution)
    before the death propagates to the consumer."""
    return max(0, int(os.environ.get("BIGDL_WORKER_CRASH_BUDGET",
                                     str(default))))


def data_workers(default: int = 0) -> int:
    """Resolve ``BIGDL_DATA_WORKERS``: 0 = serial legacy path, ``auto`` =
    host-sized (cpu count capped at 8), N = that many workers."""
    raw = os.environ.get("BIGDL_DATA_WORKERS", "").strip().lower()
    if raw == "":
        return default
    if raw == "auto":
        return max(1, min(os.cpu_count() or 1, _AUTO_CAP))
    try:
        v = int(raw)
        if v < 0:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"BIGDL_DATA_WORKERS must be a non-negative integer or 'auto', "
            f"got {raw!r}") from None
    return v


class ParallelTransformer(Transformer):
    """Run an element-wise transformer across a bounded worker pool.

    Ordered delivery via a sliding window of futures (the same pattern as the
    decode pools in ``image_folder``/``recordio``): up to
    ``window = 2 * num_workers`` elements are in flight, results yield in
    submission order, and backpressure comes from the window bound — memory
    stays O(window) however fast the workers are.

    The executor is created lazily and REUSED across epochs (``__call__``
    invocations); ``close()``/GC shuts it down. One instance therefore costs
    ``num_workers`` threads for the life of the dataset, not per epoch.
    """

    def __init__(self, inner: Transformer, num_workers: int,
                 window: Optional[int] = None):
        fn = inner.element_fn()
        if fn is None:
            raise ValueError(
                f"{type(inner).__name__} is not element-wise; only "
                f"element_fn-bearing transformers can run parallel")
        self.inner = inner
        self._fn = fn
        self.num_workers = max(1, int(num_workers))
        self.window = int(window) if window else 2 * self.num_workers
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        self._ex: Optional[ThreadPoolExecutor] = None
        self._crashes = 0  # worker deaths absorbed so far (crash budget)

    def element_fn(self):
        # parallelism is an execution property, not a semantic one: the stage
        # still maps one element to one element (lets plans compose/refuse)
        return self._fn

    def _executor(self) -> ThreadPoolExecutor:
        if self._ex is None:
            self._ex = ThreadPoolExecutor(
                self.num_workers, thread_name_prefix="bigdl-data")
        return self._ex

    def _apply(self, index: int, item):
        fault_point(SITE_TRANSFORM_WORKER)  # scripted worker death, if any
        # worker-thread spans: the stage span wraps the per-element work span
        # so the trace shows transform workers nested under their stage
        with trace.span("feed/transform"):
            t0 = time.perf_counter()
            with sample_index_scope(index), trace.span("feed/augment"):
                out = run_guarded("transform", self._fn, item)
            feed_stats.add(STAGE_AUGMENT, time.perf_counter() - t0)
        return out

    def __call__(self, prev: Iterator) -> Iterator:
        return self._gen(prev)

    def _result(self, fut, index: int, item):
        """Resolve one ordered-window future. A worker death (simulated thread
        loss) is absorbed by the crash budget: the pool is respawned for
        future submissions and THIS element re-executes in place — under
        ``sample_index_scope`` the redo is bitwise-identical, so degraded
        epochs stay deterministic. Past the budget the death propagates."""
        try:
            return fut.result()
        except WorkerDeathError:
            self._crashes += 1
            budget = worker_crash_budget()
            events.record("worker_respawn", crashes=self._crashes,
                          budget=budget)
            if self._crashes > budget:
                logger.error(
                    "ParallelTransformer: worker crash budget exhausted "
                    "(%d > %d); propagating", self._crashes, budget)
                raise
            logger.warning(
                "ParallelTransformer: worker died (%d/%d absorbed); "
                "respawning pool and re-executing element %d",
                self._crashes, budget, index)
            self._respawn()
            return self._apply(index, item)

    def _respawn(self) -> None:
        """Retire the current executor (in-flight futures drain naturally —
        their threads are unaffected) and let the next submission build a
        fresh pool."""
        old, self._ex = self._ex, None
        if old is not None:
            old.shutdown(wait=False)

    def _gen(self, prev: Iterator):
        window: deque = deque()  # (future, index, item) in submission order
        try:
            for index, item in enumerate(prev):
                window.append(
                    (self._executor().submit(self._apply, index, item),
                     index, item))
                if len(window) >= self.window:
                    # result() re-raises a worker exception with the worker's
                    # original traceback attached — the consumer sees WHERE in
                    # the transform chain it blew up, not just that it did
                    out = self._result(*window.popleft())
                    if out is not SKIPPED:  # corrupt-sample policy drop
                        yield out
            while window:
                out = self._result(*window.popleft())
                if out is not SKIPPED:
                    yield out
        finally:
            # abandoned mid-epoch (endWhen break): drop queued work, keep the
            # pool — running tasks finish and are discarded
            for f, _, _ in window:
                f.cancel()

    def close(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=False, cancel_futures=True)
            self._ex = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def plan_stages(transformers: Sequence[Transformer],
                num_workers: int) -> list:
    """Build the execution plan for a transformer chain: fuse element-wise
    runs, wrap each fused run in a :class:`ParallelTransformer` with
    ``num_workers`` workers, keep stream stages serial in between.

    ``num_workers <= 0`` returns the chain unmodified (the serial path)."""
    chained = None
    for t in transformers:
        chained = t if chained is None else chained >> t
    if chained is None:
        return []
    if num_workers <= 0:
        return [chained]
    stages = []
    for stage in fuse_chain(chained):
        if stage.element_fn() is not None:
            stages.append(ParallelTransformer(stage, num_workers))
        else:
            stages.append(stage)
    return stages


def fused_stage_count(plan: list) -> int:
    """How many element-wise stages the plan collapsed (diagnostics)."""
    n = 0
    for stage in plan:
        inner = getattr(stage, "inner", stage)
        if isinstance(inner, FusedTransformer):
            n += len(inner.stages)
        elif stage.element_fn() is not None:
            n += 1
    return n
