"""Streaming data plane — sharded record streams with window shuffle and a
checkpointable iterator position.

At dataset scale the feed cannot hold an in-memory epoch order over every
record, and at pod scale each host must read only its slice. This module is
the webdataset-style answer (tf.data's lesson, PAPERS.md 2101.12127): the
dataset is a LIST OF SHARDS — packed ``.bdlrec`` record files
(``dataset/recordio.py``) or plain uncompressed ``.tar`` archives — scanned
once at open into per-shard (offset, length) indices and read with
``os.pread`` (positioned reads, thread-safe on a shared fd).

**Window shuffle.** A true global permutation needs the whole index in one
array; a stream gets the standard approximation instead: interleave records
round-robin from the shards (shard ORDER itself permuted per epoch), fill a
bounded window of ``BIGDL_SHUFFLE_WINDOW`` slots, and for every further
record draw a deterministic index into the window, yield the occupant, and
replace it. The draw sequence comes from a per-epoch seed pulled from the
global ``RandomGenerator`` inside ``shuffle()`` — so epoch order is a pure
function of (seed, epoch), reproducible run-to-run, and IDENTICAL for any
``BIGDL_DATA_WORKERS`` setting because the order is produced here in the
single driving generator, upstream of the parallel transform engine.

**Checkpointable position.** The whole iterator state — per-shard cursors,
round-robin pointer, window contents, RNG bit-generator state, emitted
count — is explicit and serializable (:meth:`_IndexStream.state`). The
trainer snapshots :meth:`StreamingDataSet.stream_state` at epoch start into
the checkpoint payload, so ``optimize(resume="auto")`` after a mid-epoch
SIGTERM rebuilds the exact stream and replays to the exact batch — bitwise
resume over a stream, not just over an in-memory epoch order.
:meth:`position_after` / :meth:`data_from` expose the same state for direct
consumers that want to seek without replaying record IO.

**Per-host sharding.** :meth:`shard` returns this dataset restricted to
``shards[host_index::host_count]`` — the multi-host hook (GSPMD, ROADMAP
item 2): every host constructs the same shard list, then takes its slice.

Decoded records flow through the same cache-aware iteration driver as the
other sources (``dataset/sample_cache.py``): the first epoch decodes and
writes the cache, later epochs mmap it and the decode pool is never built.
"""

from __future__ import annotations

import os
import tarfile
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.profiling import STAGE_DECODE, feed_stats
from bigdl_tpu.dataset.resilience import run_guarded
from bigdl_tpu.obs import trace
from bigdl_tpu.utils.faults import SITE_DECODE, fault_point
from bigdl_tpu.utils.random_generator import RandomGenerator


def shuffle_window(default: int = 256) -> int:
    """``BIGDL_SHUFFLE_WINDOW``: window-shuffle buffer size in records.
    ``<= 1`` disables shuffling within the stream (pure shard interleave —
    shard ORDER is still permuted per epoch)."""
    raw = os.environ.get("BIGDL_SHUFFLE_WINDOW", "").strip()
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def _scan_tar(path: str) -> list[tuple[int, int]]:
    """One pass over an UNCOMPRESSED tar → [(offset, length)] per regular
    member, in archive order (the webdataset layout: one member per record).
    Compression is rejected — random ``pread`` access needs flat bytes."""
    index = []
    with tarfile.open(path, "r:") as tf:  # "r:" = no compression accepted
        for m in tf:
            if m.isfile():
                index.append((m.offset_data, m.size))
    return index


def _scan_shard(path: str) -> tuple[str, list[tuple[int, int]]]:
    """(kind, [(offset, length)]) for one shard file, by extension."""
    if path.endswith(".tar"):
        return "tar", _scan_tar(path)
    from bigdl_tpu.dataset.recordio import _scan_index
    return "bdlrec", _scan_index(path)


class _IndexStream:
    """The order-producing heart of the stream: round-robin shard interleave
    feeding a bounded shuffle window, with every piece of state explicit so
    a position can be captured, serialized, and rebuilt exactly.

    State: per-shard cursors, the active-shard list + round-robin pointer,
    the window (global record ids), the numpy bit-generator state, and the
    emitted count. ``state()``/``from_state()`` round-trip all of it.
    """

    def __init__(self, counts: Sequence[int], bases: Sequence[int],
                 order: Sequence[int], window_size: int, seed: int):
        self._counts = [int(c) for c in counts]
        self._bases = [int(b) for b in bases]
        self.order = [int(s) for s in order]
        self.window_size = max(int(window_size), 0)
        # fresh stream: all non-empty shards active in epoch order
        self._cursors = {s: 0 for s in self.order}
        self._active = [s for s in self.order if self._counts[s] > 0]
        self._rr = 0
        self._window: list[int] = []
        self._rng = np.random.default_rng(int(seed) & 0x7FFFFFFFFFFFFFFF)
        self.emitted = 0

    # ------------------------------------------------------------- iterate
    def __iter__(self) -> "_IndexStream":
        return self

    def _pull(self) -> int:
        """Next record id from the shard interleave (round-robin, one record
        per shard visit; exhausted shards drop out keeping the rotation)."""
        s = self._active[self._rr]
        c = self._cursors[s]
        gid = self._bases[s] + c
        self._cursors[s] = c + 1
        if c + 1 >= self._counts[s]:
            self._active.pop(self._rr)
            if self._rr >= len(self._active):
                self._rr = 0
        else:
            self._rr += 1
            if self._rr >= len(self._active):
                self._rr = 0
        return gid

    def __next__(self) -> int:
        while self._active:
            gid = self._pull()
            if self.window_size <= 1:
                self.emitted += 1
                return gid
            if len(self._window) < self.window_size:
                self._window.append(gid)  # filling — nothing to emit yet
                continue
            j = int(self._rng.integers(0, self.window_size))
            out, self._window[j] = self._window[j], gid
            self.emitted += 1
            return out
        if self._window:  # drain: shards exhausted, window empties randomly
            j = int(self._rng.integers(0, len(self._window)))
            self.emitted += 1
            return self._window.pop(j)
        raise StopIteration

    # --------------------------------------------------------------- state
    def state(self) -> dict:
        return {
            "cursors": dict(self._cursors),
            "active": list(self._active),
            "rr": self._rr,
            "window": list(self._window),
            "rng": self._rng.bit_generator.state,
            "emitted": self.emitted,
            "order": list(self.order),
            "window_size": self.window_size,
        }

    @classmethod
    def from_state(cls, counts: Sequence[int], bases: Sequence[int],
                   state: dict) -> "_IndexStream":
        st = cls(counts, bases, state["order"], state["window_size"], 0)
        st._cursors = {int(k): int(v) for k, v in state["cursors"].items()}
        st._active = [int(s) for s in state["active"]]
        st._rr = int(state["rr"])
        st._window = [int(g) for g in state["window"]]
        st._rng.bit_generator.state = state["rng"]
        st.emitted = int(state["emitted"])
        return st


class StreamingDataSet(AbstractDataSet):
    """Sharded record stream with deterministic window shuffle, resumable
    position, per-host shard assignment, and cache-aware decoding.

    ``paths``: ``.bdlrec`` and/or uncompressed ``.tar`` shard files.
    ``decoder``: payload bytes → record (default: the recordio image decoder
    yielding ImageFeature). ``shuffle_window``: records buffered for the
    window shuffle (None → ``BIGDL_SHUFFLE_WINDOW``, default 256).
    ``cache``: None defers to ``BIGDL_SAMPLE_CACHE``.
    """

    def __init__(self, paths: Sequence[str] | str,
                 decoder: Optional[Callable[[bytes], object]] = None,
                 shuffle_window: Optional[int] = None,
                 num_workers: int = 8,
                 cache: Optional[bool] = None,
                 cache_dir: Optional[str] = None,
                 distributed: bool = False):
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        if not self.paths:
            raise ValueError("no stream shards given")
        if decoder is None:
            from bigdl_tpu.dataset.recordio import image_record_decoder
            decoder = image_record_decoder
        self.decoder = decoder
        self.shuffle_window = shuffle_window
        self.num_workers = max(int(num_workers), 1)
        self.distributed = distributed
        self._kinds: list[str] = []
        self._indices: list[list[tuple[int, int]]] = []
        self._bases: list[int] = []
        n = 0
        for p in self.paths:
            kind, idx = _scan_shard(p)
            self._kinds.append(kind)
            self._indices.append(idx)
            self._bases.append(n)
            n += len(idx)
        self._n = n
        if n == 0:
            raise ValueError(f"no records in stream shards {self.paths}")
        # shard-granular epoch order: the existing trainer resume machinery
        # snapshots/restores `_order` generically, so keeping the shard
        # permutation here means streamed runs ride the same rails
        self._order = np.arange(len(self.paths))
        self._epoch_seed = 0
        self._fds: dict[int, int] = {}
        self._ex: Optional[ThreadPoolExecutor] = None
        self._cache_enabled = cache
        self._cache_dir = cache_dir
        self._cache = None

    # ------------------------------------------------------------ basics
    def size(self) -> int:
        return self._n

    def shuffle(self) -> None:
        """Permute the shard visit order AND draw this epoch's window-shuffle
        seed — both from the global ``RandomGenerator``, so the trainer's
        post-shuffle RNG snapshot covers every draw and a resumed run
        replays them exactly."""
        rng = RandomGenerator.numpy()
        self._order = self._order[rng.permutation(len(self._order))]
        self._epoch_seed = int(rng.integers(0, 2 ** 31 - 1))

    def close(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=False, cancel_futures=True)
            self._ex = None
        for fd in self._fds.values():
            try:
                os.close(fd)
            except OSError:
                pass
        self._fds.clear()
        if self._cache is not None:
            self._cache.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # ----------------------------------------------------------- sharding
    def shard(self, host_index: int, host_count: int) -> "StreamingDataSet":
        """This dataset restricted to ``paths[host_index::host_count]`` — the
        per-host assignment hook for multi-host input. Every host builds the
        same full shard list, then takes its strided slice; shard counts
        should be ≥ hosts and ideally a multiple (equal per-host work)."""
        if host_count < 1 or not (0 <= host_index < host_count):
            raise ValueError(
                f"invalid shard({host_index}, {host_count})")
        mine = self.paths[host_index::host_count]
        if not mine:
            raise ValueError(
                f"host {host_index}/{host_count} got no shards from "
                f"{len(self.paths)} files — write more shards than hosts")
        return StreamingDataSet(
            mine, decoder=self.decoder, shuffle_window=self.shuffle_window,
            num_workers=self.num_workers, cache=self._cache_enabled,
            cache_dir=self._cache_dir, distributed=self.distributed)

    # ------------------------------------------------------------- reading
    def _executor(self) -> ThreadPoolExecutor:
        if self._ex is None:
            self._ex = ThreadPoolExecutor(self.num_workers,
                                          thread_name_prefix="bigdl-stream")
        return self._ex

    def _fd(self, si: int) -> int:
        fd = self._fds.get(si)
        if fd is None:
            fd = os.open(self.paths[si], os.O_RDONLY)
            self._fds[si] = fd
        return fd

    def _locate(self, gid: int) -> tuple[int, int]:
        """global record id → (shard index, record index within shard)."""
        si = int(np.searchsorted(self._bases, gid, side="right")) - 1
        return si, gid - self._bases[si]

    def _read(self, gid: int) -> bytes:
        si, ri = self._locate(gid)
        if self._kinds[si] == "bdlrec":
            # payload preceded by len|crc — reuse recordio's verified read
            import struct
            import zlib
            from bigdl_tpu.dataset.recordio import _REC, RecordIOError
            off, ln = self._indices[si][ri]
            rec = os.pread(self._fd(si), _REC.size + ln, off)
            length, crc = _REC.unpack(rec[:_REC.size])
            payload = rec[_REC.size:]
            if len(payload) != length or zlib.crc32(payload) != crc:
                raise RecordIOError(
                    f"{self.paths[si]}: corrupt record @ {off} (crc mismatch)")
            return payload
        off, ln = self._indices[si][ri]
        return os.pread(self._fd(si), ln, off)

    def _load_one(self, gid: int):
        fault_point(SITE_DECODE)  # scripted decode failure, if any
        t0 = time.perf_counter()
        with trace.span("feed/decode"):
            out = self.decoder(self._read(gid))
        feed_stats.add(STAGE_DECODE, time.perf_counter() - t0)
        return out

    def _load(self, gid: int):
        # corrupt-sample policy (BIGDL_BAD_SAMPLE_POLICY) applies per record
        return run_guarded("decode", self._load_one, gid)

    # -------------------------------------------------------------- cache
    def _cache_obj(self):
        from bigdl_tpu.dataset import sample_cache
        if self._cache is None and self._cache_enabled is not False:
            enabled = (sample_cache.cache_enabled()
                       if self._cache_enabled is None else True)
            if enabled:
                default_dir = os.path.join(
                    os.path.dirname(os.path.abspath(self.paths[0])),
                    ".bigdl-sample-cache")
                material = ("stream.v1", tuple(self.paths),
                            tuple(os.path.getsize(p) for p in self.paths),
                            self._n,
                            getattr(self.decoder, "__qualname__",
                                    type(self.decoder).__name__))
                self._cache = sample_cache.SampleCache(
                    sample_cache.cache_dir(self._cache_dir or default_dir),
                    sample_cache.fingerprint(material), self._n)
        return self._cache

    # ------------------------------------------------------------ position
    def stream_state(self) -> dict:
        """Epoch-start stream identity for the checkpoint payload: with the
        shard order and epoch seed pinned, the whole epoch's record order is
        a pure function — a resumed process rebuilds it exactly even though
        its own ``shuffle()`` never ran."""
        return {"order": [int(s) for s in self._order],
                "epoch_seed": int(self._epoch_seed),
                "window": self._window_size()}

    def restore_stream_state(self, state: dict) -> None:
        self._order = np.asarray([int(s) for s in state["order"]])
        self._epoch_seed = int(state["epoch_seed"])

    def _window_size(self) -> int:
        return (shuffle_window() if self.shuffle_window is None
                else int(self.shuffle_window))

    def _fresh_stream(self) -> _IndexStream:
        counts = [len(ix) for ix in self._indices]
        return _IndexStream(counts, self._bases, list(self._order),
                            self._window_size(), self._epoch_seed)

    def position_after(self, n: int) -> dict:
        """The exact iterator state after ``n`` records of this epoch — index
        math only, no record IO, no decode. Feed it to :meth:`data_from` to
        seek."""
        st = self._fresh_stream()
        for _ in range(int(n)):
            next(st)
        return st.state()

    def data_from(self, position: dict, train: bool = True) -> Iterator:
        """Resume the epoch from a :meth:`position_after` /
        :meth:`_IndexStream.state` capture: yields exactly the records an
        uninterrupted epoch would have yielded from that point on."""
        counts = [len(ix) for ix in self._indices]
        stream = _IndexStream.from_state(counts, self._bases, position)
        return self._drive(stream)

    # ---------------------------------------------------------------- data
    def _drive(self, stream: _IndexStream) -> Iterator:
        from bigdl_tpu.dataset.sample_cache import cached_data_iter

        def submit(gid):
            return self._executor().submit(self._load, gid)

        return cached_data_iter(stream, submit, self._cache_obj(),
                                self.num_workers * 2)

    def data(self, train: bool) -> Iterator:
        return self._drive(self._fresh_stream())
