"""On-disk image-folder dataset — the ImageNet-scale input source.

Reference parity (SURVEY.md §2.2/§7.4): the reference reads ImageNet from Hadoop
sequence files partitioned by Spark (``<dl>/dataset/DataSet.scala`` ``SeqFileFolder``
— unverified, mount empty). TPU-native: a host-side streaming source over the standard
``root/<class_name>/<image>`` layout, decoding JPEG/PNG with a thread pool (PIL releases
the GIL during decode), composing with the vision ``FeatureTransformer`` pipeline and
``SampleToMiniBatch``. Behind the trainer's ``PrefetchingFeed`` the whole
decode→augment→stack→h2d chain runs off the step loop's critical path.

Layout scanned once at construction; ``shuffle()`` permutes the file order with the
global ``RandomGenerator`` (deterministic per seed). Labels are the sorted class-dir
index, 0-based by default (``one_based=True`` matches the reference's Scala/Torch
1-based convention).
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional, Sequence

import numpy as np

from bigdl_tpu.dataset.dataset import AbstractDataSet
from bigdl_tpu.dataset.profiling import STAGE_DECODE, feed_stats
from bigdl_tpu.dataset.resilience import run_guarded
from bigdl_tpu.obs import trace
from bigdl_tpu.utils.faults import SITE_DECODE, fault_point
from bigdl_tpu.utils.random_generator import RandomGenerator

_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp")


class ImageFolderDataSet(AbstractDataSet):
    """Streams :class:`~bigdl_tpu.transform.vision.image.ImageFeature` records
    (HWC uint8, RGB channel order — compose ``ChannelOrder`` for BGR models)."""

    def __init__(self, root: str, num_workers: int = 8,
                 extensions: Sequence[str] = _EXTENSIONS,
                 one_based: bool = False, distributed: bool = False,
                 cache: Optional[bool] = None, cache_dir: Optional[str] = None):
        if not os.path.isdir(root):
            raise FileNotFoundError(f"image folder root not found: {root}")
        self.root = root
        self.num_workers = max(int(num_workers), 1)
        self.distributed = distributed
        # decoded-sample cache (dataset/sample_cache.py): None defers to
        # BIGDL_SAMPLE_CACHE; the SampleCache instance persists across epochs
        # so CRC verification happens once and quarantine sticks
        self._cache_enabled = cache
        self._cache_dir = cache_dir
        self._cache = None
        exts = tuple(e.lower() for e in extensions)
        self.classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        if not self.classes:
            raise ValueError(f"no class subdirectories under {root}")
        base = 1 if one_based else 0
        self.class_to_label = {c: i + base for i, c in enumerate(self.classes)}
        self._items: list[tuple[str, int]] = []
        for c in self.classes:
            cdir = os.path.join(root, c)
            for name in sorted(os.listdir(cdir)):
                if name.lower().endswith(exts):
                    self._items.append((os.path.join(cdir, name),
                                        self.class_to_label[c]))
        if not self._items:
            raise ValueError(f"no images with extensions {exts} under {root}")
        self._order = np.arange(len(self._items))
        self._ex: Optional[ThreadPoolExecutor] = None

    def size(self) -> int:
        return len(self._items)

    def _executor(self) -> ThreadPoolExecutor:
        """One decode pool per DATASET, reused across epochs — the per-epoch
        pool was spun up inside ``data()`` and abandoned (``shutdown(wait=
        False)``) whenever the generator closed, stacking orphaned idle
        threads epoch after epoch."""
        if self._ex is None:
            self._ex = ThreadPoolExecutor(self.num_workers,
                                          thread_name_prefix="bigdl-decode")
        return self._ex

    def close(self) -> None:
        """Deterministically shut the decode pool down (tests / long-lived
        processes swapping datasets). Safe to call repeatedly; a later
        ``data()`` recreates the pool."""
        if self._ex is not None:
            self._ex.shutdown(wait=False, cancel_futures=True)
            self._ex = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def shuffle(self) -> None:
        perm = RandomGenerator.numpy().permutation(len(self._items))
        self._order = self._order[perm]

    @staticmethod
    def _decode_one(item: tuple[str, int]):
        from PIL import Image as PILImage

        from bigdl_tpu.transform.vision.image import ImageFeature

        fault_point(SITE_DECODE)  # scripted decode failure, if any
        path, label = item
        t0 = time.perf_counter()
        with trace.span("feed/decode"):
            with PILImage.open(path) as img:
                arr = np.asarray(img.convert("RGB"))
        feed_stats.add(STAGE_DECODE, time.perf_counter() - t0)
        return ImageFeature(arr, label, uri=path)

    def _decode(self, item: tuple[str, int]):
        # corrupt-sample policy (BIGDL_BAD_SAMPLE_POLICY): a truncated or
        # unreadable image can skip/retry instead of killing the decode pool
        return run_guarded("decode", self._decode_one, item)

    def _cache_obj(self):
        from bigdl_tpu.dataset import sample_cache
        if self._cache is None and self._cache_enabled is not False:
            enabled = (sample_cache.cache_enabled()
                       if self._cache_enabled is None else True)
            if enabled:
                default_dir = os.path.join(self.root, ".bigdl-sample-cache")
                self._cache = sample_cache.SampleCache(
                    sample_cache.cache_dir(self._cache_dir or default_dir),
                    sample_cache.fingerprint(
                        ("image_folder.v1", self.root, tuple(self._items))),
                    len(self._items))
        return self._cache

    def data(self, train: bool) -> Iterator:
        # cache-aware iteration (dataset/sample_cache.py): a committed cache
        # serves the whole epoch via mmap and the decode pool is never
        # created; otherwise the classic sliding window of decode futures
        # (bounded memory, preserved order), building the cache as it goes
        from bigdl_tpu.dataset.sample_cache import cached_data_iter

        def submit(i):
            return self._executor().submit(self._decode, self._items[i])

        yield from cached_data_iter((int(i) for i in self._order), submit,
                                    self._cache_obj(), self.num_workers * 2)


def write_synthetic_image_folder(root: str, n_classes: int = 4,
                                 n_per_class: int = 8, size: int = 64,
                                 seed: int = 0) -> str:
    """Materialise an ImageNet-layout directory of random PNGs (tests / demos /
    pipeline smoke runs). Returns ``root``."""
    from PIL import Image as PILImage

    rng = np.random.default_rng(seed)
    for c in range(n_classes):
        cdir = os.path.join(root, f"class_{c:03d}")
        os.makedirs(cdir, exist_ok=True)
        for i in range(n_per_class):
            arr = rng.integers(0, 256, size=(size, size, 3), dtype=np.uint8)
            PILImage.fromarray(arr).save(os.path.join(cdir, f"img_{i:04d}.png"))
    return root
