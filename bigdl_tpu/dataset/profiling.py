"""Per-stage feed profiling — attribute host input-pipeline time.

The training loop's ``feed_wait_ms`` says how long the step loop *waited* on
data, but not where a slow feed actually spends its time. This module is the
attribution layer: the pipeline stages (decode in the dataset sources, augment
in the parallel transform workers, stack in ``SampleToMiniBatch``) report their
wall time here, and the consumers (``Optimizer`` training summaries, the
``--pipeline-bench`` leg) read snapshot deltas — so a regression in any single
stage is visible instead of smearing into one opaque wait number.

Kept dependency-free (no ``optim`` import): the dataset layer must not import
the optimizer. Timings are wall-clock sums per stage occurrence; decode/augment
count per IMAGE, stack per BATCH, h2d lives in the optimizer's own metrics
(``put_batch``) and is merged by the consumer. Every add also publishes into
the obs metric registry as ``feed/<stage>`` so the unified run report and
bench legs read one source.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

from bigdl_tpu.obs.registry import registry as _obs_registry

STAGE_DECODE = "decode"
STAGE_AUGMENT = "augment"
STAGE_STACK = "stack"
#: mmap read from the decoded-sample cache (dataset/sample_cache.py) — a
#: warm epoch reports here INSTEAD of decode, so the attribution log shows
#: the cache taking over rather than decode going quietly near-zero
STAGE_CACHE = "cache"


class FeedStageStats:
    """Thread-safe (sum, count) accumulator per pipeline stage.

    Producers run in decode pools / transform workers / the prefetch producer
    thread concurrently; one lock guards the two dicts (the critical section is
    two float adds — contention is negligible next to ms-scale image work).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sums: dict[str, float] = defaultdict(float)
        self._counts: dict[str, int] = defaultdict(int)

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:
            self._sums[stage] += seconds
            self._counts[stage] += 1
        _obs_registry.histogram("feed/" + stage).observe(seconds)

    def timer(self, stage: str) -> "_StageTimer":
        return _StageTimer(self, stage)

    def snapshot(self) -> dict[str, tuple[float, int]]:
        """{stage: (total_seconds, occurrences)} — cheap copy for delta math."""
        with self._lock:
            return {k: (self._sums[k], self._counts[k]) for k in self._sums}

    def reset(self) -> None:
        with self._lock:
            self._sums.clear()
            self._counts.clear()


class _StageTimer:
    __slots__ = ("_stats", "_stage", "_t0")

    def __init__(self, stats: FeedStageStats, stage: str):
        self._stats, self._stage = stats, stage

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._stats.add(self._stage, time.perf_counter() - self._t0)
        return False


#: process-wide sink the pipeline stages report into (consumers diff snapshots,
#: so sharing one sink across datasets/epochs is fine)
feed_stats = FeedStageStats()


def stage_deltas_ms(before: dict[str, tuple[float, int]],
                    after: dict[str, tuple[float, int]] | None = None
                    ) -> dict[str, dict[str, float]]:
    """Per-stage mean ms and occurrence count between two snapshots."""
    if after is None:
        after = feed_stats.snapshot()
    out: dict[str, dict[str, float]] = {}
    for stage, (total, count) in after.items():
        t0, c0 = before.get(stage, (0.0, 0))
        dt, dc = total - t0, count - c0
        if dc > 0:
            out[stage] = {"ms": 1e3 * dt / dc, "count": dc,
                          "total_ms": 1e3 * dt}
    return out
