"""Degradable input pipeline — corrupt-sample policy for decode/transform
stages.

tf.data's production lesson (PAPERS.md 2101.12127) applies verbatim here: at
dataset scale some records ARE corrupt — truncated JPEGs, bit-rotted shards,
flaky network filesystems — and a pipeline without an explicit policy turns
one bad byte into a dead training job (the exception fires in a decode-pool
or producer thread and takes the whole feed down). This module centralizes
the policy:

- ``BIGDL_BAD_SAMPLE_POLICY`` — ``raise`` (default: fail loudly, the classic
  behavior, byte-for-byte unchanged), ``skip`` (drop the record, count it),
  or ``retry`` (re-execute with bounded exponential backoff — for transient
  IO — then propagate if it still fails; each attempt is counted).
- ``BIGDL_SAMPLE_RETRIES`` — retry attempts per record under ``retry``
  (default 3); ``BIGDL_RETRY_BACKOFF_MS`` — first backoff (default 10 ms,
  doubling, capped at 1 s).
- Per-stage counters ride the process-wide robustness event sink
  (``utils/robustness.py``) as ``sample_skipped`` / ``sample_retried``
  events tagged with the failing stage, and :func:`stage_counters` exposes a
  per-stage summary for reports and tests.

:class:`~bigdl_tpu.utils.faults.WorkerDeathError` is NEVER absorbed here —
a dead worker is an executor-health event owned by the parallel engine's
crash budget (``dataset/parallel.py``), not a data-quality event.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable

from bigdl_tpu.utils.faults import WorkerDeathError
from bigdl_tpu.utils.robustness import events

logger = logging.getLogger("bigdl_tpu.dataset")

#: sentinel yielded in place of a dropped record; stream stages filter it
SKIPPED = object()

_POLICIES = ("raise", "skip", "retry")
_BACKOFF_CAP_S = 1.0

_counter_lock = threading.Lock()
_stage_counters: dict[str, dict[str, int]] = {}


def bad_sample_policy() -> str:
    raw = os.environ.get("BIGDL_BAD_SAMPLE_POLICY", "raise").strip().lower()
    if raw not in _POLICIES:
        raise ValueError(
            f"BIGDL_BAD_SAMPLE_POLICY must be one of {_POLICIES}, got {raw!r}")
    return raw


def _retries() -> int:
    return max(0, int(os.environ.get("BIGDL_SAMPLE_RETRIES", "3")))


def _backoff_s() -> float:
    return max(0.0, float(os.environ.get("BIGDL_RETRY_BACKOFF_MS", "10"))) / 1e3


def _count(stage: str, kind: str) -> None:
    with _counter_lock:
        _stage_counters.setdefault(stage, {})[kind] = \
            _stage_counters.get(stage, {}).get(kind, 0) + 1


def stage_counters() -> dict:
    """``{stage: {"skipped": n, "retried": n}}`` accumulated this process."""
    with _counter_lock:
        return {s: dict(c) for s, c in _stage_counters.items()}


def reset_counters() -> None:
    with _counter_lock:
        _stage_counters.clear()


def run_guarded(stage: str, fn: Callable, *args):
    """Execute ``fn(*args)`` under the corrupt-sample policy.

    ``raise``: transparent call (no overhead beyond one env read).
    ``skip``: an exception drops the record — returns :data:`SKIPPED`.
    ``retry``: bounded exponential-backoff re-execution; exhausted retries
    propagate the final exception (a persistently corrupt record under
    ``retry`` is a data bug, not a transient — fail loudly; pick ``skip`` to
    degrade instead)."""
    policy = bad_sample_policy()
    if policy == "raise":
        return fn(*args)
    attempts = 1 + (_retries() if policy == "retry" else 0)
    delay = _backoff_s()
    for attempt in range(attempts):
        try:
            return fn(*args)
        except WorkerDeathError:
            raise  # executor health, not data quality
        except Exception as e:
            last = e
            if attempt + 1 < attempts:
                _count(stage, "retried")
                events.record("sample_retried", stage=stage,
                              error=type(e).__name__)
                logger.warning(
                    "stage %r failed (%s: %s); retry %d/%d after %.0f ms",
                    stage, type(e).__name__, e, attempt + 1, attempts - 1,
                    delay * 1e3)
                if delay > 0:
                    time.sleep(delay)
                delay = min(delay * 2, _BACKOFF_CAP_S)
    if policy == "skip":
        _count(stage, "skipped")
        events.record("sample_skipped", stage=stage,
                      error=type(last).__name__)
        logger.warning("stage %r dropped a corrupt record (%s: %s)",
                       stage, type(last).__name__, last)
        return SKIPPED
    raise last
