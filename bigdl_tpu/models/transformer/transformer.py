"""Encoder-decoder Transformer (seq2seq) — the reference Transformer analog.

Reference parity (SURVEY.md §2.1 tail; expected upstream
``<dl>/nn/Transformer.scala`` + ``Attention``/``FeedForwardNetwork`` — the
translation-model family added to the reference's late line, unverified,
mount empty). TPU-first build: pre-LN blocks from the stock zoo, causal self
attention through the flash/ring-capable ``MultiHeadAttention``, encoder
memory through ``nn.CrossAttention``, and decode-time search through
``nn.SequenceBeamSearch`` (one static-shape scan program).

``Transformer(...)`` maps ``T(src_ids, tgt_ids)`` → (N, Tt, tgt_vocab)
log-probs (teacher forcing); :func:`beam_translate` runs inference-time
beam search against the encoded memory.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.abstractnn import Container
from bigdl_tpu.utils.table import T
from bigdl_tpu.models.transformerlm.transformerlm import (
    PositionEmbedding, TransformerBlock)
from bigdl_tpu.utils.serializer import register as _register_serializable


def _two(input):
    """Accept the pair as a Table (1-based) or a tuple/list (training feeds
    multi-input MiniBatches as tuples)."""
    if isinstance(input, (tuple, list)):
        a, b = input
        return a, b
    return input[1], input[2]


@_register_serializable
class TransformerDecoderBlock(Container):
    """Pre-LN decoder block: causal self-attention, cross-attention over the
    memory, MLP — input/output ``T(x, memory)`` so blocks chain in a
    Sequential."""

    def __init__(self, embed_dim: int, num_heads: int, mlp_ratio: int = 4,
                 dropout: float = 0.0, attention_impl: str = "auto"):
        self_attn = nn.Sequential().add(nn.LayerNorm(embed_dim)).add(
            nn.MultiHeadAttention(embed_dim, num_heads, causal=True,
                                  attention_impl=attention_impl))
        cross = nn.Sequential().add(nn.CrossAttention(embed_dim, num_heads))
        cross_norm = nn.LayerNorm(embed_dim)
        mlp = (nn.Sequential()
               .add(nn.LayerNorm(embed_dim))
               .add(nn.TimeDistributed(nn.Linear(embed_dim, mlp_ratio * embed_dim)))
               .add(nn.GELU())
               .add(nn.TimeDistributed(nn.Linear(mlp_ratio * embed_dim, embed_dim))))
        if dropout > 0:
            self_attn.add(nn.Dropout(dropout))
            cross.add(nn.Dropout(dropout))
            mlp.add(nn.Dropout(dropout))
        super().__init__(self_attn, cross_norm, cross, mlp)

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn.abstractnn import split_rng
        x, memory = _two(input)
        r = split_rng(rng, 4)
        sa, cn, ca, mlp = self.modules
        new_s = {}
        h, new_s["0"] = sa.apply(params["0"], state["0"], x,
                                 training=training, rng=r[0])
        x = x + h
        hn, new_s["1"] = cn.apply(params["1"], state["1"], x,
                                  training=training, rng=r[1])
        h, new_s["2"] = ca.apply(params["2"], state["2"], T(hn, memory),
                                 training=training, rng=r[2])
        x = x + h
        h, new_s["3"] = mlp.apply(params["3"], state["3"], x,
                                  training=training, rng=r[3])
        return T(x + h, memory), new_s


@_register_serializable
class Transformer(Container):
    """Seq2seq transformer. ``forward(T(src, tgt))`` → (N, Tt, tgt_vocab)
    log-probs; ``src``/``tgt`` int32 token ids (teacher-forced targets)."""

    def __init__(self, src_vocab: int, tgt_vocab: int, embed_dim: int = 256,
                 num_heads: int = 4, num_encoder_layers: int = 2,
                 num_decoder_layers: int = 2, max_len: int = 512,
                 mlp_ratio: int = 4, dropout: float = 0.0,
                 attention_impl: str = "auto"):
        encoder = (nn.Sequential()
                   .add(nn.LookupTable(src_vocab, embed_dim, zero_based=True))
                   .add(PositionEmbedding(max_len, embed_dim)))
        for i in range(num_encoder_layers):
            blk = TransformerBlock(embed_dim, num_heads, mlp_ratio, dropout,
                                   attention_impl, causal=False)
            encoder.add(blk.set_name(f"enc{i + 1}"))
        encoder.add(nn.LayerNorm(embed_dim).set_name("enc_norm"))

        tgt_embed = (nn.Sequential()
                     .add(nn.LookupTable(tgt_vocab, embed_dim, zero_based=True))
                     .add(PositionEmbedding(max_len, embed_dim)))
        decoder = nn.Sequential()
        for i in range(num_decoder_layers):
            decoder.add(TransformerDecoderBlock(
                embed_dim, num_heads, mlp_ratio, dropout,
                attention_impl).set_name(f"dec{i + 1}"))
        head = (nn.Sequential()
                .add(nn.LayerNorm(embed_dim))
                .add(nn.TimeDistributed(nn.Linear(embed_dim, tgt_vocab)))
                .add(nn.TimeDistributed(nn.LogSoftMax())))
        super().__init__(encoder, tgt_embed, decoder, head)
        self.tgt_vocab = tgt_vocab

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn.abstractnn import split_rng
        src, tgt = _two(input)
        r = split_rng(rng, 4)
        enc, emb, dec, head = self.modules
        new_s = {}
        memory, new_s["0"] = enc.apply(params["0"], state["0"], src,
                                       training=training, rng=r[0])
        x, new_s["1"] = emb.apply(params["1"], state["1"], tgt,
                                  training=training, rng=r[1])
        out, new_s["2"] = dec.apply(params["2"], state["2"], T(x, memory),
                                    training=training, rng=r[2])
        logp, new_s["3"] = head.apply(params["3"], state["3"], out[1],
                                      training=training, rng=r[3])
        return logp, new_s


class _MemoryDecoder(Container):
    """Decode-time adapter binding a Transformer to a fixed encoded memory so
    ``SequenceBeamSearch`` (which drives token-block decoders) can search over
    the target side. Beam flattening multiplies the batch: the memory is tiled
    to match the incoming (N*beam) rows. Eval-path helper — not serialized."""

    def __init__(self, transformer: Transformer, memory):
        super().__init__(transformer)
        self._memory = jnp.asarray(memory)

    def _tile(self, memory, rows: int):
        return jnp.repeat(memory, rows // memory.shape[0], axis=0)

    def apply(self, params, state, input, *, training=False, rng=None):
        model = self.modules[0]
        memory = self._tile(self._memory, input.shape[0])
        _, emb, dec, head = model.modules
        p, s = params["0"], state["0"]
        x, _ = emb.apply(p["1"], s["1"], input, training=False, rng=None)
        out, _ = dec.apply(p["2"], s["2"], T(x, memory),
                           training=False, rng=None)
        logp, _ = head.apply(p["3"], s["3"], out[1], training=False, rng=None)
        return logp, state


def beam_translate(model: Transformer, src, *, beam_size: int = 4,
                   eos_id: int, bos_id: int, decode_length: int,
                   alpha: float = 0.6, pad_id: int = 0):
    """Beam-search translate ``src`` (N, Ts) int32 → (sequences, scores):
    sequences (N, beam, 1 + decode_length) starting with ``bos_id``."""
    src = jnp.asarray(src, jnp.int32)
    enc = model.modules[0]
    memory, _ = enc.apply(model.get_params()["0"], model.get_state()["0"],
                          src, training=False, rng=None)
    wrapped = _MemoryDecoder(model, memory)
    bs = nn.SequenceBeamSearch(wrapped, beam_size, eos_id, decode_length,
                               alpha=alpha, pad_id=pad_id).evaluate()
    prompt = jnp.full((src.shape[0], 1), bos_id, jnp.int32)
    out = bs.forward(prompt)
    return np.asarray(out[1]), np.asarray(out[2])


class _CachedMemoryDecoder(_MemoryDecoder):
    """Like :class:`_MemoryDecoder` but threads MODULE STATE through, so the
    decoder stack's KV caches (``nn.install_decode_cache``) survive between
    steps — the O(L)-per-token cached translate path.

    The memory travels as a PARAMS leaf (not a closure constant) and the jit
    cache is shared with the underlying transformer, so repeat translates of
    the same shape reuse the compiled beam scan instead of retracing."""

    def __init__(self, transformer: Transformer, memory):
        super().__init__(transformer, memory)
        self._apply_cache = transformer._apply_cache

    def get_params(self):
        return {**super().get_params(), "memory": self._memory}

    def apply(self, params, state, input, *, training=False, rng=None):
        model = self.modules[0]
        memory = self._tile(params["memory"], input.shape[0])
        p, s = params["0"], state["0"]
        x, s1 = model.modules[1].apply(p["1"], s["1"], input,
                                       training=False, rng=None)
        out, s2 = model.modules[2].apply(p["2"], s["2"], T(x, memory),
                                         training=False, rng=None)
        logp, s3 = model.modules[3].apply(p["3"], s["3"], out[1],
                                          training=False, rng=None)
        return logp, {"0": {"0": s["0"], "1": s1, "2": s2, "3": s3}}


def translate_generate(model: Transformer, src, *, beam_size: int = 4,
                       eos_id: int, bos_id: int, decode_length: int,
                       alpha: float = 0.6, pad_id: int = 0):
    """KV-cached beam translate — same contract (and, tie-breaks aside, the
    same result — pinned by test) as :func:`beam_translate`, but the decoder
    self-attention runs O(L) per generated token through the decode cache
    instead of re-running the full target prefix every step. The cache scope
    excludes the bidirectional encoder (it runs once, here, up front)."""
    from bigdl_tpu.nn.incremental import beam_generate

    src = jnp.asarray(src, jnp.int32)
    enc = model.modules[0]
    memory, _ = enc.apply(model.get_params()["0"], model.get_state()["0"],
                          src, training=False, rng=None)
    wrapped = _CachedMemoryDecoder(model, memory)
    prompt = jnp.full((src.shape[0], 1), bos_id, jnp.int32)
    seqs, scores = beam_generate(
        wrapped, prompt, decode_length, beam_size=beam_size, eos_id=eos_id,
        alpha=alpha, pad_id=pad_id,
        cache_roots=[model.modules[1], model.modules[2]])
    return np.asarray(seqs), np.asarray(scores)
