"""Seq2seq Transformer training main (reference transformer example analog —
SURVEY.md §2.5 examples row). ``python -m bigdl_tpu.models.transformer.train``
trains on a synthetic reversal "translation" corpus (or tab-separated
``src\\ttgt`` token-id lines via --folder) and optionally beam-translates a
held-out batch after training.
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="seq2seq Transformer training")
    p.add_argument("-f", "--folder", default=None,
                   help="file of 'src-ids<TAB>tgt-ids' lines (space-separated)")
    p.add_argument("-b", "--batch-size", type=int, default=64)
    p.add_argument("--src-vocab", type=int, default=32)
    p.add_argument("--tgt-vocab", type=int, default=34)
    p.add_argument("--seq-len", type=int, default=8)
    p.add_argument("--embed-dim", type=int, default=64)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--num-encoder-layers", type=int, default=2)
    p.add_argument("--num-decoder-layers", type=int, default=2)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--max-epoch", type=int, default=10)
    p.add_argument("--learning-rate", type=float, default=3e-3)
    p.add_argument("--synthetic-size", type=int, default=2048)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--translate", type=int, default=0, metavar="N",
                   help="after training, beam-translate N held-out rows")
    p.add_argument("--beam", type=int, default=4)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.transformer import Transformer, translate_generate
    from bigdl_tpu.optim import Adam, DistriOptimizer, LocalOptimizer, Trigger
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    bos, eos = args.tgt_vocab - 2, args.tgt_vocab - 1
    payload = min(args.src_vocab, args.tgt_vocab - 2)
    rng = np.random.default_rng(0)

    if args.folder:
        pairs = []
        with open(args.folder) as f:
            for ln, line in enumerate(f, 1):
                s, t = line.rstrip("\n").split("\t")
                pairs.append((np.asarray(s.split(), np.int32),
                              np.asarray(t.split(), np.int32)))
                if pairs[-1][0].max(initial=0) >= args.src_vocab:
                    raise SystemExit(f"{args.folder}:{ln}: src id "
                                     f">= --src-vocab {args.src_vocab}")
                if pairs[-1][1].max(initial=0) >= bos:
                    raise SystemExit(
                        f"{args.folder}:{ln}: tgt id >= {bos} (the top two "
                        f"--tgt-vocab ids are reserved for bos/eos)")
        lens_s = {len(p[0]) for p in pairs}
        lens_t = {len(p[1]) for p in pairs}
        if len(lens_s) != 1 or len(lens_t) != 1:
            raise SystemExit(f"{args.folder}: ragged lines (src lens {sorted(lens_s)}, "
                             f"tgt lens {sorted(lens_t)}); pad to uniform length")
        args.seq_len = max(lens_s.pop(), lens_t.pop())
        srcs = [p[0] for p in pairs]
        tgts = [p[1] for p in pairs]
    else:  # synthetic translation: target is the reversed source
        src = rng.integers(0, payload, (args.synthetic_size, args.seq_len))
        srcs = list(src.astype(np.int32))
        tgts = list(src[:, ::-1].astype(np.int32))

    samples = []
    for s, t in zip(srcs, tgts):
        tin = np.concatenate([[bos], t]).astype(np.int32)
        tout = np.concatenate([t, [eos]]).astype(np.int32)
        samples.append(Sample((s, tin), tout))
    data = (DataSet.array(samples, distributed=args.distributed)
            >> SampleToMiniBatch(args.batch_size))

    model = Transformer(args.src_vocab, args.tgt_vocab, args.embed_dim,
                        args.num_heads, args.num_encoder_layers,
                        args.num_decoder_layers,
                        max_len=args.seq_len + 2, dropout=args.dropout)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    opt = (cls(model, data, crit)
           .set_optim_method(Adam(learningrate=args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch)))
    opt.optimize()
    print(f"final loss: {opt.state['loss']:.4f}")

    if args.translate:
        if args.folder:
            # no held-out split is defined for a user file: translate its
            # first rows and say so
            hsrc, origin = np.stack(srcs[: args.translate]), "training-file"
        else:
            hsrc = rng.integers(
                0, payload, (args.translate, args.seq_len)).astype(np.int32)
            origin = "held-out"
        # the KV-cached search (O(L)/token); result-equal to beam_translate
        seqs, scores = translate_generate(
            model, hsrc, beam_size=args.beam, eos_id=eos, bos_id=bos,
            decode_length=hsrc.shape[1] + 1)
        for n in range(len(hsrc)):
            print(f"{origin} src: {hsrc[n].tolist()}  ->  "
                  f"tgt: {seqs[n, 0, 1:].tolist()} (score {scores[n, 0]:.2f})")
    return opt.state["loss"]


if __name__ == "__main__":
    main()
