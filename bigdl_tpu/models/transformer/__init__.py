from bigdl_tpu.models.transformer.transformer import (
    Transformer, TransformerDecoderBlock, beam_translate, translate_generate)

__all__ = ["Transformer", "TransformerDecoderBlock", "beam_translate",
           "translate_generate"]
