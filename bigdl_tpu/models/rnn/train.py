"""PTB LSTM language-model training main (reference parity:
``<dl>/example/languagemodel/PTBWordLM.scala`` — unverified, SURVEY.md §2.5; baseline
config #4). ``python -m bigdl_tpu.models.rnn.train``.
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="PTB LSTM LM training")
    p.add_argument("-f", "--folder", default=None, help="dir with ptb.train.txt etc.")
    p.add_argument("-b", "--batch-size", type=int, default=32)
    p.add_argument("--vocab-size", type=int, default=10000)
    p.add_argument("--hidden-size", type=int, default=256)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--bptt", type=int, default=20)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--max-epoch", type=int, default=1)
    p.add_argument("--learning-rate", type=float, default=1.0)
    p.add_argument("--clip-norm", type=float, default=5.0)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--summary-dir", default=None)
    p.add_argument("--distributed", action="store_true")
    # reference rnn Test.scala generates text after training; same here via
    # SequenceBeamSearch (nn/beam_search.py)
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, beam-decode N tokens from a seed")
    p.add_argument("--beam", type=int, default=3)
    p.add_argument("--alpha", type=float, default=0.6,
                   help="beam length-penalty exponent")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.text import load_ptb, ptb_windows
    from bigdl_tpu.models.rnn import PTBModel
    from bigdl_tpu.optim import DistriOptimizer, LocalOptimizer, Loss, SGD, Trigger
    from bigdl_tpu.utils.engine import Engine

    if not Engine.is_initialized():
        Engine.init()

    ids, dictionary = load_ptb(args.folder, "train", vocab_size=args.vocab_size)
    vids, _ = load_ptb(args.folder, "valid", dictionary=dictionary)
    vocab = dictionary.vocab_size()
    xs, ys = ptb_windows(ids, args.bptt)
    vxs, vys = ptb_windows(vids, args.bptt)
    train_set = (DataSet.array([Sample(x, y) for x, y in zip(xs, ys)],
                               distributed=args.distributed)
                 >> SampleToMiniBatch(args.batch_size))
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                            size_average=True)
    val_set = (DataSet.array([Sample(x, y) for x, y in zip(vxs, vys)],
                             distributed=args.distributed)
               >> SampleToMiniBatch(args.batch_size))

    model = PTBModel(vocab, args.hidden_size, num_layers=args.num_layers,
                     dropout=args.dropout)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    optimizer = (cls(model, train_set, criterion)
                 .set_optim_method(SGD(learningrate=args.learning_rate))
                 .set_end_when(Trigger.max_epoch(args.max_epoch))
                 .set_validation(Trigger.every_epoch(), val_set, [Loss(criterion)]))
    if args.clip_norm:
        optimizer.set_gradient_clipping_by_l2_norm(args.clip_norm)
    if args.checkpoint:
        optimizer.set_checkpoint(args.checkpoint, Trigger.every_epoch())
    if args.summary_dir:
        from bigdl_tpu.visualization import TrainSummary, ValidationSummary
        optimizer.set_train_summary(TrainSummary(args.summary_dir, "ptb"))
        optimizer.set_val_summary(ValidationSummary(args.summary_dir, "ptb"))
    trained = optimizer.optimize()
    loss = optimizer.state["loss"]
    print(f"final loss: {loss:.4f}  perplexity: {np.exp(min(loss, 20.0)):.2f}")
    if args.generate:
        seed = np.asarray(vxs[0][: max(2, args.bptt // 4)])[None].astype(np.int32)
        # synthetic corpora have no <eos>; get_index would alias <unk>(0) and
        # prematurely finish beams — decode the full length instead
        eos = dictionary.get_index("<eos>")
        if dictionary.get_word(eos) != "<eos>":
            eos = -1
        bs = nn.SequenceBeamSearch(
            trained, beam_size=args.beam, eos_id=eos,
            decode_length=args.generate, alpha=args.alpha).evaluate()
        out = bs.forward(seed)
        toks = np.asarray(out[1])[0, 0]
        print("generated:", " ".join(dictionary.get_word(int(t)) for t in toks))
    return trained


if __name__ == "__main__":
    main()
