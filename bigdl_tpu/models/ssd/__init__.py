from bigdl_tpu.models.ssd.ssd import SSD, PermuteFlatten, detector
