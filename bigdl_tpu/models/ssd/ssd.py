"""SSD — single-shot multibox detector built from the detection layer family.

Reference parity: the reference ships the SSD *layers* (PriorBox /
NormalizeScale / DetectionOutputSSD — SURVEY.md §2.1 layer zoo) but no SSD
zoo model; this builder completes the family into a trainable/servable model
the way the reference zoo wraps its other topologies. The graph follows the
SSD paper's shape: shared conv trunk, per-scale loc/conf 3×3 heads, priors
generated per scale, concatenated into the Caffe wire format
``Table(loc (N, P*4), conf (N, P*C), priors (1, 2, P*4))`` — exactly what
:class:`~bigdl_tpu.nn.MultiBoxCriterion` trains against and
:class:`~bigdl_tpu.nn.DetectionOutputSSD` serves from.

TPU shape notes: every scale contributes a static number of priors, so the
concatenated wire tensors are fixed-shape; the priors are trace-time
constants (PriorBox); the whole model jits as one program in either image
layout (the permute-flatten respects ``nn.layout``).
"""

from __future__ import annotations

from typing import Optional, Sequence

from bigdl_tpu import nn


class PermuteFlatten(nn.TensorModule):
    """(N, C, H, W) → (N, H*W*C) in Caffe head order (y, x, anchor, coord):
    channels move innermost so the flattened vector interleaves per-location
    blocks in the same order PriorBox emits priors. Under the NHWC layout
    flag the conv output is already channel-last — flatten directly."""

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        x = input
        if not layout.is_nhwc():
            x = x.transpose(0, 2, 3, 1)
        return x.reshape(x.shape[0], -1), state


def _conv_block(c_in: int, c_out: int, stride_pool: bool = True) -> nn.Sequential:
    b = nn.Sequential()
    b.add(nn.SpatialConvolution(c_in, c_out, 3, 3, pad_w=1, pad_h=1))
    b.add(nn.ReLU())
    b.add(nn.SpatialConvolution(c_out, c_out, 3, 3, pad_w=1, pad_h=1))
    b.add(nn.ReLU())
    if stride_pool:
        b.add(nn.SpatialMaxPooling(2, 2, 2, 2))
    return b


def SSD(n_classes: int, img_size: int = 64,
        base_channels: Sequence[int] = (16, 32, 64),
        min_sizes: Optional[Sequence[float]] = None,
        aspect_ratios: Sequence[float] = ()) -> nn.Graph:
    """Two-scale SSD graph. ``n_classes`` INCLUDES background (label 0).

    Scales: stride-8 (``img_size/8`` cells) and stride-16 features. With the
    default empty ``aspect_ratios`` each cell carries one prior per
    ``min_size`` entry; pass ratios for the paper's multi-anchor heads.
    Output: ``Table(loc, conf, priors)`` wire format.
    """
    if img_size % 16 != 0:
        raise ValueError("img_size must be divisible by 16 (two stride scales)")
    if min_sizes is None:
        min_sizes = [img_size * 0.15, img_size * 0.4]
    if len(min_sizes) != 2:
        raise ValueError("min_sizes must give one size per scale (2)")
    c1, c2, c3 = base_channels

    inp = nn.Input()
    # trunk: three stride-2 stages → stride-8 feature map
    s8 = nn.Sequential()
    s8.add(_conv_block(3, c1))
    s8.add(_conv_block(c1, c2))
    s8.add(_conv_block(c2, c3))
    feat8 = s8.set_name("trunk_s8").inputs(inp)
    norm8 = nn.NormalizeScale(p=2.0, scale=20.0, size=c3) \
        .set_name("norm_s8").inputs(feat8)
    # extra stage → stride-16
    feat16 = _conv_block(c3, c3).set_name("trunk_s16").inputs(feat8)

    locs, confs, priors = [], [], []
    for tag, node, ms in (("s8", norm8, min_sizes[0]),
                          ("s16", feat16, min_sizes[1])):
        pb = nn.PriorBox([ms], aspect_ratios=list(aspect_ratios), flip=True,
                         img_h=img_size, img_w=img_size)
        a = pb.num_priors
        loc = nn.SpatialConvolution(c3, a * 4, 3, 3, pad_w=1, pad_h=1) \
            .set_name(f"loc_{tag}").inputs(node)
        conf = nn.SpatialConvolution(c3, a * n_classes, 3, 3, pad_w=1, pad_h=1) \
            .set_name(f"conf_{tag}").inputs(node)
        locs.append(PermuteFlatten().inputs(loc))
        confs.append(PermuteFlatten().inputs(conf))
        priors.append(pb.set_name(f"priors_{tag}").inputs(node))

    loc_all = nn.JoinTable(2).set_name("loc_cat").inputs(*locs)
    conf_all = nn.JoinTable(2).set_name("conf_cat").inputs(*confs)
    prior_all = nn.JoinTable(3).set_name("prior_cat").inputs(*priors)
    return nn.Graph([inp], [loc_all, conf_all, prior_all])


# portable serialization: the head-order flatten is model-private but must
# round-trip inside saved SSD archives like any other module
from bigdl_tpu.utils.serializer import register as _register_serializable  # noqa: E402

_register_serializable(PermuteFlatten)


def detector(model: nn.Graph, n_classes: int, keep_topk: int = 20,
             conf_thresh: float = 0.3, nms_thresh: float = 0.45):
    """Wrap a trained SSD graph with DetectionOutputSSD for serving: returns
    a callable image-batch → (N, keep_topk, 6) detections."""
    head = nn.DetectionOutputSSD(n_classes=n_classes, keep_topk=keep_topk,
                                 conf_thresh=conf_thresh, nms_thresh=nms_thresh)

    def run(images):
        model.evaluate()
        return head.forward(model.forward(images))

    return run
