"""SSD training main — synthetic shapes detection when no dataset is mounted.

``python -m bigdl_tpu.models.ssd.train`` trains the two-scale SSD on a
synthetic bright/dim-square detection task (the environment ships no
detection dataset), reports MultiBox loss and held-out localization IoU, and
optionally saves the model. Mirrors the zoo's Train.scala conventions
(argparse options, checkpoint/save flags).
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="SSD on synthetic shapes")
    p.add_argument("-b", "--batch-size", type=int, default=16)
    p.add_argument("--img-size", type=int, default=64)
    p.add_argument("--learning-rate", type=float, default=0.01)
    p.add_argument("--max-epoch", type=int, default=20)
    p.add_argument("--n-train", type=int, default=256)
    p.add_argument("--save", default=None, help="save trained model here")
    p.add_argument("--distributed", action="store_true")
    return p


def make_dataset(n: int, img: int, rng: np.random.RandomState):
    """Bright squares = class 1, dim squares = class 2; one object/image,
    padded (1, 5) gt rows [label, x1, y1, x2, y2] normalized."""
    from bigdl_tpu.dataset.sample import Sample
    out = []
    for _ in range(n):
        x = rng.rand(3, img, img).astype(np.float32) * 0.1
        side = rng.randint(img // 8, img // 4)
        y0 = rng.randint(0, img - side)
        x0 = rng.randint(0, img - side)
        cls = rng.randint(1, 3)
        x[:, y0:y0 + side, x0:x0 + side] = 1.0 if cls == 1 else 0.55
        gt = np.array([[cls, x0 / img, y0 / img,
                        (x0 + side) / img, (y0 + side) / img]], np.float32)
        out.append(Sample(x, gt))
    return out


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.models.ssd import SSD, detector
    from bigdl_tpu.optim import Adam, DistriOptimizer, LocalOptimizer, Trigger
    from bigdl_tpu.utils.engine import Engine
    import jax.numpy as jnp

    if not Engine.is_initialized():
        Engine.init()
    rng = np.random.RandomState(0)
    n_cls = 3   # bg + bright + dim

    train = make_dataset(args.n_train, args.img_size, rng)
    data = (DataSet.array(train, distributed=args.distributed)
            >> SampleToMiniBatch(args.batch_size))
    model = SSD(n_cls, img_size=args.img_size)
    opt_cls = DistriOptimizer if args.distributed else LocalOptimizer
    opt = (opt_cls(model, data, nn.MultiBoxCriterion(n_classes=n_cls))
           .set_optim_method(Adam(learningrate=args.learning_rate))
           .set_end_when(Trigger.max_epoch(args.max_epoch)))
    opt.optimize()
    print(f"final loss: {float(opt.state['loss']):.4f}")

    # held-out eval: detection IoU + class accuracy through the serve head
    serve = detector(model, n_cls, keep_topk=1, conf_thresh=0.01)
    test = make_dataset(32, args.img_size, rng)
    ious, cls_ok = [], 0
    for s in test:
        det = np.asarray(serve(jnp.asarray(s.feature[0][None])))[0, 0]
        gt = s.label[0][0]
        iou = float(nn.pairwise_iou(jnp.asarray(det[None, 2:]),
                                    jnp.asarray(gt[None, 1:]))[0, 0])
        ious.append(iou)
        cls_ok += int(det[0] == gt[0])
    print(f"held-out mean IoU: {np.mean(ious):.3f}  "
          f"class acc: {cls_ok / len(test):.3f}")

    if args.save:
        model.save_module(args.save)
        print(f"saved to {args.save}")
    return float(np.mean(ious))


if __name__ == "__main__":
    main()
