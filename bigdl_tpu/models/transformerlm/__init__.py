from bigdl_tpu.models.transformerlm.transformerlm import (
    PositionEmbedding, TransformerBlock, TransformerLM, lm_criterion,
)
