"""Decoder-only Transformer language model.

No reference counterpart (the reference predates transformers — SURVEY.md §5.7
its longest-sequence workload is the PTB LSTM); this family exists because
long-context is a first-class requirement of the TPU build. It is the showcase
model for the attention stack: causal ``MultiHeadAttention`` routes to the
single-chip Pallas flash kernel on TPU and to sequence-parallel ring attention
when the Engine mesh has a ``seq`` axis — the SAME model scales from one chip
to a sequence-sharded mesh unchanged. ``remat=True`` wraps each block in
``nn.Remat`` (jax.checkpoint) so depth x context fits HBM.

Pre-LN blocks (x + MHA(LN(x)); x + MLP(LN(x))) built from the stock layer
zoo: the residual join is the ConcatTable(Identity, branch) >> CAddTable
idiom, LayerNorm is the fused Pallas kernel on TPU.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import RandomNormal
from bigdl_tpu.utils.serializer import register as _register_serializable


@_register_serializable
class PositionEmbedding(TensorModule):
    """Learned absolute position embedding added to (N, T, E) token embeddings."""

    def __init__(self, max_len: int, embed_dim: int):
        super().__init__()
        self.max_len, self.embed_dim = max_len, embed_dim
        self.reset()

    def reset(self) -> None:
        # global-RandomGenerator convention: seedable and re-randomized by reset
        self._params = {"pos": jnp.asarray(RandomNormal(0.0, 0.02).init(
            (self.max_len, self.embed_dim),
            fan_in=self.embed_dim, fan_out=self.embed_dim))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        if isinstance(state, dict) and "pos_idx" in state:
            # cached incremental decode (nn.incremental): input is the next
            # t positions (t > 1 = the serving engine's chunked prefill) —
            # add their embeddings, advance the counter. A (b,) pos_idx is
            # the per-slot continuous-batching form: every row embeds at its
            # own depth.
            idx = state["pos_idx"]
            t = input.shape[1]
            if idx.ndim == 1:
                pp = idx[:, None] + jnp.arange(t)[None, :]          # (b, t)
                return input + jnp.take(params["pos"], pp, axis=0), \
                    {"pos_idx": idx + t}
            pp = idx + jnp.arange(t)                                # (t,)
            emb = jnp.take(params["pos"], pp, axis=0)               # (t, E)
            return input + emb[None], {"pos_idx": idx + t}
        t = input.shape[1]
        if t > self.max_len:
            raise ValueError(f"sequence length {t} > max_len {self.max_len}")
        return input + params["pos"][None, :t], state


def _residual(inner: nn.AbstractModule) -> nn.Sequential:
    return (nn.Sequential()
            .add(nn.ConcatTable().add(nn.Identity()).add(inner))
            .add(nn.CAddTable()))


def TransformerBlock(embed_dim: int, num_heads: int, mlp_ratio: int = 4,
                     dropout: float = 0.0,
                     attention_impl: str = "auto",
                     causal: bool = True,
                     num_kv_heads=None, rope: bool = False,
                     norm: str = "layer", mlp_kind: str = "gelu") -> nn.Sequential:
    if norm not in ("layer", "rms"):
        raise ValueError(f"norm must be layer|rms, got {norm!r}")
    if mlp_kind not in ("gelu", "swiglu"):
        raise ValueError(f"mlp_kind must be gelu|swiglu, got {mlp_kind!r}")
    norm_layer = nn.RMSNorm if norm == "rms" else nn.LayerNorm
    attn = nn.Sequential().add(norm_layer(embed_dim)).add(
        nn.MultiHeadAttention(embed_dim, num_heads, causal=causal,
                              attention_impl=attention_impl,
                              num_kv_heads=num_kv_heads, rope=rope))
    hidden = mlp_ratio * embed_dim
    mlp = nn.Sequential().add(norm_layer(embed_dim))
    if mlp_kind == "swiglu":
        # llama-style gated MLP from the stock table algebra:
        # (silu(x W_gate) * (x W_up)) W_down — the branch product is the
        # ConcatTable >> CMulTable idiom
        mlp.add(nn.ConcatTable()
                .add(nn.Sequential()
                     .add(nn.TimeDistributed(nn.Linear(embed_dim, hidden)))
                     .add(nn.Swish()))
                .add(nn.TimeDistributed(nn.Linear(embed_dim, hidden))))
        mlp.add(nn.CMulTable())
        mlp.add(nn.TimeDistributed(nn.Linear(hidden, embed_dim)))
    else:
        mlp.add(nn.TimeDistributed(nn.Linear(embed_dim, hidden)))
        mlp.add(nn.GELU())
        mlp.add(nn.TimeDistributed(nn.Linear(hidden, embed_dim)))
    if dropout > 0:
        attn.add(nn.Dropout(dropout))
        mlp.add(nn.Dropout(dropout))
    return nn.Sequential().add(_residual(attn)).add(_residual(mlp))


def TransformerLM(vocab_size: int, embed_dim: int = 256, num_heads: int = 4,
                  num_layers: int = 4, max_len: int = 1024,
                  mlp_ratio: int = 4, dropout: float = 0.0,
                  remat: bool = False,
                  attention_impl: str = "auto",
                  fused_head: bool = False,
                  num_kv_heads=None,
                  position: str = "learned",
                  norm: str = "layer", mlp_kind: str = "gelu") -> nn.Sequential:
    """Token ids (N, T) int32 → per-position log-probs (N, T, vocab).

    ``fused_head=True`` swaps the ``Linear >> LogSoftMax`` decoder for
    :class:`~bigdl_tpu.nn.FusedLMHead`: training streams the loss over vocab
    chunks (pair with :func:`lm_criterion`) so the (N, T, vocab) logits
    tensor is never materialized — the large-vocab memory path; eval output
    stays per-position log-probs either way. ``position="rope"`` replaces the
    learned absolute table with rotary embeddings applied inside every
    attention (relative positions; no max_len table to outgrow)."""
    if position not in ("learned", "rope"):
        raise ValueError(f"position must be learned|rope, got {position!r}")
    model = (nn.Sequential()
             .add(nn.LookupTable(vocab_size, embed_dim, zero_based=True)
                  .set_name("embedding")))
    if position == "learned":
        model.add(PositionEmbedding(max_len, embed_dim).set_name("pos"))
    for i in range(num_layers):
        block = TransformerBlock(embed_dim, num_heads, mlp_ratio, dropout,
                                 attention_impl, num_kv_heads=num_kv_heads,
                                 rope=(position == "rope"),
                                 norm=norm, mlp_kind=mlp_kind)
        if remat:
            block = nn.Remat(block)
        model.add(block.set_name(f"block{i + 1}"))
    final_norm = nn.RMSNorm if norm == "rms" else nn.LayerNorm
    model.add(final_norm(embed_dim).set_name("final_norm"))
    if fused_head:
        model.add(nn.FusedLMHead(embed_dim, vocab_size, eval_log_probs=True)
                  .set_name("decoder"))
    else:
        model.add(nn.TimeDistributed(nn.Linear(embed_dim, vocab_size))
                  .set_name("decoder"))
        model.add(nn.TimeDistributed(nn.LogSoftMax()))
    return model


def lm_criterion(fused_head: bool = False, chunk_size: int = 8192):
    """The training criterion matching :func:`TransformerLM`'s head choice."""
    if fused_head:
        return nn.ChunkedSoftmaxCrossEntropy(chunk_size=chunk_size)
    return nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
