"""Transformer LM training main — the long-context flagship. Synthetic token
stream offline (dataset/text.py synthetic_ptb); real text via --folder with a
whitespace corpus file. ``--distributed`` trains SPMD over the Engine mesh;
with a ``seq`` axis in the mesh the attention runs sequence-parallel ring over
ICI, otherwise the flash kernel per chip.
``python -m bigdl_tpu.models.transformerlm.train``
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Transformer LM training")
    p.add_argument("-f", "--folder", default=None,
                   help="text corpus file; synthetic stream if unset")
    p.add_argument("-b", "--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--embed-dim", type=int, default=128)
    p.add_argument("--num-heads", type=int, default=4)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--vocab-size", type=int, default=256)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--model-snapshot", default=None,
                   help="start from a saved model archive (fine-tuning); "
                        "vocab/seq-len/rope/fused-head are read from the "
                        "model, not these flags")
    p.add_argument("--save", default=None,
                   help="save the trained model archive here")
    p.add_argument("--lora", type=int, default=0, metavar="RANK",
                   help="LoRA fine-tune: adapt attention+Linear layers at "
                        "this rank, freeze everything else")
    p.add_argument("--rope", action="store_true",
                   help="rotary position embeddings instead of the learned table")
    p.add_argument("--num-kv-heads", type=int, default=None,
                   help="grouped-query attention: KV heads shared across "
                        "query-head groups (1 = multi-query)")
    p.add_argument("--norm", default="layer", choices=["layer", "rms"])
    p.add_argument("--mlp", default="gelu", choices=["gelu", "swiglu"])
    p.add_argument("--fused-head", action="store_true",
                   help="FusedLMHead + chunked softmax CE: the large-vocab "
                        "memory path (logits never materialized in training)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each block (fit deeper/longer in HBM)")
    p.add_argument("--max-iteration", type=int, default=8)
    p.add_argument("--learning-rate", type=float, default=3e-4)
    p.add_argument("--distributed", action="store_true")
    p.add_argument("--synthetic-tokens", type=int, default=200_000)
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, beam-decode N tokens from a seed")
    p.add_argument("--beam", type=int, default=3)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.text import ptb_windows, synthetic_ptb
    from bigdl_tpu.models.transformerlm import (
        PositionEmbedding, TransformerLM, lm_criterion)
    from bigdl_tpu.optim import Adam, DistriOptimizer, LocalOptimizer, Trigger
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random_generator import RandomGenerator

    if not Engine.is_initialized():
        Engine.init()
    RandomGenerator.set_seed(0)

    from bigdl_tpu.nn.incremental import iter_modules

    if args.lora and not args.model_snapshot:
        print("WARNING: --lora without --model-snapshot freezes a RANDOM "
              "base and trains only the adapters — this is almost never "
              "what you want (LoRA fine-tunes a pretrained model)")
    if args.model_snapshot:
        model = nn.AbstractModule.load(args.model_snapshot)
        # trust the MODEL, not the flags, for everything structural
        mods = list(iter_modules(model))
        args.fused_head = any(isinstance(m, nn.FusedLMHead) for m in mods)
        args.rope = any(getattr(m, "rope", False) for m in mods
                        if isinstance(m, nn.MultiHeadAttention))
        emb = [m for m in mods if isinstance(m, nn.LookupTable)]
        if emb and emb[0].n_index != args.vocab_size:
            print(f"snapshot vocab {emb[0].n_index} overrides "
                  f"--vocab-size {args.vocab_size}")
            args.vocab_size = emb[0].n_index
        pos = [m for m in mods if isinstance(m, PositionEmbedding)]
        if pos and args.seq_len > pos[0].max_len:
            print(f"snapshot max_len {pos[0].max_len} caps "
                  f"--seq-len {args.seq_len}")
            args.seq_len = pos[0].max_len
    else:
        model = TransformerLM(args.vocab_size, args.embed_dim, args.num_heads,
                              args.num_layers, max_len=args.seq_len,
                              dropout=args.dropout, remat=args.remat,
                              fused_head=args.fused_head,
                              num_kv_heads=args.num_kv_heads,
                              position="rope" if args.rope else "learned",
                              norm=args.norm, mlp_kind=args.mlp)
    if args.lora:
        already = any(isinstance(m, nn.LoRALinear)
                      or getattr(m, "lora_rank", None)
                      for m in iter_modules(model))
        if already:
            print("snapshot already carries LoRA adapters — resuming "
                  "fine-tuning with them (bases stay frozen)")
        else:
            n = nn.apply_lora(model, rank=args.lora)
            print(f"LoRA: adapted {n} modules at rank {args.lora} "
                  f"(base frozen)")

    if args.folder is not None:
        from bigdl_tpu.dataset.text import Dictionary, SentenceTokenizer
        text = open(args.folder).read()
        tokens = next(iter(SentenceTokenizer()(iter([text]))))
        vocab = Dictionary(tokens, vocab_size=args.vocab_size)
        ids = np.asarray([vocab.get_index(t) for t in tokens], np.int32)
    else:
        ids = synthetic_ptb(args.synthetic_tokens, vocab_size=args.vocab_size)
    xs, ys = ptb_windows(ids, args.seq_len)
    samples = [Sample(x, y) for x, y in zip(xs, ys)]
    data = (DataSet.array(samples, distributed=args.distributed)
            >> SampleToMiniBatch(args.batch_size))

    criterion = lm_criterion(fused_head=args.fused_head)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    opt = (cls(model, data, criterion)
           .set_optim_method(Adam(learningrate=args.learning_rate))
           .set_end_when(Trigger.max_iteration(args.max_iteration)))
    opt.optimize()
    print(f"final loss: {opt.state['loss']:.4f}")
    if args.save:
        model.save_module(args.save)
        print(f"saved to {args.save}")
    if args.generate:
        # rope models have no position table to outgrow; only the learned
        # table bounds total length
        if not args.rope and args.generate + args.seq_len // 4 > args.seq_len:
            raise SystemExit("--generate must fit in --seq-len (the model's "
                             "max_len) together with the seed prefix")
        seed = np.asarray(xs[0][: args.seq_len // 4])[None].astype(np.int32)
        bs = nn.SequenceBeamSearch(model, beam_size=args.beam, eos_id=-1,
                                   decode_length=args.generate,
                                   alpha=0.6).evaluate()
        out = bs.forward(seed)
        print("generated ids:", np.asarray(out[1])[0, 0].tolist())
    return opt.state["loss"]


if __name__ == "__main__":
    main()
