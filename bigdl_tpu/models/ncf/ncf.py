"""Neural Collaborative Filtering (NeuMF) — the recommendation-example model.

Reference parity (SURVEY.md §2.5 Examples, expected upstream
``<dl>/example/recommendation/NeuralCFexample.scala`` + ``NeuralCF`` model —
unverified, mount empty): GMF branch (elementwise product of user/item
embeddings) + MLP branch (concatenated embeddings through a ReLU tower), fused
by a final affine layer into class scores.

TPU-native: the whole model is one ``nn.Graph`` — embeddings are gathers, both
branches and the fusion compile into a single XLA program; batched (user, item)
id pairs arrive as one (N, 2) int32 tensor, so the input pipeline ships one
array per batch instead of a table of columns.
"""

from __future__ import annotations

from bigdl_tpu import nn


def NeuralCF(user_count: int, item_count: int, class_num: int = 2,
             user_embed: int = 16, item_embed: int = 16,
             hidden_layers: tuple[int, ...] = (32, 16, 8),
             mf_embed: int = 8, hash_buckets: int = 0,
             sharded: bool = False) -> nn.Graph:
    """Build NeuMF. ``hash_buckets > 0`` switches both id spaces to the hashing
    trick (``HashBucketEmbedding``) so unbounded ids need no dictionary.
    ``sharded=True`` wraps every table in ``parallel.ShardedEmbedding``:
    row-sharded placement over the mesh's ``model`` axis, deduped gathers, and
    sparse per-row optimizer updates when trained (bitwise-equal forward).

    Input: (N, 2) int32 of 1-based (user, item) ids — or raw ids when hashing.
    Output: (N, class_num) log-probabilities.
    """
    def make_embed(count: int, dim: int):
        if hash_buckets > 0:
            table = nn.HashBucketEmbedding(hash_buckets, dim)
        else:
            table = nn.LookupTable(count, dim)
        if sharded:
            from bigdl_tpu.parallel.embedding import ShardedEmbedding
            return ShardedEmbedding(table)
        return table

    inp = nn.Input()
    user = nn.Select(2, 1).inputs(inp)   # (N,) user ids
    item = nn.Select(2, 2).inputs(inp)   # (N,) item ids

    # GMF branch: elementwise product in the latent space
    mf_user = make_embed(user_count, mf_embed).inputs(user)
    mf_item = make_embed(item_count, mf_embed).inputs(item)
    gmf = nn.CMulTable().inputs(mf_user, mf_item)

    # MLP branch: concat embeddings → ReLU tower
    mlp_user = make_embed(user_count, user_embed).inputs(user)
    mlp_item = make_embed(item_count, item_embed).inputs(item)
    x = nn.JoinTable(2).inputs(mlp_user, mlp_item)
    in_dim = user_embed + item_embed
    for width in hidden_layers:
        x = nn.Linear(in_dim, width).inputs(x)
        x = nn.ReLU().inputs(x)
        in_dim = width

    merged = nn.JoinTable(2).inputs(gmf, x)
    out = nn.Linear(mf_embed + in_dim, class_num).inputs(merged)
    out = nn.LogSoftMax().inputs(out)
    return nn.Graph(inp, out)
