"""NCF recommendation example main (reference parity: upstream
``example/recommendation/NeuralCFexample.scala`` — unverified, SURVEY.md §2.5).

``python -m bigdl_tpu.models.ncf.train`` — trains NeuMF on implicit-feedback
interactions (synthetic by default: each user has a latent affinity over item
clusters, positives are drawn from it, negatives sampled uniformly), then
evaluates HitRatio@k / NDCG@k over (1 positive + neg_num negatives) groups.
"""

from __future__ import annotations

import argparse

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="NeuralCF on implicit interactions")
    p.add_argument("-b", "--batch-size", type=int, default=256)
    p.add_argument("--learning-rate", type=float, default=1e-3)
    p.add_argument("--optimizer", default="adam", choices=["adam", "sgd"])
    p.add_argument("--max-epoch", type=int, default=4)
    p.add_argument("--user-count", type=int, default=200)
    p.add_argument("--item-count", type=int, default=100)
    p.add_argument("--interactions", type=int, default=8192)
    p.add_argument("--neg-ratio", type=int, default=3,
                   help="training negatives per positive")
    p.add_argument("--eval-neg-num", type=int, default=20,
                   help="candidates per HR/NDCG group = eval_neg_num + 1")
    p.add_argument("--k", type=int, default=10)
    p.add_argument("--hash-buckets", type=int, default=0,
                   help=">0: use the hashing trick instead of dense vocab")
    p.add_argument("--sharded", action="store_true",
                   help="row-shard the embedding tables over the mesh's "
                        "'model' axis (parallel.ShardedEmbedding): deduped "
                        "gathers + sparse per-row optimizer updates")
    p.add_argument("--distributed", action="store_true")
    return p


def synthetic_interactions(user_count: int, item_count: int, n: int, seed=0):
    """Clustered implicit feedback: users prefer one of 8 item clusters, so a
    model that learns anything beats uniform ranking."""
    rng = np.random.default_rng(seed)
    n_clusters = 8
    user_cluster = rng.integers(0, n_clusters, size=user_count)
    item_cluster = rng.integers(0, n_clusters, size=item_count)
    users = rng.integers(0, user_count, size=n)
    members = [np.flatnonzero(item_cluster == c) for c in range(n_clusters)]
    # positive items: 80% from the user's cluster, 20% uniform
    pos_items = np.empty(n, np.int64)
    for idx in range(n):
        own = members[user_cluster[users[idx]]]
        if rng.random() < 0.8 and len(own):
            pos_items[idx] = rng.choice(own)
        else:
            pos_items[idx] = rng.integers(0, item_count)
    return users, pos_items, user_cluster, item_cluster


def build_training_samples(users, pos_items, item_count, neg_ratio, seed=1):
    from bigdl_tpu.dataset.sample import Sample
    rng = np.random.default_rng(seed)
    samples = []
    for u, i in zip(users, pos_items):
        # 0-based classes: 1 = interaction, 0 = no interaction
        samples.append(Sample(np.asarray([u + 1, i + 1], np.int32), np.int32(1)))
        for _ in range(neg_ratio):
            j = rng.integers(0, item_count)
            samples.append(Sample(np.asarray([u + 1, j + 1], np.int32), np.int32(0)))
    rng.shuffle(samples)
    return samples


def build_eval_batches(users, pos_items, item_count, neg_num, batch_groups=8,
                       seed=2):
    """(1 positive + neg_num negatives) per group; MiniBatches of whole groups."""
    from bigdl_tpu.dataset.sample import MiniBatch
    rng = np.random.default_rng(seed)
    batches, feats, labels = [], [], []
    for u, i in zip(users, pos_items):
        cand = [(u + 1, i + 1, 1)]
        while len(cand) < neg_num + 1:
            j = int(rng.integers(0, item_count))
            if j != i:
                cand.append((u + 1, j + 1, 0))
        for uu, ii, y in cand:
            feats.append([uu, ii])
            labels.append(y)
        if len(feats) >= batch_groups * (neg_num + 1):
            batches.append(MiniBatch(np.asarray(feats, np.int32),
                                     np.asarray(labels, np.int32)))
            feats, labels = [], []
    if feats:
        batches.append(MiniBatch(np.asarray(feats, np.int32),
                                 np.asarray(labels, np.int32)))
    return batches


def main(argv=None):
    args = build_parser().parse_args(argv)

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.models.ncf import NeuralCF
    from bigdl_tpu.optim import (
        Adam, DistriOptimizer, HitRatio, LocalOptimizer, NDCG, SGD, Trigger,
    )
    from bigdl_tpu.optim.evaluator import run_device_eval
    from bigdl_tpu.utils.engine import Engine

    Engine.init()

    users, pos_items, _, _ = synthetic_interactions(
        args.user_count, args.item_count, args.interactions)
    # leave-one-out evaluation (reference NCF protocol): each user's LAST
    # positive is held out of training and ranked against sampled negatives —
    # the metrics measure generalization, not memorization
    last_idx = {}
    for idx, u in enumerate(users):
        last_idx[int(u)] = idx
    holdout = set(last_idx.values())
    train_mask = np.array([i not in holdout for i in range(len(users))])
    train_samples = build_training_samples(
        users[train_mask], pos_items[train_mask], args.item_count,
        args.neg_ratio)
    data = DataSet.array(train_samples, distributed=args.distributed) \
        >> SampleToMiniBatch(args.batch_size)

    model = NeuralCF(args.user_count, args.item_count, class_num=2,
                     hash_buckets=args.hash_buckets, sharded=args.sharded)
    cls = DistriOptimizer if args.distributed else LocalOptimizer
    if args.optimizer == "adam":
        method = Adam(learningrate=args.learning_rate)
    else:
        method = SGD(learningrate=args.learning_rate, momentum=0.9, dampening=0.0)
    opt = (cls(model, data, nn.ClassNLLCriterion())
           .set_optim_method(method)
           .set_end_when(Trigger.max_epoch(args.max_epoch)))
    opt.log_every = 20
    opt.optimize()

    # ranked evaluation on the held-out positives: score = P(interaction)
    eval_pairs = sorted(last_idx.items())
    eval_users = np.asarray([u for u, _ in eval_pairs])
    eval_items = np.asarray([pos_items[i] for _, i in eval_pairs])
    batches = build_eval_batches(eval_users, eval_items, args.item_count,
                                 args.eval_neg_num)
    model.evaluate()
    hr = HitRatio(k=args.k, neg_num=args.eval_neg_num)
    ndcg = NDCG(k=args.k, neg_num=args.eval_neg_num)
    # device-resident eval: HR/NDCG fold into O(1) scalars on device — the
    # only d2h traffic is the final accumulated pytree, never the logits
    hr_res, ndcg_res = run_device_eval(
        model, model.get_params(), model.get_state(),
        DataSet.array(batches), [hr, ndcg])[0]
    hr_v, n = hr_res.result()
    ndcg_v, _ = ndcg_res.result()
    random_hr = args.k / (args.eval_neg_num + 1)
    print(f"HitRatio@{args.k}: {hr_v:.4f} over {n} groups "
          f"(uniform-random baseline {random_hr:.4f})")
    print(f"NDCG@{args.k}: {ndcg_v:.4f}")
    return hr_v, ndcg_v


if __name__ == "__main__":
    main()
