from bigdl_tpu.models.maskrcnn.maskrcnn import MaskRCNN, MaskRCNNBackbone

__all__ = ["MaskRCNN", "MaskRCNNBackbone"]
