"""Mask R-CNN — inference model composed from the round-5 detection family.

Reference parity (SURVEY §2.1/§2.5: the reference carries the Mask-R-CNN
module set — RoiAlign/FPN/Pooler/RegionProposal/BoxHead/MaskHead — and a zoo
inference model over them, expected ``<dl>/models/maskrcnn`` — unverified,
mount empty). This builder wires those modules end-to-end the way the
reference zoo does: backbone pyramid → FPN → RPN proposals → box head →
per-class decode/NMS → mask head on the kept detections.

TPU shape discipline: every stage runs on FIXED budgets (proposal count,
detections per image), so the whole detector traces to ONE static-shape XLA
program — the same redesign the SSD family uses. Single-image contract
(matching the RegionProposal/Proposal layers); vmap/loop over images for
batches.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.utils.table import Table


def MaskRCNNBackbone(in_channels: int = 3,
                     widths: Sequence[int] = (32, 64, 128),
                     out_channels: int = 64) -> nn.Graph:
    """A small conv pyramid (stride 4/8/16 feature maps) + FPN — the
    structural stand-in for the reference's ResNet-FPN backbone (swap in
    ``models.resnet`` stages for real training; the wire format is the
    same: a Table of per-level (N, C, H, W) maps, fine → coarse)."""
    inp = nn.Input()

    def block(c_in, c_out, node):
        seq = nn.Sequential()
        seq.add(nn.SpatialMaxPooling(2, 2))
        seq.add(nn.SpatialConvolution(c_in, c_out, 3, 3, pad_w=1, pad_h=1))
        seq.add(nn.ReLU())
        seq.add(nn.SpatialConvolution(c_out, c_out, 3, 3, pad_w=1, pad_h=1))
        seq.add(nn.ReLU())
        return seq.inputs(node)

    # stride 4 stem: two stride-2 convs
    stem = (nn.Sequential()
            .add(nn.SpatialConvolution(in_channels, widths[0], 3, 3,
                                       stride_w=2, stride_h=2,
                                       pad_w=1, pad_h=1))
            .add(nn.ReLU())
            .add(nn.SpatialConvolution(widths[0], widths[0], 3, 3,
                                       stride_w=2, stride_h=2,
                                       pad_w=1, pad_h=1))
            .add(nn.ReLU())).inputs(inp)
    c3 = block(widths[0], widths[1], stem)           # stride 8
    c4 = block(widths[1], widths[2], c3)             # stride 16
    fpn = nn.FPN(list(widths), out_channels).inputs(stem, c3, c4)
    return nn.Graph(inp, fpn)


class MaskRCNN(nn.Container):
    """Single-image Mask-R-CNN inference: ``(1, 3, H, W)`` pixels →
    Table(dets (max_per_image, 6) ``[label, score, x1, y1, x2, y2]``,
    valid (max_per_image,), masks (max_per_image, n_classes, 2·mask_res,
    2·mask_res)). Image size is static per compile (the usual padded-batch
    serving discipline)."""

    def __init__(self, n_classes: int, image_size: Sequence[int] = (128, 128),
                 out_channels: int = 64, post_nms_topn: int = 60,
                 max_per_image: int = 20, box_resolution: int = 7,
                 mask_resolution: int = 14):
        backbone = MaskRCNNBackbone(out_channels=out_channels)
        scales = [1.0 / 4, 1.0 / 8, 1.0 / 16]
        rpn = nn.RegionProposal(out_channels,
                                anchor_sizes=(32, 64, 128),
                                feat_strides=(4, 8, 16),
                                pre_nms_topn=4 * post_nms_topn,
                                post_nms_topn=post_nms_topn,
                                rpn_min_size=2)
        box_head = nn.BoxHead(out_channels, box_resolution, scales, 2,
                              n_classes=n_classes, representation=256)
        mask_head = nn.MaskHead(out_channels, mask_resolution, scales, 2,
                                n_classes=n_classes, layers=(64, 64))
        super().__init__(backbone, rpn, box_head, mask_head)
        self.n_classes = n_classes
        self.image_size = tuple(int(s) for s in image_size)
        self.max_per_image = max_per_image
        self.detection_out = nn.DetectionOutputFrcnn(
            n_classes, score_thresh=0.05, max_per_image=max_per_image)

    def apply(self, params, state, input, *, training=False, rng=None):
        if training:
            raise ValueError(
                "MaskRCNN is the inference composition (reference zoo "
                "contract); train the heads against your proposal/target "
                "sampler directly")
        h, w = self.image_size
        if tuple(input.shape[-2:]) != (h, w):
            # im_info drives proposal/box clipping — a mismatched image
            # would be silently confined to the configured bounds
            raise ValueError(
                f"MaskRCNN compiled for {h}x{w} images, got "
                f"{input.shape[-2]}x{input.shape[-1]} (pad/resize, or build "
                f"with image_size matching the serving shape)")
        new_state = dict(state)

        def run(i, x):
            out, s = self.modules[i].apply(params[str(i)], state[str(i)], x,
                                           training=False, rng=None)
            new_state[str(i)] = s
            return out

        feats = run(0, input)                                   # FPN pyramid
        im_info = jnp.asarray([[float(h), float(w), 1.0]])
        rois, roi_valid = run(1, Table(feats, im_info)).values()
        logits, deltas = run(2, Table(feats, rois)).values()
        dout, _ = self.detection_out.apply(
            {}, {}, Table(logits, deltas, rois, im_info, roi_valid))
        dets, valid = dout.values()
        # mask head on the KEPT detections' boxes (batch col 0)
        det_rois = jnp.concatenate(
            [jnp.zeros((self.max_per_image, 1)), dets[:, 2:]], axis=1)
        masks = run(3, Table(feats, det_rois))
        return Table(dets, valid, masks), new_state

    def __repr__(self):
        return (f"MaskRCNN(classes={self.n_classes}, "
                f"image={self.image_size}, max={self.max_per_image})")


from bigdl_tpu.utils.serializer import register as _register  # noqa: E402

_register(MaskRCNN)
