"""ResNet model family — CIFAR-10 and ImageNet variants.

Reference parity (SURVEY.md §2.5, expected ``<dl>/models/resnet/ResNet.scala`` —
unverified, mount empty): the reference builder takes ``(classNum, T(opts))`` with
``depth`` (20/32/44/56/110 CIFAR = 6n+2 basic blocks; 18/34/50/101/152 ImageNet),
``shortcutType`` ("A" zero-padded identity, "B" projection on shape change, "C" projection
always), ``dataSet`` (CIFAR-10 | ImageNet), and ``optnet`` (memory-optimised variant —
irrelevant on TPU: XLA owns buffer reuse). Blocks are basicBlock (2×3x3) or bottleneck
(1x1→3x3→1x1, expansion 4); weights use MSRA (He) init; final-block BN gammas may be
zero-initialised for large-batch convergence.

TPU-native design notes: shortcut join is ``ConcatTable`` → ``CAddTable`` (a pure add XLA
fuses into the preceding conv epilogue); shortcut type A's zero-pad + stride is a
``lax``-friendly pad/slice with no custom kernel; global average pool is a mean reduce.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import MsraFiller, Zeros
from bigdl_tpu.utils.serializer import register as _register_serializable
from bigdl_tpu.utils.table import Table


@_register_serializable
class _ShortcutA(TensorModule):
    """Type-A shortcut: stride-subsample spatially, zero-pad extra channels (no params)."""

    def __init__(self, n_in: int, n_out: int, stride: int):
        super().__init__()
        self.n_in, self.n_out, self.stride = n_in, n_out, stride

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        x = input
        nhwc = layout.is_nhwc()
        if self.stride != 1:
            s = self.stride
            x = x[:, ::s, ::s, :] if nhwc else x[:, :, ::s, ::s]
        if self.n_out > self.n_in:
            pad = self.n_out - self.n_in
            widths = ((0, 0), (0, 0), (0, 0), (0, pad)) if nhwc \
                else ((0, 0), (0, pad), (0, 0), (0, 0))
            x = jnp.pad(x, widths)
        return x, state


def conv_bn(n_in: int, n_out: int, k: int, stride: int = 1, pad: int = 0,
            relu: bool = True, zero_bn_gamma: bool = False) -> nn.Sequential:
    """conv (MSRA init, no bias — BN supplies the shift) → BN → optional ReLU."""
    seq = (nn.Sequential()
           .add(nn.SpatialConvolution(n_in, n_out, k, k, stride, stride, pad, pad,
                                      with_bias=False, w_init=MsraFiller()))
           .add(nn.SpatialBatchNormalization(
               n_out, init_weight=Zeros() if zero_bn_gamma else None)))
    if relu:
        seq.add(nn.ReLU())
    return seq


def _shortcut(n_in: int, n_out: int, stride: int, shortcut_type: str) -> nn.AbstractModule:
    use_conv = (shortcut_type == "C"
                or (shortcut_type == "B" and (n_in != n_out or stride != 1)))
    if use_conv:
        return (nn.Sequential()
                .add(nn.SpatialConvolution(n_in, n_out, 1, 1, stride, stride,
                                           with_bias=False, w_init=MsraFiller()))
                .add(nn.SpatialBatchNormalization(n_out)))
    if n_in != n_out or stride != 1:
        return _ShortcutA(n_in, n_out, stride)
    return nn.Identity()


def basic_block(n_in: int, n_out: int, stride: int, shortcut_type: str,
                zero_init_residual: bool = False) -> nn.Sequential:
    """Two 3x3 convs + shortcut (ResNet-18/34 and all CIFAR depths)."""
    branch = (nn.Sequential()
              .add(conv_bn(n_in, n_out, 3, stride, 1))
              .add(conv_bn(n_out, n_out, 3, 1, 1, relu=False,
                           zero_bn_gamma=zero_init_residual)))
    return (nn.Sequential()
            .add(nn.ConcatTable().add(branch).add(_shortcut(n_in, n_out, stride,
                                                            shortcut_type)))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


def bottleneck(n_in: int, n_mid: int, stride: int, shortcut_type: str,
               zero_init_residual: bool = False) -> nn.Sequential:
    """1x1 → 3x3 → 1x1 with expansion 4 (ResNet-50/101/152)."""
    n_out = n_mid * 4
    branch = (nn.Sequential()
              .add(conv_bn(n_in, n_mid, 1))
              .add(conv_bn(n_mid, n_mid, 3, stride, 1))
              .add(conv_bn(n_mid, n_out, 1, relu=False,
                           zero_bn_gamma=zero_init_residual)))
    return (nn.Sequential()
            .add(nn.ConcatTable().add(branch).add(_shortcut(n_in, n_out, stride,
                                                            shortcut_type)))
            .add(nn.CAddTable())
            .add(nn.ReLU()))


@_register_serializable
class _Conv1SpaceToDepth(TensorModule):
    """ImageNet stem conv (7x7 stride-2 pad-3, no bias) in space-to-depth form
    (the MLPerf ResNet TPU trick): the input is space-to-depth'd 2x2 on device
    (one cheap reshape+transpose) and the conv becomes 4x4 stride-1 over 12
    channels — much better MXU tiling than a 3-channel 7x7.

    The trainable weight IS the (64, 12, 4, 4) tensor, initialised as the exact
    rearrangement of an MSRA 7x7x3 stem; the 15 positions with no 7x7 pre-image
    (the implicit 8th tap) start at zero, so at init the output equals the plain
    conv bit-for-bit (verified by test). They train afterwards — equivalent to
    an 8x8 stride-2 stem, a strict superset of the reference's 7x7.
    """

    def __init__(self, n_out: int = 64):
        super().__init__()
        self.n_out = n_out
        self.reset()

    def reset(self) -> None:
        import numpy as np
        # same fan_in/fan_out as the plain 7x7 stem's SpatialConvolution.reset
        # (fan_out includes the kernel taps) so the init distribution matches
        w7 = np.asarray(MsraFiller().init((self.n_out, 3, 7, 7),
                                          fan_in=3 * 7 * 7,
                                          fan_out=self.n_out * 7 * 7))
        self._params = {"weight": jnp.asarray(self.transform_7x7(w7))}
        self.zero_grad_parameters()

    @staticmethod
    def transform_7x7(w7):
        """(O, 3, 7, 7) stem weights → the equivalent (O, 12, 4, 4) s2d weights.

        Output position o reads input p = 2o + k - 3 (k in 0..6). Writing
        p = 2m + r (r the parity), the s2d tap index is mh = m - o + 2 in 0..3
        and the s2d channel is rh*6 + rw*3 + c (matching the reshape below).
        """
        import numpy as np
        o, c_in = w7.shape[0], w7.shape[1]
        w4 = np.zeros((o, 4 * c_in, 4, 4), w7.dtype)
        for kh in range(7):
            rh, mh = (kh - 3) % 2, ((kh - 3) - (kh - 3) % 2) // 2 + 2
            for kw in range(7):
                rw, mw = (kw - 3) % 2, ((kw - 3) - (kw - 3) % 2) // 2 + 2
                for c in range(c_in):
                    w4[:, rh * 2 * c_in + rw * c_in + c, mh, mw] = w7[:, c, kh, kw]
        return w4

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        x = input
        if layout.is_nhwc():
            n, h, w, c = x.shape
            xs = x.reshape(n, h // 2, 2, w // 2, 2, c) \
                  .transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
        else:
            n, c, h, w = x.shape
            xs = x.reshape(n, c, h // 2, 2, w // 2, 2) \
                  .transpose(0, 3, 5, 1, 2, 4).reshape(n, 4 * c, h // 2, w // 2)
        out = jax.lax.conv_general_dilated(
            xs, params["weight"], window_strides=(1, 1),
            padding=((2, 1), (2, 1)),
            dimension_numbers=layout.conv_dimension_numbers())
        return out, state


@_register_serializable
class _GlobalAvgPool(TensorModule):
    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        return jnp.mean(input, axis=layout.spatial_axes(input.ndim)), state


# (depth -> (block kind, per-stage counts)) for ImageNet variants
_IMAGENET_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def ResNet(class_num: int, opt: Table | dict | None = None) -> nn.Sequential:
    """Builder mirroring the reference's ``ResNet(classNum, T(opts))``."""
    opt = dict(opt.items()) if isinstance(opt, Table) else dict(opt or {})
    depth = int(opt.get("depth", 18))
    dataset = opt.get("dataSet", opt.get("dataset", "CIFAR-10"))
    shortcut = opt.get("shortcutType", "B" if dataset == "ImageNet" else "A")
    zero_init_residual = bool(opt.get("zeroInitResidual", False))

    model = nn.Sequential()
    if dataset == "ImageNet":
        kind, counts = _IMAGENET_CFG[depth]
        if opt.get("conv1SpaceToDepth"):
            model.add(nn.Sequential()
                      .add(_Conv1SpaceToDepth(64))
                      .add(nn.SpatialBatchNormalization(64))
                      .add(nn.ReLU()))
        else:
            model.add(conv_bn(3, 64, 7, 2, 3))
        model.add(nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1))
        n_in = 64
        for stage, n_blocks in enumerate(counts):
            n_mid = 64 * (2 ** stage)
            for b in range(n_blocks):
                stride = 2 if (stage > 0 and b == 0) else 1
                if kind == "bottleneck":
                    model.add(bottleneck(n_in, n_mid, stride, shortcut,
                                         zero_init_residual))
                    n_in = n_mid * 4
                else:
                    model.add(basic_block(n_in, n_mid, stride, shortcut,
                                          zero_init_residual))
                    n_in = n_mid
        model.add(_GlobalAvgPool())
        model.add(nn.Linear(n_in, class_num, w_init=MsraFiller()))
    else:  # CIFAR-10: depth = 6n+2
        assert (depth - 2) % 6 == 0, "CIFAR depth must be 6n+2"
        n = (depth - 2) // 6
        model.add(conv_bn(3, 16, 3, 1, 1))
        n_in = 16
        for stage, n_out in enumerate([16, 32, 64]):
            for b in range(n):
                stride = 2 if (stage > 0 and b == 0) else 1
                model.add(basic_block(n_in, n_out, stride, shortcut,
                                      zero_init_residual))
                n_in = n_out
        model.add(_GlobalAvgPool())
        model.add(nn.Linear(64, class_num, w_init=MsraFiller()))
    model.add(nn.LogSoftMax())
    return model


def ResNet50(class_num: int = 1000, shortcut_type: str = "B") -> nn.Sequential:
    """The flagship/benchmark model (BASELINE.md config #2)."""
    return ResNet(class_num, {"depth": 50, "dataSet": "ImageNet",
                              "shortcutType": shortcut_type})
