from bigdl_tpu.models.inception.inception import (
    Inception_Layer_v1, Inception_v1, Inception_v1_NoAuxClassifier,
)
