"""Convergence harness — accuracy-parity measurement for the BASELINE configs.

Reference parity (SURVEY.md §6, BASELINE.md): the blueprint's definition of
done is throughput AND accuracy parity per config (top-1 / test accuracy /
perplexity). The zoo ``train.py`` mains already accept ``--folder <real
data>``; this harness wires them to per-config TARGET metrics and emits one
JSON verdict line, so the moment real data is mounted the parity claim is a
single command per row:

    bigdl-tpu converge lenet --data /datasets/mnist
    bigdl-tpu converge vgg16 --data /datasets/cifar10 --epochs 60

Targets are the standard literature values for each architecture/dataset —
NOT numbers recalled from the reference (BASELINE.md's no-fabrication rule;
the reference mount has been empty every round). When the reference mounts,
replace targets with its published figures via ``--target``.

With no data folder the mains fall back to their synthetic sets — the
harness still runs end-to-end (plumbing provable in CI) but marks the
verdict ``synthetic: true`` so a synthetic-data number is never mistaken
for a parity claim.
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _eval_top1(model, test_samples, batch_size):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.optim import Evaluator, Top1Accuracy

    test_set = DataSet.array(test_samples) >> SampleToMiniBatch(batch_size)
    res = Evaluator(model).test(test_set, [Top1Accuracy()])
    return float(res[0][0].result()[0])


def _base_argv(folder, epochs, batch_size, distributed, extra):
    argv = ["-b", str(batch_size), "--max-epoch", str(epochs)]
    if folder:
        argv += ["-f", folder]
    if distributed:
        argv += ["--distributed"]
    return argv + list(extra or ())


def _run_lenet(folder, epochs, batch_size, distributed, extra=()):
    from bigdl_tpu.dataset.mnist import load_mnist, to_samples
    from bigdl_tpu.models.lenet import train as lenet_train

    argv = _base_argv(folder, epochs, batch_size, distributed, extra)
    model = lenet_train.main(argv)
    test = to_samples(*load_mnist(folder, "test"))
    return _eval_top1(model, test, batch_size)


def _run_vgg16(folder, epochs, batch_size, distributed, extra=()):
    from bigdl_tpu.dataset.cifar import load_cifar10, normalize, to_samples
    from bigdl_tpu.models.vgg import train as vgg_train

    argv = _base_argv(folder, epochs, batch_size, distributed, extra)
    model = vgg_train.main(argv)
    imgs, labels = load_cifar10(folder, "test")
    test = to_samples(normalize(imgs), labels)
    return _eval_top1(model, test, batch_size)


def _run_imagenet(train_main, folder, epochs, batch_size, distributed, extra=()):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.models.imagenet_data import imagenet_sets
    from bigdl_tpu.optim import Evaluator, Top1Accuracy

    argv = _base_argv(folder, epochs, batch_size, distributed, extra)
    model = train_main.main(argv)
    _, val_set = imagenet_sets(folder, batch_size)
    res = Evaluator(model).test(val_set, [Top1Accuracy()])
    return float(res[0][0].result()[0])


def _run_resnet50(folder, epochs, batch_size, distributed, extra=()):
    from bigdl_tpu.models.resnet import train as resnet_train
    return _run_imagenet(resnet_train, folder, epochs, batch_size,
                         distributed, extra)


def _run_inception(folder, epochs, batch_size, distributed, extra=()):
    from bigdl_tpu.models.inception import train as inception_train
    return _run_imagenet(inception_train, folder, epochs, batch_size,
                         distributed, extra)


def _run_ptb(folder, epochs, batch_size, distributed, extra=()):
    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.text import load_ptb, ptb_windows
    from bigdl_tpu.models.rnn import train as rnn_train
    from bigdl_tpu.optim import Evaluator, Loss

    argv = _base_argv(folder, epochs, batch_size, distributed, extra)
    model = rnn_train.main(argv)
    ids, dictionary = load_ptb(folder, "train")
    tids, _ = load_ptb(folder, "test", dictionary=dictionary)
    xs, ys = ptb_windows(tids, 35)
    test_set = (DataSet.array([Sample(x, y) for x, y in zip(xs, ys)])
                >> SampleToMiniBatch(batch_size))
    criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                            size_average=True)
    res = Evaluator(model).test(test_set, [Loss(criterion)])
    mean_loss = float(res[0][0].result()[0])
    return float(np.exp(min(mean_loss, 20.0)))


# config → (runner, metric name, literature target, higher_is_better,
#           default epochs, default batch)
CONFIGS = {
    "lenet": (_run_lenet, "top1", 0.985, True, 5, 128),
    "vgg16": (_run_vgg16, "top1", 0.90, True, 60, 128),
    "resnet50": (_run_resnet50, "top1", 0.747, True, 90, 256),
    "inception": (_run_inception, "top1", 0.689, True, 90, 256),
    "ptb-lstm": (_run_ptb, "perplexity", 120.0, False, 13, 64),
}


def converge(config: str, data_folder: str | None = None,
             epochs: int | None = None, batch_size: int | None = None,
             target: float | None = None, distributed: bool = False,
             extra: tuple = ()) -> dict:
    """Train a BASELINE config and judge its final metric against the target.

    Returns the verdict dict (also usable programmatically); ``achieved`` is
    None when the run was synthetic — a fallback dataset can't prove parity.
    """
    if config not in CONFIGS:
        raise ValueError(f"unknown config {config!r}; have {sorted(CONFIGS)}")
    runner, metric, default_target, higher, d_epochs, d_batch = CONFIGS[config]
    target = default_target if target is None else float(target)
    epochs = d_epochs if epochs is None else int(epochs)
    batch_size = d_batch if batch_size is None else int(batch_size)
    value = runner(data_folder, epochs, batch_size, distributed, extra)
    synthetic = data_folder is None
    achieved = None if synthetic else (
        value >= target if higher else value <= target)
    return {
        "config": config,
        "metric": metric,
        "value": round(float(value), 4),
        "target": target,
        "achieved": achieved,
        "synthetic": synthetic,
        "epochs": epochs,
        "batch": batch_size,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="accuracy-parity harness for the BASELINE configs")
    p.add_argument("config", choices=sorted(CONFIGS))
    p.add_argument("--data", default=None,
                   help="real dataset folder (absent → synthetic fallback, "
                        "verdict marked synthetic)")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--target", type=float, default=None,
                   help="override the literature target "
                        "(e.g. the reference's published figure)")
    p.add_argument("--distributed", action="store_true")
    # unknown options are forwarded to the config's train main
    # (e.g. --learning-rate 0.1)
    args, rest = p.parse_known_args(argv)
    verdict = converge(args.config, args.data, args.epochs, args.batch_size,
                       args.target, args.distributed, tuple(rest))
    print(json.dumps(verdict))
    return 0


if __name__ == "__main__":
    sys.exit(main())
