"""Fully-connected layers.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/Linear.scala`` — unverified): weight
shape (outputSize, inputSize), optional bias, Torch default init U(-1/sqrt(fanIn), +).
TPU-native: one ``jnp.dot`` lowered onto the MXU; weight regularisation hooks carried as
metadata consumed by the optimizer.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform, Zeros


def normalize_linear_input(input):
    """Reference Linear shape rule, shared with LoRALinear so the two can't
    drift: >2-D flattens to (batch, -1); 1-D promotes to a single row (and
    the returned ``restore`` demotes the output back)."""
    if input.ndim > 2:
        return input.reshape(input.shape[0], -1), (lambda out: out)
    if input.ndim == 1:
        return input[None, :], (lambda out: out[0])
    return input, (lambda out: out)


class Linear(TensorModule):
    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.reset()

    def reset(self) -> None:
        w = self.w_init.init((self.output_size, self.input_size),
                             fan_in=self.input_size, fan_out=self.output_size)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            b = self.b_init.init((self.output_size,),
                                 fan_in=self.input_size, fan_out=self.output_size)
            self._params["bias"] = jnp.asarray(b)
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x, restore = normalize_linear_input(input)
        out = x @ params["weight"].T
        if self.with_bias:
            out = out + params["bias"]
        return restore(out), state

    def __repr__(self):
        return f"Linear({self.input_size} -> {self.output_size})"
