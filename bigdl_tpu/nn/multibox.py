"""MultiBox training criterion — SSD detection loss.

Reference parity: the reference ships the SSD *inference* ops (PriorBox /
DetectionOutputSSD); SSD training lived outside its main tree, so this
criterion is the completion of the detection family rather than a line-item
port. Semantics follow the SSD paper / Caffe MultiBoxLoss: match priors to
ground truth by IoU (best-gt-per-prior over a threshold, plus the best prior
of every gt force-matched), encode matched boxes against their priors with
the variance-scaled center-size encoding, smooth-L1 on localization, softmax
cross-entropy on confidence with 3:1 hard-negative mining.

TPU-native shape discipline: ground truth arrives PADDED — ``(N, G, 5)`` rows
``[label, x1, y1, x2, y2]`` with label -1 padding — so matching, encoding and
mining are fixed-shape tensor programs (argmax matching over the (P, G) IoU
matrix, top-k negative selection) inside one jitted loss; nothing falls back
to the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.criterion import AbstractCriterion
from bigdl_tpu.nn.detection import encode_ssd, pairwise_iou
from bigdl_tpu.utils.table import Table


def match_priors(priors: jnp.ndarray, gt_boxes: jnp.ndarray,
                 gt_valid: jnp.ndarray, iou_threshold: float):
    """SSD two-way matching. ``priors (P, 4)``, ``gt_boxes (G, 4)``,
    ``gt_valid (G,)`` bool. Returns ``(matched_gt (P,) int32, is_pos (P,)
    bool)`` — matched_gt[p] is the gt index each prior trains against."""
    iou = pairwise_iou(priors, gt_boxes)               # (P, G)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)                  # (P,)
    best_gt_iou = jnp.max(iou, axis=1)
    # force-match: every valid gt claims its best prior (overrides threshold).
    # Invalid (padding) gts are routed OUT OF RANGE so mode="drop" discards
    # their scatter — they must not clobber a valid gt's claim on prior 0.
    best_prior = jnp.where(gt_valid, jnp.argmax(iou, axis=0),
                           priors.shape[0])            # (G,)
    forced = jnp.zeros(priors.shape[0], bool)
    forced_gt = jnp.zeros(priors.shape[0], jnp.int32)
    g_idx = jnp.arange(gt_boxes.shape[0], dtype=jnp.int32)
    forced = forced.at[best_prior].set(True, mode="drop")
    forced_gt = forced_gt.at[best_prior].set(g_idx, mode="drop")
    is_pos = (best_gt_iou >= iou_threshold) | forced
    matched = jnp.where(forced, forced_gt, best_gt).astype(jnp.int32)
    return matched, is_pos


class MultiBoxCriterion(AbstractCriterion):
    """SSD training loss over the head's raw predictions.

    input: Table ``(loc (N, P*4), conf (N, P*n_classes), priors (1, 2, P*4))``
    — the same wire format DetectionOutputSSD serves from.
    target: ``(N, G, 5)`` padded ground truth ``[label, x1, y1, x2, y2]``
    (label -1 = padding; label 0 is reserved for background).

    loss = (smooth-L1(loc) + softmax-CE(conf)) / max(#positives, 1), with
    ``neg_pos_ratio`` hard negatives (highest-confidence-wrong background
    priors) mined per image.
    """

    # normalized by the per-batch positive count: mean-like under gradient
    # accumulation (same caveat as weighted ClassNLL — per-batch denominators
    # can differ micro vs full under imbalance)
    size_average = True

    def __init__(self, n_classes: int, iou_threshold: float = 0.5,
                 neg_pos_ratio: float = 3.0, loc_weight: float = 1.0):
        super().__init__()
        self.n_classes = int(n_classes)
        self.iou_threshold = float(iou_threshold)
        self.neg_pos_ratio = float(neg_pos_ratio)
        self.loc_weight = float(loc_weight)

    def apply(self, input, target):
        xs = input.values() if isinstance(input, Table) else list(input)
        loc, conf, priors = xs[0], xs[1], xs[2]
        n = loc.shape[0]
        p = loc.shape[1] // 4
        pri = priors.reshape(2, p, 4)
        prior_boxes, prior_var = pri[0], pri[1]
        loc = loc.reshape(n, p, 4)
        conf = conf.reshape(n, p, self.n_classes)

        def one_image(loc_i, conf_i, gt_i):
            labels = gt_i[:, 0].astype(jnp.int32)
            gt_valid = labels > 0
            matched, is_pos = match_priors(prior_boxes, gt_i[:, 1:],
                                           gt_valid, self.iou_threshold)
            # localization: smooth-L1 on encoded offsets, positives only
            tgt_boxes = gt_i[:, 1:][matched]
            enc = encode_ssd(prior_boxes, prior_var, tgt_boxes)
            diff = jnp.abs(loc_i - enc)
            sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
            loc_loss = jnp.where(is_pos, sl1.sum(axis=1), 0.0).sum()

            # confidence: positives train their class, mined negatives bg(0)
            cls_tgt = jnp.where(is_pos, labels[matched], 0)
            logp = jax.nn.log_softmax(conf_i, axis=-1)
            ce = -jnp.take_along_axis(logp, cls_tgt[:, None], axis=1)[:, 0]
            n_pos = is_pos.sum()
            # hard negative mining: top-k background priors by CE
            n_neg = jnp.minimum(
                (self.neg_pos_ratio * n_pos).astype(jnp.int32),
                p - n_pos)
            neg_score = jnp.where(is_pos, -jnp.inf, ce)
            order = jnp.argsort(-neg_score)
            rank = jnp.zeros(p, jnp.int32).at[order].set(jnp.arange(p))
            is_neg = (~is_pos) & (rank < n_neg)
            conf_loss = jnp.where(is_pos | is_neg, ce, 0.0).sum()
            return loc_loss, conf_loss, n_pos

        loc_l, conf_l, n_pos = jax.vmap(one_image)(loc, conf, target)
        denom = jnp.maximum(n_pos.sum(), 1).astype(jnp.float32)
        return (self.loc_weight * loc_l.sum() + conf_l.sum()) / denom

    def __repr__(self):
        return (f"MultiBoxCriterion(classes={self.n_classes}, "
                f"iou={self.iou_threshold}, neg:pos={self.neg_pos_ratio})")
