"""Misc layer-zoo coverage: reductions, shrink/threshold activations, bilinear
forms, table algebra, upsampling.

Reference parity (SURVEY.md §2.1 layer zoo, expected one file per layer under
``<dl>/nn/`` — unverified, mount empty): these are the small single-op layers
that round out the ~200-layer surface. Each is one fused XLA op (VPU) or one
contraction (MXU); dims follow the reference's 1-based Torch convention.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import AbstractModule, TensorModule
from bigdl_tpu.nn.initialization import (
    InitializationMethod, RandomUniform, Xavier, Zeros,
)
from bigdl_tpu.utils.table import Table


def _axis(dim: int, ndim: int) -> int:
    return dim - 1 if dim > 0 else ndim + dim


class _Reduce(TensorModule):
    def __init__(self, dim: int = 1, n_input_dims: int = -1):
        super().__init__()
        self.dim = dim
        self.n_input_dims = n_input_dims

    def _resolve_axis(self, x) -> int:
        axis = _axis(self.dim, x.ndim)
        # a leading batch dim shifts POSITIVE dims only — negative dims count
        # from the end and are already layout-independent
        if self.dim > 0 and self.n_input_dims > 0 \
                and x.ndim == self.n_input_dims + 1:
            axis += 1
        return axis


class Max(_Reduce):
    """Max over dim (reference ``Max`` — returns values only)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.max(input, axis=self._resolve_axis(input)), state


class Min(_Reduce):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.min(input, axis=self._resolve_axis(input)), state


class Mean(_Reduce):
    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.mean(input, axis=self._resolve_axis(input)), state


class Sum(_Reduce):
    def __init__(self, dim: int = 1, n_input_dims: int = -1,
                 size_average: bool = False):
        super().__init__(dim, n_input_dims)
        self.size_average = size_average

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self._resolve_axis(input)
        out = jnp.sum(input, axis=axis)
        if self.size_average:
            out = out / input.shape[axis]
        return out, state


class Threshold(TensorModule):
    """``x if x > th else value`` (reference ``Threshold``)."""

    def __init__(self, threshold: float = 1e-6, value: float = 0.0,
                 inplace: bool = False):
        super().__init__()
        self.th, self.value = threshold, value

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.where(input > self.th, input, self.value), state


class HardShrink(TensorModule):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.where(jnp.abs(input) > self.lam, input, 0.0), state


class SoftShrink(TensorModule):
    def __init__(self, lam: float = 0.5):
        super().__init__()
        self.lam = lam

    def apply(self, params, state, input, *, training=False, rng=None):
        return (jnp.where(input > self.lam, input - self.lam, 0.0)
                + jnp.where(input < -self.lam, input + self.lam, 0.0)), state


class RReLU(TensorModule):
    """Randomized leaky ReLU: negative slope ~ U(lower, upper) in training,
    the midpoint in eval (torch semantics)."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 inplace: bool = False):
        super().__init__()
        self.lower, self.upper = lower, upper

    def needs_rng(self) -> bool:
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        if training and rng is not None:
            import jax
            a = jax.random.uniform(rng, input.shape, input.dtype,
                                   self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.where(input >= 0, input, a * input), state


class Negative(TensorModule):
    def __init__(self, inplace: bool = False):
        super().__init__()

    def apply(self, params, state, input, *, training=False, rng=None):
        return -input, state


class DotProduct(AbstractModule):
    """Rowwise dot product of a Table pair → (N,)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        return jnp.sum(xs[0] * xs[1], axis=-1), state


class MM(AbstractModule):
    """Matrix multiply of a Table pair, with optional transposes (reference
    ``MM(transA, transB)``); supports batched (N, a, b) operands."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.trans_a, self.trans_b = trans_a, trans_b

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        a, b = xs[0], xs[1]
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b, state


class MV(AbstractModule):
    """Matrix-vector product of a Table (matrix, vector) pair (batched OK)."""

    def __init__(self, trans: bool = False):
        super().__init__()
        self.trans = trans

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        m, v = xs[0], xs[1]
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v), state


class Euclidean(TensorModule):
    """Distance to learnable centers: out[b, o] = ||x[b] - w[o]||_2 (reference
    ``Euclidean(inputSize, outputSize)``)."""

    def __init__(self, input_size: int, output_size: int,
                 w_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size, self.output_size = input_size, output_size
        self.w_init = w_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.asarray(
            self.w_init.init((self.output_size, self.input_size),
                             fan_in=self.input_size, fan_out=self.output_size))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input if input.ndim == 2 else input[None]
        d = x[:, None, :] - params["weight"][None, :, :]
        out = jnp.sqrt(jnp.sum(jnp.square(d), axis=-1) + 1e-12)
        if input.ndim == 1:
            out = out[0]
        return out, state


class Bilinear(AbstractModule):
    """Bilinear form over a Table pair: out[b,o] = x1[b] @ W[o] @ x2[b] + bias
    (reference ``Bilinear(in1, in2, out)``; torch ``nn.Bilinear`` semantics)."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size1 = input_size1
        self.input_size2 = input_size2
        self.output_size = output_size
        self.bias_res = bias_res
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or Zeros()
        self.reset()

    def reset(self) -> None:
        fan_in = self.input_size1 * self.input_size2
        self._params = {"weight": jnp.asarray(
            self.w_init.init((self.output_size, self.input_size1, self.input_size2),
                             fan_in=fan_in, fan_out=self.output_size))}
        if self.bias_res:
            self._params["bias"] = jnp.asarray(
                self.b_init.init((self.output_size,), fan_in=fan_in,
                                 fan_out=self.output_size))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        x1, x2 = xs[0], xs[1]
        out = jnp.einsum("bi,oij,bj->bo", x1, params["weight"], x2)
        if self.bias_res:
            out = out + params["bias"]
        return out, state


class Maxout(TensorModule):
    """Maxout over ``pool_size`` linear pieces (reference ``Maxout``): a single
    Linear to pool_size*output units followed by a max over the pieces — one
    matmul on the MXU plus a reshape-max."""

    def __init__(self, input_size: int, output_size: int, pool_size: int,
                 with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_size, self.output_size, self.pool_size = \
            input_size, output_size, pool_size
        self.with_bias = with_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        n_out = self.output_size * self.pool_size
        self._params = {"weight": jnp.asarray(
            self.w_init.init((n_out, self.input_size),
                             fan_in=self.input_size, fan_out=n_out))}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(
                self.b_init.init((n_out,), fan_in=self.input_size, fan_out=n_out))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input if input.ndim == 2 else input[None]
        z = x @ params["weight"].T
        if self.with_bias:
            z = z + params["bias"]
        z = z.reshape(z.shape[0], self.output_size, self.pool_size)
        out = jnp.max(z, axis=-1)
        if input.ndim == 1:
            out = out[0]
        return out, state


class SpatialUpSamplingNearest(TensorModule):
    """Nearest-neighbor upsample by an integer scale, NCHW (reference
    ``SpatialUpSamplingNearest``)."""

    def __init__(self, scale: int):
        super().__init__()
        self.scale = int(scale)

    def apply(self, params, state, input, *, training=False, rng=None):
        out = jnp.repeat(jnp.repeat(input, self.scale, axis=-2),
                         self.scale, axis=-1)
        return out, state


class SpatialUpSamplingBilinear(TensorModule):
    """Bilinear upsample to scale*size, align_corners=True (torch
    ``UpsamplingBilinear2d`` / reference ``SpatialUpSamplingBilinear``)."""

    def __init__(self, scale: int):
        super().__init__()
        self.scale = int(scale)

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        n, c, h, w = x.shape
        oh, ow = h * self.scale, w * self.scale
        # align_corners=True sampling grid
        ys = jnp.linspace(0.0, h - 1.0, oh)
        xs_ = jnp.linspace(0.0, w - 1.0, ow)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs_).astype(jnp.int32), 0, w - 1)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, None, :, None]
        wx = (xs_ - x0)[None, None, None, :]
        g = lambda yy, xx: x[:, :, yy, :][:, :, :, xx]
        out = ((1 - wy) * (1 - wx) * g(y0, x0) + (1 - wy) * wx * g(y0, x1)
               + wy * (1 - wx) * g(y1, x0) + wy * wx * g(y1, x1))
        out = out.astype(x.dtype)
        if squeeze:
            out = out[0]
        return out, state


# ----------------------------------------------------------------- grad tricks
import jax as _jax


@_jax.custom_vjp
def _grad_reverse(x, lam):
    return x


def _grad_reverse_fwd(x, lam):
    return x, lam


def _grad_reverse_bwd(lam, g):
    return (-lam * g, None)


_grad_reverse.defvjp(_grad_reverse_fwd, _grad_reverse_bwd)


class GradientReversal(TensorModule):
    """Identity forward; backward multiplies the gradient by ``-lambda``
    (reference ``GradientReversal`` — domain-adversarial training). Implemented
    as a ``jax.custom_vjp`` so it works inside the one-jit training step."""

    def __init__(self, the_lambda: float = 1.0):
        super().__init__()
        self.the_lambda = float(the_lambda)

    def set_lambda(self, lam: float) -> "GradientReversal":
        self.the_lambda = float(lam)
        self._apply_cache = {}  # lambda is baked into the trace — invalidate
        # keep the recorded constructor args in sync (portable serializer
        # rebuilds from them; see pooling.ceil for the failure mode)
        args, _ = self._init_args
        self._init_args = ((), {"the_lambda": float(lam)})
        return self

    def apply(self, params, state, input, *, training=False, rng=None):
        return _grad_reverse(input, self.the_lambda), state


@_jax.custom_vjp
def _l1_penalty(x, strength):
    return x


def _l1_penalty_fwd(x, strength):
    return x, (jnp.sign(x), strength)


def _l1_penalty_bwd(res, g):
    sign, strength = res
    return (g + strength * sign.astype(g.dtype), None)


_l1_penalty.defvjp(_l1_penalty_fwd, _l1_penalty_bwd)


class L1Penalty(TensorModule):
    """Identity forward that adds an L1 sparsity gradient ``l1weight*sign(x)``
    on the way back (reference ``L1Penalty(l1weight, sizeAverage)``)."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True):
        super().__init__()
        self.l1weight = float(l1weight)
        self.size_average = size_average

    def apply(self, params, state, input, *, training=False, rng=None):
        strength = self.l1weight
        if self.size_average:
            strength = strength / input.size
        if training:
            return _l1_penalty(input, strength), state
        return input, state


class Scale(AbstractModule):
    """Elementwise affine y = x * w + b with weight/bias of shape ``size``
    broadcast over the batch (reference ``Scale`` = CMul + CAdd fused; the
    Caffe ``Scale`` layer analog)."""

    def __init__(self, size):
        super().__init__()
        self.size = tuple(int(s) for s in size)
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.ones(self.size, jnp.float32),
                        "bias": jnp.zeros(self.size, jnp.float32)}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        w, b = params["weight"], params["bias"]
        # broadcast (size) against (N, *size)-or-compatible input, torch-style
        shape = (1,) * (input.ndim - w.ndim) + w.shape
        return input * w.reshape(shape) + b.reshape(shape), state


class PairwiseDistance(AbstractModule):
    """p-norm distance between the two entries of a Table pair → (N,)
    (reference ``PairwiseDistance(norm)``; torch ``nn.PairwiseDistance``)."""

    def __init__(self, norm: int = 2):
        super().__init__()
        self.norm = norm

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        d = xs[0] - xs[1]
        if d.ndim == 1:
            d = d[None]
        p = float(self.norm)
        # epsilon once on the summed value, not per element — identical inputs
        # stay ~0 regardless of feature count (torch semantics)
        out = (jnp.sum(jnp.abs(d) ** p, axis=-1) + 1e-12) ** (1.0 / p)
        return out, state


class GaussianSampler(AbstractModule):
    """Reparameterised sample from N(mu, exp(log_var)) given a Table
    (mu, log_var) (reference ``GaussianSampler`` — the VAE sampling layer)."""

    def needs_rng(self) -> bool:
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        mu, log_var = xs[0], xs[1]
        if rng is None:
            return mu, state  # eval mode: the mean is the sample
        eps = _jax.random.normal(rng, mu.shape, mu.dtype)
        return mu + jnp.exp(0.5 * log_var) * eps, state


class Highway(AbstractModule):
    """Highway layer: ``t*g(Wx+b) + (1-t)*x`` with transform gate
    ``t = sigmoid(Wt x + bt)`` (reference ``Highway(size, withBias,
    activation)``). Two matmuls on the MXU, gating fused by XLA."""

    def __init__(self, size: int, with_bias: bool = True, activation=None,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.size = size
        self.with_bias = with_bias
        # Parameter-free AbstractModule or None → tanh. Parametric activations
        # (PReLU…) would need their params registered on this leaf module to
        # train; reject them loudly rather than silently freezing them.
        if activation is not None and activation.get_params():
            raise ValueError(
                "Highway only supports parameter-free activations (got "
                f"{type(activation).__name__} with trainable params); apply "
                "parametric activations as a separate layer after Highway")
        self.activation = activation
        self.w_init = w_init or Xavier()
        self.b_init = b_init or Zeros()
        self.reset()

    def reset(self) -> None:
        s = self.size
        self._params = {
            "weight": jnp.asarray(self.w_init.init((s, s), fan_in=s, fan_out=s)),
            "gate_weight": jnp.asarray(self.w_init.init((s, s), fan_in=s, fan_out=s)),
        }
        if self.with_bias:
            self._params["bias"] = jnp.asarray(
                self.b_init.init((s,), fan_in=s, fan_out=s))
            # negative gate bias opens the carry path early (standard practice)
            self._params["gate_bias"] = jnp.full((s,), -1.0, jnp.float32)
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        h = input @ params["weight"].T
        t = input @ params["gate_weight"].T
        if self.with_bias:
            h = h + params["bias"]
            t = t + params["gate_bias"]
        if self.activation is None:
            h = jnp.tanh(h)
        else:
            h, _ = self.activation.apply({}, {}, h, training=training, rng=None)
        t = _jax.nn.sigmoid(t)
        return t * h + (1.0 - t) * input, state


class UpSampling1D(TensorModule):
    """Repeat each temporal step ``length`` times: (N, T, C) → (N, T*length, C)
    (reference ``UpSampling1D``; keras temporal convention)."""

    def __init__(self, length: int = 2):
        super().__init__()
        self.length = int(length)

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = input.ndim - 2
        return jnp.repeat(input, self.length, axis=axis), state


class UpSampling2D(TensorModule):
    """Nearest-neighbor upsample by (size_h, size_w) (reference
    ``UpSampling2D``; spatial axes follow ``nn.layout``)."""

    def __init__(self, size=(2, 2)):
        super().__init__()
        self.size = (int(size[0]), int(size[1]))

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        ha, wa = layout.spatial_axes(input.ndim)
        out = jnp.repeat(input, self.size[0], axis=ha)
        return jnp.repeat(out, self.size[1], axis=wa), state


class UpSampling3D(TensorModule):
    """Nearest-neighbor upsample NCDHW by (d, h, w) (reference
    ``UpSampling3D``)."""

    def __init__(self, size=(2, 2, 2)):
        super().__init__()
        self.size = tuple(int(s) for s in size)

    def apply(self, params, state, input, *, training=False, rng=None):
        out = jnp.repeat(input, self.size[0], axis=-3)
        out = jnp.repeat(out, self.size[1], axis=-2)
        return jnp.repeat(out, self.size[2], axis=-1), state


def _bilinear_resize(x, oh, ow, align_corners):
    """NCHW bilinear resize via two gathers + lerp (XLA fuses the weights)."""
    n, c, h, w = x.shape

    def grid(out_size, in_size):
        if align_corners and out_size > 1:
            return jnp.linspace(0.0, in_size - 1.0, out_size)
        # half-pixel centers (torch align_corners=False / TF half_pixel)
        scale = in_size / out_size
        return jnp.clip((jnp.arange(out_size) + 0.5) * scale - 0.5,
                        0.0, in_size - 1.0)

    ys, xs_ = grid(oh, h), grid(ow, w)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs_).astype(jnp.int32), 0, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs_ - x0)[None, None, None, :]
    g = lambda yy, xx: x[:, :, yy, :][:, :, :, xx]
    out = ((1 - wy) * (1 - wx) * g(y0, x0) + (1 - wy) * wx * g(y0, x1)
           + wy * (1 - wx) * g(y1, x0) + wy * wx * g(y1, x1))
    return out.astype(x.dtype)


class ResizeBilinear(TensorModule):
    """Bilinear resize to an arbitrary (output_height, output_width), NCHW
    (reference ``ResizeBilinear(outputHeight, outputWidth, alignCorners)``)."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False):
        super().__init__()
        self.output_height = int(output_height)
        self.output_width = int(output_width)
        self.align_corners = align_corners

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        out = _bilinear_resize(x, self.output_height, self.output_width,
                               self.align_corners)
        if squeeze:
            out = out[0]
        return out, state


class Cropping2D(TensorModule):
    """Crop (top, bottom) rows and (left, right) cols off NCHW input
    (reference ``Cropping2D``)."""

    def __init__(self, height_crop=(0, 0), width_crop=(0, 0)):
        super().__init__()
        self.height_crop = (int(height_crop[0]), int(height_crop[1]))
        self.width_crop = (int(width_crop[0]), int(width_crop[1]))

    def apply(self, params, state, input, *, training=False, rng=None):
        (t, b), (l, r) = self.height_crop, self.width_crop
        h, w = input.shape[-2], input.shape[-1]
        if t + b >= h or l + r >= w:
            raise ValueError(
                f"Cropping2D extents {self.height_crop}/{self.width_crop} "
                f"consume the whole {h}x{w} input")
        return input[..., t:h - b or None, l:w - r or None], state


class Cropping3D(TensorModule):
    """Crop symmetric-pair extents off the three spatial dims of NCDHW input
    (reference ``Cropping3D``)."""

    def __init__(self, dim1_crop=(0, 0), dim2_crop=(0, 0), dim3_crop=(0, 0)):
        super().__init__()
        self.dim1_crop = tuple(int(v) for v in dim1_crop)
        self.dim2_crop = tuple(int(v) for v in dim2_crop)
        self.dim3_crop = tuple(int(v) for v in dim3_crop)

    def apply(self, params, state, input, *, training=False, rng=None):
        (a0, a1), (b0, b1), (c0, c1) = \
            self.dim1_crop, self.dim2_crop, self.dim3_crop
        d, h, w = input.shape[-3], input.shape[-2], input.shape[-1]
        if a0 + a1 >= d or b0 + b1 >= h or c0 + c1 >= w:
            raise ValueError(
                f"Cropping3D extents {self.dim1_crop}/{self.dim2_crop}/"
                f"{self.dim3_crop} consume the whole {d}x{h}x{w} input")
        return input[..., a0:d - a1 or None, b0:h - b1 or None,
                     c0:w - c1 or None], state


class ActivityRegularization(TensorModule):
    """Identity forward that declares an L1/L2 activity penalty (reference
    ``ActivityRegularization``; keras semantics). Rides the framework's
    ``penalty`` state convention (optim/optimizer.py): added to the training
    objective at FULL strength — the coefficient lives HERE, unlike the
    globally-scaled ``aux_loss`` leaf MoE uses — so keras-ported models keep
    their penalty magnitudes and coexist with MoE in one model."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        super().__init__()
        self.l1, self.l2 = float(l1), float(l2)
        self._state = {"penalty": jnp.zeros((), jnp.float32)}

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input.astype(jnp.float32)
        pen = self.l1 * jnp.sum(jnp.abs(x)) + self.l2 * jnp.sum(jnp.square(x))
        return input, {**state, "penalty": pen}


class NegativeEntropyPenalty(TensorModule):
    """Identity forward penalising low-entropy probability activations
    (reference ``NegativeEntropyPenalty``): penalty = beta * sum(p log p).
    Encourages exploration in probability outputs; full-strength ``penalty``
    leaf like ActivityRegularization (the coefficient is the layer's own)."""

    def __init__(self, beta: float = 0.01):
        super().__init__()
        self.beta = float(beta)
        self._state = {"penalty": jnp.zeros((), jnp.float32)}

    def apply(self, params, state, input, *, training=False, rng=None):
        p = input.astype(jnp.float32)
        ent = jnp.sum(p * jnp.log(jnp.clip(p, 1e-12, None)))
        return input, {**state, "penalty": self.beta * ent}


class CrossProduct(AbstractModule):
    """All pairwise dot products of a Table of N same-shape vectors →
    (batch, N*(N-1)/2) in (1,2),(1,3),...,(N-1,N) order (reference
    ``CrossProduct``, the DeepFM/feature-interaction building block)."""

    def __init__(self, num_tensor: int = 0, embedding_size: int = 0):
        super().__init__()
        self.num_tensor = num_tensor        # 0 = infer from input
        self.embedding_size = embedding_size  # 0 = any width

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        if self.num_tensor and len(xs) != self.num_tensor:
            raise ValueError(
                f"CrossProduct expected {self.num_tensor} tensors, "
                f"got {len(xs)}")
        if self.embedding_size:
            bad = [x.shape[-1] for x in xs if x.shape[-1] != self.embedding_size]
            if bad:
                raise ValueError(
                    f"CrossProduct expected embedding size "
                    f"{self.embedding_size}, got {bad}")
        outs = [jnp.sum(xs[i] * xs[j], axis=-1)
                for i in range(len(xs)) for j in range(i + 1, len(xs))]
        return jnp.stack(outs, axis=-1), state


class ImageNormalize(TensorModule):
    """On-device image normalization: ``(x * scale - mean) / std`` per channel.

    The TPU-native input path (SURVEY.md §2.2 redesign): the reference's
    pipeline normalizes on the CPU and ships float32 activations to the
    compute tier; on TPU the wire (PCIe/tunnel) is the scarce resource, so the
    feed stays ``uint8`` (4x fewer bytes than fp32) and this layer casts +
    normalizes on device, where XLA fuses it into the first convolution's
    epilogue at zero marginal cost. Defaults are the ImageNet mean/std in
    0-1 range with ``scale=1/255`` (uint8 pixels); pass ``scale=1.0`` for
    pre-scaled float input. Channel broadcasting follows ``nn.layout``.
    """

    def __init__(self, mean=(0.485, 0.456, 0.406), std=(0.229, 0.224, 0.225),
                 scale: float = 1.0 / 255.0):
        super().__init__()
        mean = mean if isinstance(mean, (tuple, list)) else (mean,)
        std = std if isinstance(std, (tuple, list)) else (std,)
        self.mean = tuple(float(m) for m in mean)
        self.std = tuple(float(s) for s in std)
        if len(self.mean) != len(self.std):
            raise ValueError(
                f"ImageNormalize: mean has {len(self.mean)} channels but std "
                f"has {len(self.std)} — they must pair up")
        self.scale = float(scale)

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        from bigdl_tpu.utils.engine import Engine
        x = jnp.asarray(input)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(Engine.compute_dtype() if Engine.is_initialized()
                         else jnp.float32)
        shape = layout.bias_shape(len(self.mean), x.ndim) if x.ndim >= 3 \
            else (len(self.mean),)
        mean = jnp.asarray(self.mean, x.dtype).reshape(shape)
        std = jnp.asarray(self.std, x.dtype).reshape(shape)
        return (x * jnp.asarray(self.scale, x.dtype) - mean) / std, state

    def __repr__(self):
        return (f"ImageNormalize(mean={self.mean}, std={self.std}, "
                f"scale={self.scale:g})")
