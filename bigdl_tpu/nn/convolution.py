"""Spatial convolution layers.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/SpatialConvolution.scala`` —
unverified): NCHW activations, OIHW weights (with groups: (nGroup, out/g, in/g, kH, kW)
upstream; here flat OIHW + ``feature_group_count``), stride (dW, dH), padding (padW, padH)
with ``-1`` meaning TensorFlow-style SAME. Default init Xavier-like U(-1/sqrt(fanIn), +).

TPU-native: ``lax.conv_general_dilated`` — XLA tiles it onto the MXU directly; the
reference's im2col+gemm with per-thread workspaces (BLAS path) and its mkldnn layout
reorders are both deleted as concepts.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomUniform


def _conv_padding(pad_w: int, pad_h: int):
    """Map reference pad ints to lax padding. -1 → SAME (reference convention)."""
    if pad_w == -1 or pad_h == -1:
        return "SAME"
    return [(pad_h, pad_h), (pad_w, pad_w)]


class SpatialConvolution(TensorModule):
    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 n_group: int = 1, propagate_back: bool = True,
                 with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None,
                 w_regularizer=None, b_regularizer=None):
        super().__init__()
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        self.n_input_plane = n_input_plane
        self.n_output_plane = n_output_plane
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.n_group = n_group
        self.propagate_back = propagate_back
        self.with_bias = with_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.reset()

    def reset(self) -> None:
        fan_in = (self.n_input_plane // self.n_group) * self.kernel_h * self.kernel_w
        fan_out = (self.n_output_plane // self.n_group) * self.kernel_h * self.kernel_w
        w = self.w_init.init(
            (self.n_output_plane, self.n_input_plane // self.n_group,
             self.kernel_h, self.kernel_w),
            fan_in=fan_in, fan_out=fan_out)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            b = self.b_init.init((self.n_output_plane,), fan_in=fan_in, fan_out=fan_out)
            self._params["bias"] = jnp.asarray(b)
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        x = input
        if not self.propagate_back:
            # reference propagateBack=false: no gradient to the INPUT (first
            # conv of a frozen stem); weight gradients still flow
            x = lax.stop_gradient(x)
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        out = lax.conv_general_dilated(
            x, params["weight"],
            window_strides=(self.stride_h, self.stride_w),
            padding=_conv_padding(self.pad_w, self.pad_h),
            dimension_numbers=layout.conv_dimension_numbers(),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            out = out + params["bias"].reshape(layout.bias_shape(
                self.n_output_plane))
        if squeeze:
            out = out[0]
        return out, state

    def fuse_bn(self, bn, relu: bool = False,
                fold_inference: Optional[bool] = None):
        """Fuse an adjacent :class:`~bigdl_tpu.nn.normalization
        .SpatialBatchNormalization` (and optional trailing ReLU) into one
        :class:`~bigdl_tpu.kernels.conv_bn.FusedConvBNReLU` module — the
        manual entry point of the graph-level ``nn.fuse_conv_bn`` pass.
        This module's live parameter arrays carry over untouched."""
        from bigdl_tpu.kernels.conv_bn import FusedConvBNReLU
        if bn.n_output != self.n_output_plane:
            raise ValueError(
                f"fuse_bn: bn features {bn.n_output} != conv output planes "
                f"{self.n_output_plane}")
        return FusedConvBNReLU(self, bn, relu=relu,
                               fold_inference=fold_inference)

    def __repr__(self):
        return (f"SpatialConvolution({self.n_input_plane} -> {self.n_output_plane}, "
                f"{self.kernel_w}x{self.kernel_h}, {self.stride_w},{self.stride_h}, "
                f"{self.pad_w},{self.pad_h})")


class SpatialConvolutionMap(TensorModule):
    """Convolution with an explicit input→output connection table (reference
    ``SpatialConvolutionMap``; torch's pre-grouped-conv sparse connectivity).
    ``conn_table`` is (K, 2) of 1-based (from_in_plane, to_out_plane) pairs;
    one (kh, kw) kernel is learned per connection. TPU-native execution:
    the K per-connection kernels scatter into a dense (O, I, kh, kw) weight
    (zeros where unconnected) and run as ONE dense MXU conv — identical math
    to the reference's per-connection loop, none of its scalar scheduling."""

    def __init__(self, conn_table, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        table = jnp.asarray(conn_table, jnp.int32).reshape(-1, 2)
        self.conn_table = [(int(a), int(b)) for a, b in table.tolist()]
        self.n_input_plane = max(a for a, _ in self.conn_table)
        self.n_output_plane = max(b for _, b in self.conn_table)
        self._to_idx = jnp.asarray([b - 1 for _, b in self.conn_table])
        self._from_idx = jnp.asarray([a - 1 for a, _ in self.conn_table])
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    @staticmethod
    def full(n_in: int, n_out: int):
        return [(i + 1, o + 1) for o in range(n_out) for i in range(n_in)]

    @staticmethod
    def one_to_one(n: int):
        return [(i + 1, i + 1) for i in range(n)]

    @staticmethod
    def random(n_in: int, n_out: int, n_from: int, seed: int = 0):
        import numpy as _np
        rng = _np.random.default_rng(seed)
        return [(int(i) + 1, o + 1)
                for o in range(n_out)
                for i in rng.choice(n_in, size=n_from, replace=False)]

    def reset(self) -> None:
        k = len(self.conn_table)
        # per-output fan-in mirrors the reference's per-connection init scale
        fan_in = self.kernel_h * self.kernel_w * max(
            1, k // self.n_output_plane)
        w = self.w_init.init((k, self.kernel_h, self.kernel_w),
                             fan_in=fan_in, fan_out=fan_in)
        b = self.b_init.init((self.n_output_plane,),
                             fan_in=fan_in, fan_out=fan_in)
        self._params = {"weight": jnp.asarray(w), "bias": jnp.asarray(b)}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        dense = jnp.zeros((self.n_output_plane, self.n_input_plane,
                           self.kernel_h, self.kernel_w),
                          params["weight"].dtype)
        # scatter-ADD: duplicate (from, to) pairs accumulate, matching the
        # reference's per-connection summation
        dense = dense.at[self._to_idx, self._from_idx].add(params["weight"])
        out = lax.conv_general_dilated(
            x, dense,
            window_strides=(self.stride_h, self.stride_w),
            padding=_conv_padding(self.pad_w, self.pad_h),
            dimension_numbers=layout.conv_dimension_numbers(),
        )
        out = out + params["bias"].reshape(layout.bias_shape(
            self.n_output_plane))
        if squeeze:
            out = out[0]
        return out, state

    def __repr__(self):
        return (f"SpatialConvolutionMap({len(self.conn_table)} connections, "
                f"{self.n_input_plane} -> {self.n_output_plane}, "
                f"{self.kernel_w}x{self.kernel_h})")


class SpatialSeparableConvolution(TensorModule):
    """Depthwise-separable conv (reference ``SpatialSeparableConvolution``):
    depthwise (channel multiplier) then 1x1 pointwise — two MXU convs, XLA
    fuses the intermediate."""

    def __init__(self, n_input_channel: int, n_output_channel: int,
                 depth_multiplier: int, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.n_input_channel = n_input_channel
        self.n_output_channel = n_output_channel
        self.depth_multiplier = depth_multiplier
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.with_bias = with_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        ic, m, oc = self.n_input_channel, self.depth_multiplier, \
            self.n_output_channel
        fan_d = self.kernel_h * self.kernel_w
        dw = self.w_init.init((ic * m, 1, self.kernel_h, self.kernel_w),
                              fan_in=fan_d, fan_out=fan_d * m)
        pw = self.w_init.init((oc, ic * m, 1, 1),
                              fan_in=ic * m, fan_out=oc)
        self._params = {"depth_weight": jnp.asarray(dw),
                        "point_weight": jnp.asarray(pw)}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(
                self.b_init.init((oc,), fan_in=ic * m, fan_out=oc))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        mid = lax.conv_general_dilated(
            x, params["depth_weight"],
            window_strides=(self.stride_h, self.stride_w),
            padding=_conv_padding(self.pad_w, self.pad_h),
            dimension_numbers=layout.conv_dimension_numbers(),
            feature_group_count=self.n_input_channel,
        )
        out = lax.conv_general_dilated(
            mid, params["point_weight"],
            window_strides=(1, 1),
            padding="VALID",
            dimension_numbers=layout.conv_dimension_numbers(),
        )
        if self.with_bias:
            out = out + params["bias"].reshape(layout.bias_shape(
                self.n_output_channel))
        if squeeze:
            out = out[0]
        return out, state

    def __repr__(self):
        return (f"SpatialSeparableConvolution({self.n_input_channel} -> "
                f"{self.n_output_channel}, x{self.depth_multiplier} depth, "
                f"{self.kernel_w}x{self.kernel_h})")


class SpatialDilatedConvolution(TensorModule):
    """Atrous convolution (reference ``<dl>/nn/SpatialDilatedConvolution.scala``)."""

    def __init__(self, n_input_plane, n_output_plane, kw, kh, dw=1, dh=1,
                 pad_w=0, pad_h=0, dilation_w=1, dilation_h=1,
                 w_init=None, b_init=None, with_bias: bool = True):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h = pad_w, pad_h
        self.dilation_w, self.dilation_h = dilation_w, dilation_h
        self.with_bias = with_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self):
        fan_in = self.n_input_plane * self.kh * self.kw
        fan_out = self.n_output_plane * self.kh * self.kw
        w = self.w_init.init((self.n_output_plane, self.n_input_plane, self.kh, self.kw),
                             fan_in=fan_in, fan_out=fan_out)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(
                self.b_init.init((self.n_output_plane,), fan_in=fan_in, fan_out=fan_out))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        out = lax.conv_general_dilated(
            x, params["weight"],
            window_strides=(self.dh, self.dw),
            padding=[(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)],
            rhs_dilation=(self.dilation_h, self.dilation_w),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None]
        if squeeze:
            out = out[0]
        return out, state


class SpatialFullConvolution(TensorModule):
    """Transposed convolution (deconvolution), reference ``SpatialFullConvolution``."""

    def __init__(self, n_input_plane, n_output_plane, kw, kh, dw=1, dh=1,
                 pad_w=0, pad_h=0, adj_w=0, adj_h=0, n_group=1,
                 no_bias: bool = False, w_init=None, b_init=None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.kw, self.kh, self.dw, self.dh = kw, kh, dw, dh
        self.pad_w, self.pad_h, self.adj_w, self.adj_h = pad_w, pad_h, adj_w, adj_h
        self.n_group = n_group
        self.with_bias = not no_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self):
        fan_in = self.n_input_plane * self.kh * self.kw
        fan_out = self.n_output_plane * self.kh * self.kw
        # Torch layout for full conv: (nIn, nOut/g, kH, kW); keep IOHW and tell lax.
        w = self.w_init.init(
            (self.n_input_plane, self.n_output_plane // self.n_group, self.kh, self.kw),
            fan_in=fan_in, fan_out=fan_out)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(
                self.b_init.init((self.n_output_plane,), fan_in=fan_in, fan_out=fan_out))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        kh, kw = self.kh, self.kw
        pad = [(kh - 1 - self.pad_h, kh - 1 - self.pad_h + self.adj_h),
               (kw - 1 - self.pad_w, kw - 1 - self.pad_w + self.adj_w)]
        # lax convs are correlations; the transpose of a correlation applies the
        # SPATIALLY FLIPPED kernel (torch/Caffe deconv semantics)
        w = jnp.flip(params["weight"], (-2, -1))
        if self.n_group > 1:
            # grouped deconv: torch keeps (I, O/g) with groups sliced along I;
            # lax wants rhs (I/g, O) with group j in O-slice j — rearrange
            g = self.n_group
            i, og = w.shape[0], w.shape[1]
            w = w.reshape(g, i // g, og, kh, kw).transpose(1, 0, 2, 3, 4) \
                 .reshape(i // g, g * og, kh, kw)
        out = lax.conv_general_dilated(
            x, w,
            window_strides=(1, 1),
            padding=pad,
            lhs_dilation=(self.dh, self.dw),
            dimension_numbers=("NCHW", "IOHW", "NCHW"),
            feature_group_count=self.n_group,
        )
        if self.with_bias:
            out = out + params["bias"][None, :, None, None]
        if squeeze:
            out = out[0]
        return out, state


class TemporalConvolution(TensorModule):
    """1-D convolution over time (reference ``<dl>/nn/TemporalConvolution.scala``
    — unverified): input (N, T, input_frame_size) → (N, (T-kw)//dw+1,
    output_frame_size). One NWC conv lowered onto the MXU."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1, with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        fan_in = self.input_frame_size * self.kernel_w
        w = self.w_init.init((self.kernel_w, self.input_frame_size,
                              self.output_frame_size),
                             fan_in=fan_in, fan_out=self.output_frame_size)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            b = self.b_init.init((self.output_frame_size,), fan_in=fan_in,
                                 fan_out=self.output_frame_size)
            self._params["bias"] = jnp.asarray(b)
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 2
        if squeeze:
            x = x[None]
        out = lax.conv_general_dilated(
            x, params["weight"],
            window_strides=(self.stride_w,),
            padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        if self.with_bias:
            out = out + params["bias"]
        if squeeze:
            out = out[0]
        return out, state

    def __repr__(self):
        return (f"TemporalConvolution({self.input_frame_size} -> "
                f"{self.output_frame_size}, {self.kernel_w}, {self.stride_w})")


class SpatialShareConvolution(SpatialConvolution):
    """Reference ``SpatialShareConvolution``: a SpatialConvolution variant whose
    only upstream difference is sharing the im2col workspace across replica
    threads. XLA owns all workspace memory on TPU, so the compute is identical;
    the type is kept distinct for API and serialization parity."""


class LocallyConnected2D(TensorModule):
    """Unshared convolution (reference ``LocallyConnected2D``): each output
    location has its own filter bank. TPU-native: extract patches with
    ``conv_general_dilated_patches`` (one fused gather) and contract location-
    wise with a single batched einsum on the MXU — no per-location loop."""

    def __init__(self, n_input_plane: int, input_width: int, input_height: int,
                 n_output_plane: int, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.n_input_plane, self.n_output_plane = n_input_plane, n_output_plane
        self.input_width, self.input_height = input_width, input_height
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w, self.stride_h = stride_w, stride_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.with_bias = with_bias
        self.out_h = (input_height + 2 * pad_h - kernel_h) // stride_h + 1
        self.out_w = (input_width + 2 * pad_w - kernel_w) // stride_w + 1
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        k = self.n_input_plane * self.kernel_h * self.kernel_w
        n_loc = self.out_h * self.out_w
        w = self.w_init.init((n_loc, self.n_output_plane, k),
                             fan_in=k, fan_out=self.n_output_plane)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(self.b_init.init(
                (n_loc, self.n_output_plane), fan_in=k,
                fan_out=self.n_output_plane))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        # patches: (N, C*kh*kw, OH, OW), feature dim ordered (c, kh, kw) —
        # matches the (n_loc, o, c*kh*kw) weight layout's contraction dim
        patches = lax.conv_general_dilated_patches(
            x, (self.kernel_h, self.kernel_w),
            (self.stride_h, self.stride_w),
            [(self.pad_h, self.pad_h), (self.pad_w, self.pad_w)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        n = patches.shape[0]
        p = patches.reshape(n, patches.shape[1], -1)        # (N, K, P)
        out = jnp.einsum("nkp,pok->npo", p, params["weight"])
        if self.with_bias:
            out = out + params["bias"][None]
        out = jnp.transpose(out, (0, 2, 1)).reshape(
            n, self.n_output_plane, self.out_h, self.out_w)
        if squeeze:
            out = out[0]
        return out, state


class LocallyConnected1D(TensorModule):
    """Unshared temporal convolution (reference ``LocallyConnected1D``):
    input (N, T, C) like TemporalConvolution, per-output-frame filters."""

    def __init__(self, n_input_frame: int, input_frame_size: int,
                 output_frame_size: int, kernel_w: int, stride_w: int = 1,
                 with_bias: bool = True,
                 w_init: Optional[InitializationMethod] = None,
                 b_init: Optional[InitializationMethod] = None):
        super().__init__()
        self.n_input_frame = n_input_frame
        self.input_frame_size = input_frame_size
        self.output_frame_size = output_frame_size
        self.kernel_w, self.stride_w = kernel_w, stride_w
        self.with_bias = with_bias
        self.n_output_frame = (n_input_frame - kernel_w) // stride_w + 1
        self.w_init = w_init or RandomUniform()
        self.b_init = b_init or RandomUniform()
        self.reset()

    def reset(self) -> None:
        k = self.kernel_w * self.input_frame_size
        w = self.w_init.init((self.n_output_frame, self.output_frame_size, k),
                             fan_in=k, fan_out=self.output_frame_size)
        self._params = {"weight": jnp.asarray(w)}
        if self.with_bias:
            self._params["bias"] = jnp.asarray(self.b_init.init(
                (self.n_output_frame, self.output_frame_size),
                fan_in=k, fan_out=self.output_frame_size))
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 2
        if squeeze:
            x = x[None]
        idx = (jnp.arange(self.n_output_frame)[:, None] * self.stride_w
               + jnp.arange(self.kernel_w)[None, :])          # (OT, kw)
        patches = x[:, idx, :]                                # (N, OT, kw, C)
        p = patches.reshape(x.shape[0], self.n_output_frame, -1)
        out = jnp.einsum("npk,pok->npo", p, params["weight"])
        if self.with_bias:
            out = out + params["bias"][None]
        if squeeze:
            out = out[0]
        return out, state
