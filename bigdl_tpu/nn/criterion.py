"""Loss criterions.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/ClassNLLCriterion.scala`` etc. —
unverified): ~30 Torch-style criterions with ``forward(input, target)`` /
``backward(input, target)``, ``sizeAverage`` semantics.

TPU-native: each criterion is a pure function ``apply(input, target) -> scalar``; the
trainer differentiates through it together with the model (one fused XLA program).
``backward`` on the facade uses ``jax.grad`` for API parity.

Label convention: targets are **0-based** class indices by default (numpy/torch-native);
pass ``one_based=True`` for the reference's Torch 1-based labels.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.utils.table import Table


from bigdl_tpu.nn.abstractnn import RecordsInit


class AbstractCriterion(metaclass=RecordsInit):
    def __init__(self) -> None:
        self.output = None
        self.grad_input = None
        self._cache: dict = {}

    # functional core ------------------------------------------------------
    def apply(self, input, target):
        """Pure loss. Returns a scalar."""
        raise NotImplementedError

    # facade ---------------------------------------------------------------
    def forward(self, input, target):
        if "fwd" not in self._cache:
            self._cache["fwd"] = jax.jit(self.apply)
        self.output = self._cache["fwd"](input, target)
        return self.output

    def backward(self, input, target):
        if "bwd" not in self._cache:
            self._cache["bwd"] = jax.jit(jax.grad(lambda i, t: self.apply(i, t)))
        self.grad_input = self._cache["bwd"](input, target)
        return self.grad_input

    def __call__(self, input, target):
        return self.forward(input, target)

    def __repr__(self):
        return type(self).__name__

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_cache"] = {}
        return d


def _reduce(loss, size_average: bool):
    return jnp.mean(loss) if size_average else jnp.sum(loss)


def _class_index(target, one_based: bool):
    t = target.astype(jnp.int32)
    return t - 1 if one_based else t


class ClassNLLCriterion(AbstractCriterion):
    """Negative log-likelihood over log-probabilities (pairs with LogSoftMax)."""

    def __init__(self, weights=None, size_average: bool = True,
                 logprob_as_input: bool = True, one_based: bool = False):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average
        self.logprob_as_input = logprob_as_input
        self.one_based = one_based

    def apply(self, input, target):
        logp = input if self.logprob_as_input else jnp.log(jnp.clip(input, 1e-8))
        if logp.ndim == 1:
            logp = logp[None]
            target = jnp.reshape(target, (1,))
        idx = _class_index(jnp.reshape(target, (-1,)), self.one_based)
        picked = jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = jnp.take(self.weights, idx)
            loss = -(picked * w)
            return jnp.sum(loss) / jnp.sum(w) if self.size_average else jnp.sum(loss)
        return _reduce(-picked, self.size_average)


class CrossEntropyCriterion(AbstractCriterion):
    """LogSoftMax + ClassNLL fused (input = raw logits)."""

    def __init__(self, weights=None, size_average: bool = True, one_based: bool = False):
        super().__init__()
        self.inner = ClassNLLCriterion(weights, size_average, one_based=one_based)

    @property
    def size_average(self) -> bool:
        # averaging lives on the wrapped ClassNLL; expose it so wrappers
        # (TimeDistributedCriterion) classify this criterion correctly
        return self.inner.size_average

    def apply(self, input, target):
        return self.inner.apply(jax.nn.log_softmax(input, axis=-1), target)


class MSECriterion(AbstractCriterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        return _reduce(jnp.square(input - target), self.size_average)


class AbsCriterion(AbstractCriterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        return _reduce(jnp.abs(input - target), self.size_average)


class BCECriterion(AbstractCriterion):
    """Binary cross-entropy over probabilities (pairs with Sigmoid)."""

    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, input, target):
        eps = 1e-12
        p = jnp.clip(input, eps, 1.0 - eps)
        loss = -(target * jnp.log(p) + (1.0 - target) * jnp.log1p(-p))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss, self.size_average)


class BCECriterionWithLogits(AbstractCriterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        loss = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        return _reduce(loss, self.size_average)


class SmoothL1Criterion(AbstractCriterion):
    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        d = jnp.abs(input - target)
        loss = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(loss, self.size_average)


class MarginCriterion(AbstractCriterion):
    """Hinge loss; target ∈ {-1, 1}."""

    def __init__(self, margin: float = 1.0, size_average: bool = True,
                 squared: bool = False):
        super().__init__()
        self.margin, self.size_average, self.squared = margin, size_average, squared

    def apply(self, input, target):
        loss = jnp.maximum(0.0, self.margin - input * target)
        if self.squared:
            loss = jnp.square(loss)
        return _reduce(loss, self.size_average)


class HingeEmbeddingCriterion(AbstractCriterion):
    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__()
        self.margin, self.size_average = margin, size_average

    def apply(self, input, target):
        loss = jnp.where(target > 0, input, jnp.maximum(0.0, self.margin - input))
        return _reduce(loss, self.size_average)


class DistKLDivCriterion(AbstractCriterion):
    """KL(target ‖ input) where input is log-prob, target is prob."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        loss = jnp.where(target > 0, target * (jnp.log(jnp.clip(target, 1e-12)) - input), 0.0)
        return _reduce(loss, self.size_average)


class MultiLabelSoftMarginCriterion(AbstractCriterion):
    def __init__(self, weights=None, size_average: bool = True):
        super().__init__()
        self.weights = None if weights is None else jnp.asarray(weights)
        self.size_average = size_average

    def apply(self, input, target):
        loss = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss, self.size_average)


class CosineEmbeddingCriterion(AbstractCriterion):
    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin, self.size_average = margin, size_average

    def apply(self, input, target):
        x1, x2 = (input[1], input[2]) if isinstance(input, Table) else (input[0], input[1])
        cos = jnp.sum(x1 * x2, -1) / jnp.clip(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        t = jnp.reshape(target, cos.shape)
        loss = jnp.where(t > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(loss, self.size_average)


class MarginRankingCriterion(AbstractCriterion):
    """Ranking hinge over a pair of score tensors: ``max(0, -y*(x1-x2)+margin)``
    (reference ``<dl>/nn/MarginRankingCriterion.scala`` — unverified). Input is a
    Table/tuple (x1, x2); target ∈ {-1, 1}."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__()
        self.margin, self.size_average = margin, size_average

    def apply(self, input, target):
        x1, x2 = (input[1], input[2]) if isinstance(input, Table) else (input[0], input[1])
        t = jnp.reshape(target, x1.shape)
        loss = jnp.maximum(0.0, -t * (x1 - x2) + self.margin)
        return _reduce(loss, self.size_average)


class MultiMarginCriterion(AbstractCriterion):
    """Multi-class hinge (reference ``MultiMarginCriterion`` — unverified):
    ``mean_j(max(0, margin - x[y] + x[j])^p)`` over j != y. 0-based targets by
    default (framework convention); ``one_based=True`` for Torch parity."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True, one_based: bool = False):
        super().__init__()
        if p not in (1, 2):
            raise ValueError("p must be 1 or 2")
        self.p, self.margin, self.size_average = p, margin, size_average
        self.weights = None if weights is None else jnp.asarray(weights)
        self.one_based = one_based

    def apply(self, input, target):
        x = input if input.ndim == 2 else input[None]
        t = jnp.reshape(target, (-1,)).astype(jnp.int32)
        if self.one_based:
            t = t - 1
        n, c = x.shape
        correct = jnp.take_along_axis(x, t[:, None], axis=1)
        loss = jnp.maximum(0.0, self.margin - correct + x)
        if self.p == 2:
            loss = jnp.square(loss)
        if self.weights is not None:
            loss = loss * self.weights[t][:, None]
        # zero out the j == y term
        mask = jnp.arange(c)[None, :] != t[:, None]
        per_sample = jnp.sum(loss * mask, axis=1) / c
        return jnp.mean(per_sample) if self.size_average else jnp.sum(per_sample)


class MultiLabelMarginCriterion(AbstractCriterion):
    """Multi-label multi-class hinge (reference ``MultiLabelMarginCriterion`` —
    unverified; torch ``multilabel_margin_loss`` semantics). ``target`` rows
    list label indices, padded with the sentinel 0 (1-based labels) or -1
    (``one_based=False``); labels after the first sentinel are ignored.

    Memory note: the vectorized hinge materializes an (n, L, c) tensor where L
    is the target width (= c under torch-shape targets), i.e. O(n*c^2) — fine
    for the typical multi-label class counts this loss targets (<= a few
    thousand classes), not for extreme-classification c."""

    def __init__(self, size_average: bool = True, one_based: bool = False):
        super().__init__()
        self.size_average = size_average
        self.one_based = one_based

    def apply(self, input, target):
        x = input if input.ndim == 2 else input[None]
        t = target if target.ndim == 2 else target[None]
        t = t.astype(jnp.int32)
        n, c = x.shape
        sentinel = 0 if self.one_based else -1
        # valid prefix: labels before the first sentinel
        is_pad = (t == sentinel)
        valid = jnp.cumsum(is_pad, axis=1) == 0
        idx = jnp.clip(t - (1 if self.one_based else 0), 0, c - 1)
        # is_target[b, j] = j appears in the valid label prefix of row b
        onehot = jax.nn.one_hot(idx, c, dtype=x.dtype) * valid[..., None]
        is_target = jnp.clip(jnp.sum(onehot, axis=1), 0.0, 1.0)
        x_target = jnp.take_along_axis(x, idx, axis=1)  # (n, L)
        # hinge of every valid target score against every non-target class
        margins = jnp.maximum(
            0.0, 1.0 - x_target[:, :, None] + x[:, None, :])  # (n, L, c)
        mask = valid[:, :, None] * (1.0 - is_target)[:, None, :]
        per_sample = jnp.sum(margins * mask, axis=(1, 2)) / c
        return jnp.mean(per_sample) if self.size_average else jnp.sum(per_sample)


class SoftMarginCriterion(AbstractCriterion):
    """``mean(log(1 + exp(-y * x)))``, target ∈ {-1, 1} (reference
    ``SoftMarginCriterion`` — unverified)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        # logaddexp is the overflow-safe log(1 + exp(z)) (cf. BCECriterionWithLogits)
        return _reduce(jnp.logaddexp(0.0, -input * target), self.size_average)


class CosineDistanceCriterion(AbstractCriterion):
    """``1 - cos(x, y)`` between prediction and target tensors (reference
    ``CosineDistanceCriterion`` — unverified)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        from bigdl_tpu.nn.cosine import cosine_similarity
        return _reduce(1.0 - cosine_similarity(input, target), self.size_average)


class L1HingeEmbeddingCriterion(AbstractCriterion):
    """L1 distance hinge over a pair: ``d = |x1 - x2|_1``; loss ``d`` if y=1 else
    ``max(0, margin - d)`` (reference ``L1HingeEmbeddingCriterion`` — unverified)."""

    size_average = True   # batch-mean reduced (gradient-accumulation contract)

    def __init__(self, margin: float = 1.0):
        super().__init__()
        self.margin = margin

    def apply(self, input, target):
        x1, x2 = (input[1], input[2]) if isinstance(input, Table) else (input[0], input[1])
        d = jnp.sum(jnp.abs(x1 - x2), axis=-1)
        t = jnp.reshape(target, d.shape)
        loss = jnp.where(t > 0, d, jnp.maximum(0.0, self.margin - d))
        return jnp.mean(loss)


class PoissonCriterion(AbstractCriterion):
    """Poisson NLL over positive rates: ``mean(pred - target * log(pred))``
    (keras-style; reference keras loss set — unverified)."""

    size_average = True

    def apply(self, input, target):
        return jnp.mean(input - target * jnp.log(jnp.clip(input, 1e-12)))


class CosineProximityCriterion(AbstractCriterion):
    """Negative mean cosine proximity of l2-normalised tensors (keras
    ``cosine_proximity``; reference keras loss set — unverified)."""

    size_average = True

    def apply(self, input, target):
        from bigdl_tpu.nn.cosine import cosine_similarity
        return -jnp.mean(cosine_similarity(input, target))


class MeanAbsolutePercentageCriterion(AbstractCriterion):
    """MAPE: ``100 * mean(|t - x| / clip(|t|))`` (keras-style)."""

    size_average = True

    def apply(self, input, target):
        return 100.0 * jnp.mean(
            jnp.abs(target - input) / jnp.clip(jnp.abs(target), 1e-7))


class MeanSquaredLogarithmicCriterion(AbstractCriterion):
    """MSLE: ``mean((log(1+t) - log(1+x))^2)`` (keras-style)."""

    size_average = True

    def apply(self, input, target):
        return jnp.mean(jnp.square(
            jnp.log1p(jnp.clip(target, 0.0)) - jnp.log1p(jnp.clip(input, 0.0))))


class KullbackLeiblerDivergenceCriterion(AbstractCriterion):
    """KL(target ‖ input) over probability distributions (keras ``kld``; the
    log-prob-input variant is :class:`DistKLDivCriterion`)."""

    size_average = True

    def apply(self, input, target):
        t = jnp.clip(target, 1e-7, 1.0)
        p = jnp.clip(input, 1e-7, 1.0)
        return jnp.mean(jnp.sum(t * jnp.log(t / p), axis=-1))


class ClassSimplexCriterion(AbstractCriterion):
    """MSE against regular-simplex target embeddings (reference
    ``ClassSimplexCriterion`` — unverified): class ``y`` maps to the ``y``-th
    vertex of a regular (nClasses-1)-simplex in R^nClasses."""

    def __init__(self, n_classes: int, size_average: bool = True,
                 one_based: bool = False):
        super().__init__()
        if n_classes < 2:
            raise ValueError("n_classes must be >= 2")
        self.n_classes = n_classes
        self.size_average = size_average
        self.one_based = one_based
        self._simplex = jnp.asarray(self._build_simplex(n_classes))

    @staticmethod
    def _build_simplex(k: int):
        import numpy as _np
        # Gram-Schmidt construction of k unit vectors with equal pairwise distance
        a = _np.zeros((k, k), _np.float32)
        for i in range(k):
            for j in range(i):
                a[i, j] = -(1.0 / k + _np.dot(a[i], a[j])) / a[j, j] if a[j, j] != 0 else 0.0
            rest = 1.0 - _np.sum(a[i] ** 2)
            a[i, i] = _np.sqrt(max(rest, 0.0))
        return a

    def apply(self, input, target):
        t = jnp.reshape(target, (-1,)).astype(jnp.int32)
        if self.one_based:
            t = t - 1
        goal = self._simplex[t]
        return _reduce(jnp.square(input - goal), self.size_average)


class ParallelCriterion(AbstractCriterion):
    """Weighted sum of criterions over (Table input, Table target) pairs."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.criterions: list[tuple[AbstractCriterion, float]] = []
        self.repeat_target = repeat_target

    def add(self, criterion: AbstractCriterion, weight: float = 1.0) -> "ParallelCriterion":
        self.criterions.append((criterion, weight))
        return self

    @property
    def size_average(self) -> bool:
        # a weighted sum of means is itself mean-like under gradient
        # accumulation; only an all-sum composite accumulates by summing
        return all(bool(getattr(c, "size_average", True))
                   for c, _ in self.criterions)

    def apply(self, input, target):
        xs = input.values() if isinstance(input, Table) else list(input)
        if self.repeat_target:
            ts = [target] * len(xs)
        else:
            ts = target.values() if isinstance(target, Table) else list(target)
        total = 0.0
        for (crit, w), x, t in zip(self.criterions, xs, ts):
            total = total + w * crit.apply(x, t)
        return total


class TimeDistributedCriterion(AbstractCriterion):
    """Apply an inner criterion at every timestep of (N, T, ...) input."""

    def __init__(self, criterion: AbstractCriterion, size_average: bool = False,
                 dimension: int = 2):
        super().__init__()
        self.criterion = criterion
        # the reference arg name means "divide by T" — NOT batch reduction;
        # stored under its real meaning so the gradient-accumulation contract
        # (the size_average property below) can answer the batch question
        self.time_average = size_average

    @property
    def size_average(self) -> bool:
        # batch-reduction semantics for gradient accumulation: the T division
        # is a constant factor, so whether micro-losses average or sum over
        # the batch is decided by the inner criterion's reduction
        return bool(getattr(self.criterion, "size_average", True))

    def apply(self, input, target):
        # Reference semantics: loss = Σ_t inner(input[:, t], target[:, t]),
        # divided by T when time-averaging. Flattening time into batch
        # computes the same thing in ONE inner call, but the rescale depends
        # on whether the inner criterion itself averages: an averaging inner
        # on the flat (N*T, ...) batch already IS the time-averaged result
        # (the old code divided by T a second time, shrinking LM losses T-fold).
        t_steps = input.shape[1]
        flat_in = input.reshape((-1,) + input.shape[2:])
        flat_t = target.reshape((-1,) + target.shape[2:])
        loss = self.criterion.apply(flat_in, flat_t)
        if bool(getattr(self.criterion, "size_average", False)):
            return loss if self.time_average else loss * t_steps
        return loss / t_steps if self.time_average else loss


class MultiCriterion(AbstractCriterion):
    """Weighted sum of criterions applied to the SAME (input, target)."""

    def __init__(self):
        super().__init__()
        self.criterions: list[tuple[AbstractCriterion, float]] = []

    def add(self, criterion: AbstractCriterion, weight: float = 1.0) -> "MultiCriterion":
        self.criterions.append((criterion, weight))
        return self

    @property
    def size_average(self) -> bool:
        return all(bool(getattr(c, "size_average", True))
                   for c, _ in self.criterions)

    def apply(self, input, target):
        total = 0.0
        for crit, w in self.criterions:
            total = total + w * crit.apply(input, target)
        return total


class L1Cost(AbstractCriterion):
    size_average = False   # sum-reduced: micro-losses add up to the batch loss

    def apply(self, input, target):
        return jnp.sum(jnp.abs(input))


class KLDCriterion(AbstractCriterion):
    """Gaussian KL divergence to the unit prior given a Table (mean, log_var)
    (reference ``KLDCriterion`` — the VAE regulariser; target is ignored):
    ``0.5 * sum(mu^2 + exp(log_var) - 1 - log_var)``."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        xs = input.values() if isinstance(input, Table) else list(input)
        mu, log_var = xs[0], xs[1]
        kl = 0.5 * jnp.sum(jnp.square(mu) + jnp.exp(log_var) - 1.0 - log_var,
                           axis=-1)
        return jnp.mean(kl) if self.size_average else jnp.sum(kl)


class GaussianCriterion(AbstractCriterion):
    """Negative log-likelihood of ``target`` under N(mean, exp(log_var)) given a
    Table (mean, log_var) (reference ``GaussianCriterion``)."""

    def __init__(self, size_average: bool = False):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        xs = input.values() if isinstance(input, Table) else list(input)
        mu, log_var = xs[0], xs[1]
        nll = 0.5 * (jnp.log(2.0 * jnp.pi) + log_var
                     + jnp.square(target - mu) / jnp.exp(log_var))
        return _reduce(nll, self.size_average)


class DiceCoefficientCriterion(AbstractCriterion):
    """1 - Sørensen–Dice overlap (reference ``DiceCoefficientCriterion`` —
    segmentation loss): per-sample ``1 - 2·Σxy / (Σx + Σy + ε)``, averaged."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        super().__init__()
        self.size_average = size_average
        self.epsilon = epsilon

    def apply(self, input, target):
        x = input.reshape(input.shape[0], -1)
        y = target.reshape(target.shape[0], -1).astype(x.dtype)
        inter = jnp.sum(x * y, axis=1)
        denom = jnp.sum(x, axis=1) + jnp.sum(y, axis=1) + self.epsilon
        loss = 1.0 - 2.0 * inter / denom
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class SoftmaxWithCriterion(AbstractCriterion):
    """Fused softmax + multinomial logistic loss over logits, Caffe
    ``SoftmaxWithLoss`` semantics (reference ``SoftmaxWithCriterion``):
    optional ``ignore_label`` and normalize modes ``valid`` (default: divide by
    non-ignored count), ``full`` (all), ``batch_size``, ``none``."""

    def __init__(self, ignore_label: Optional[int] = None,
                 normalize_mode: str = "valid", one_based: bool = False):
        super().__init__()
        if normalize_mode not in ("valid", "full", "batch_size", "none"):
            raise ValueError(f"unknown normalize_mode {normalize_mode!r}")
        self.ignore_label = ignore_label
        self.normalize_mode = normalize_mode
        self.one_based = one_based
        # valid/full/batch_size all divide by a per-batch count (mean-like
        # under gradient accumulation); only "none" is a raw sum
        self.size_average = normalize_mode != "none"

    def apply(self, input, target):
        logp = jax.nn.log_softmax(input.astype(jnp.float32), axis=1) \
            if input.ndim > 1 else jax.nn.log_softmax(input)
        # channel dim = axis 1 (NC or NCHW); move classes last, flatten the rest
        logp = jnp.moveaxis(logp, 1, -1).reshape(-1, input.shape[1])
        idx = _class_index(jnp.reshape(target, (-1,)), self.one_based)
        if self.ignore_label is not None:
            ignore = _class_index(jnp.asarray(self.ignore_label), self.one_based)
            mask = (idx != ignore).astype(logp.dtype)
            # ignore labels may be out of class range (Caffe's 255): clamp the
            # gather index to 0 for masked rows so no NaN leaks through 0*NaN
            idx = jnp.where(idx != ignore, idx, 0)
            picked = -jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
            picked = picked * mask
            valid = jnp.sum(mask)
        else:
            picked = -jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
            valid = jnp.asarray(picked.shape[0], picked.dtype)
        total = jnp.sum(picked)
        if self.normalize_mode == "valid":
            return total / jnp.maximum(valid, 1.0)
        if self.normalize_mode == "full":
            return total / picked.shape[0]
        if self.normalize_mode == "batch_size":
            return total / input.shape[0]
        return total


class CategoricalCrossEntropy(AbstractCriterion):
    """Keras-style categorical cross-entropy: probabilities vs one-hot targets
    (reference ``CategoricalCrossEntropy``)."""

    def __init__(self, size_average: bool = True):
        super().__init__()
        self.size_average = size_average

    def apply(self, input, target):
        p = jnp.clip(input, 1e-8, 1.0)
        loss = -jnp.sum(target * jnp.log(p), axis=-1)
        return jnp.mean(loss) if self.size_average else jnp.sum(loss)


class TimeDistributedMaskCriterion(AbstractCriterion):
    """TimeDistributedCriterion that skips padded timesteps (reference
    ``TimeDistributedMaskCriterion(criterion, paddingValue)``): timesteps whose
    target equals ``padding_value`` contribute nothing, and the mean runs over
    the non-padded count only. The inner criterion must be class-index based
    (ClassNLL / CrossEntropy — the padded-label use case)."""

    size_average = True   # normalized by the non-padded count (mean-like)

    def __init__(self, criterion: AbstractCriterion, padding_value: int = 0):
        super().__init__()
        if isinstance(criterion, CrossEntropyCriterion):
            self._logits = True
        elif isinstance(criterion, ClassNLLCriterion):
            self._logits = not criterion.logprob_as_input
        else:
            raise TypeError(
                "TimeDistributedMaskCriterion supports class-index criterions "
                f"(ClassNLL/CrossEntropy), got {type(criterion).__name__}")
        inner = criterion.inner if isinstance(criterion, CrossEntropyCriterion) \
            else criterion
        self.one_based = inner.one_based
        self.padding_value = padding_value

    def apply(self, input, target):
        logp = input.reshape(-1, input.shape[-1])
        if self._logits:
            logp = jax.nn.log_softmax(logp.astype(jnp.float32), axis=-1)
        raw = jnp.reshape(target, (-1,))
        mask = (raw != self.padding_value).astype(logp.dtype)
        idx = _class_index(raw, self.one_based)
        idx = jnp.where(mask > 0, idx, 0)  # padded rows pick class 0, masked out
        picked = -jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]
        return jnp.sum(picked * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class SmoothL1CriterionWithWeights(AbstractCriterion):
    """Fast-RCNN bbox regression loss (reference
    ``SmoothL1CriterionWithWeights(sigma, num)``): target is a Table
    (t, inside_w, outside_w); ``sum(outside_w * smoothL1(inside_w*(x-t)))/num``
    with the sigma-scaled Huber transition at ``1/sigma^2``."""

    # sum-reduced for accumulation purposes even when num > 0: the divisor is
    # a CONSTANT, so micro-losses add up to the full-batch loss
    size_average = False

    def __init__(self, sigma: float = 1.0, num: int = 0):
        super().__init__()
        self.sigma2 = sigma * sigma
        self.num = num

    def apply(self, input, target):
        if isinstance(target, Table):
            t, iw, ow = target.values()
        elif isinstance(target, (tuple, list)) and len(target) == 3:
            t, iw, ow = target
        else:
            t, iw, ow = target, None, None
        d = input - t
        if iw is not None:
            d = d * iw
        ad = jnp.abs(d)
        loss = jnp.where(ad < 1.0 / self.sigma2,
                         0.5 * self.sigma2 * jnp.square(d),
                         ad - 0.5 / self.sigma2)
        if ow is not None:
            loss = loss * ow
        total = jnp.sum(loss)
        return total / self.num if self.num > 0 else total


class TransformerCriterion(AbstractCriterion):
    """Apply (frozen) transform modules to input and/or target before an inner
    criterion (reference ``TransformerCriterion`` — perceptual-loss pattern).
    The transforms' parameters are captured as constants: they do not train
    through the loss, matching the upstream frozen-feature-extractor usage."""

    def __init__(self, criterion: AbstractCriterion,
                 input_transformer=None, target_transformer=None):
        super().__init__()
        self.criterion = criterion
        self.input_transformer = input_transformer
        self.target_transformer = target_transformer

    @property
    def size_average(self) -> bool:
        return bool(getattr(self.criterion, "size_average", True))

    def _run(self, module, x):
        if module is None:
            return x
        out, _ = module.apply(module.get_params(), module.get_state(), x,
                              training=False, rng=None)
        return out

    def apply(self, input, target):
        return self.criterion.apply(self._run(self.input_transformer, input),
                                    self._run(self.target_transformer, target))
