"""Image data layout policy: NCHW (reference parity default) vs NHWC.

The reference fixes NCHW activations end-to-end (SURVEY.md §2.1 layer
conventions). On TPU, XLA's layout assignment makes channel the minor (lane)
dimension internally regardless of the logical order, but a logical-NCHW feed
still pays entry/exit transposes and splits activations across two internal
layouts inside one program (measured on v5e: ~3.5 ms/step of pure layout churn
in the ResNet-50 train step). ``set_image_format("NHWC")`` switches the spatial
layers (SpatialConvolution / SpatialBatchNormalization / pooling / the zoo's
spatial glue) to channels-last so the logical layout matches the physical one.

Semantics: the format is read at TRACE time. Set it before building/jitting a
model; a live jitted step keeps the format it was traced with. Parameter
layouts (OIHW conv weights) are format-independent — checkpoints and the
portable serializer are unaffected by the activation layout.

Layers honoring the flag: SpatialConvolution (+Share/Map subclasses),
SpatialBatchNormalization, SpatialMaxPooling, SpatialAveragePooling,
SpatialDropout2D, SpatialCrossMapLRN, PReLU, UpSampling2D, ImageNormalize,
Concat, and the ResNet zoo glue (shortcut-A / global-avg-pool / s2d stem).
The long tail of exotic spatial layers (dilated/full conv, within-channel
LRN, subtractive/divisive norm, volumetric 3-D ops, ROI ops, keras wrappers)
remains NCHW-only — build those models with the default format.

**Spatial-glue rule:** under NHWC mode, glue layers that address "the channel
axis" by the reference's positional convention (``Concat(dimension=2)`` on a
4-D activation, per-channel broadcasts) re-resolve that position to the
channels-last axis, because the semantic intent — branch merge / broadcast
over channels — is layout-invariant. This applies to ALL 4-D activations
while NHWC mode is on; concatenating 4-D non-image tables along a literal
second axis in an NHWC model needs ``Concat(dim, literal_dim=True)``.
"""

from __future__ import annotations

import os

_FORMAT: str | None = None

_VALID = ("NCHW", "NHWC")


def image_format() -> str:
    """Current image format: explicit ``set_image_format`` wins, else
    ``BIGDL_IMAGE_FORMAT`` (default NCHW)."""
    if _FORMAT is not None:
        return _FORMAT
    fmt = os.environ.get("BIGDL_IMAGE_FORMAT", "NCHW").upper()
    return fmt if fmt in _VALID else "NCHW"


def set_image_format(fmt: str | None) -> None:
    """Set the process-wide image format (``None`` → back to env/default)."""
    global _FORMAT
    if fmt is not None:
        fmt = fmt.upper()
        if fmt not in _VALID:
            raise ValueError(f"image format must be one of {_VALID}, got {fmt!r}")
    _FORMAT = fmt


def is_nhwc() -> bool:
    return image_format() == "NHWC"


def channel_axis(ndim: int = 4) -> int:
    """Axis holding channels for a spatial tensor of ``ndim`` dims (4 = NCHW/NHWC,
    3 = unbatched CHW/HWC)."""
    return ndim - 3 if not is_nhwc() else ndim - 1


def spatial_axes(ndim: int = 4) -> tuple[int, int]:
    """(H, W) axes for a spatial tensor of ``ndim`` dims."""
    if is_nhwc():
        return ndim - 3, ndim - 2
    return ndim - 2, ndim - 1


def conv_dimension_numbers() -> tuple[str, str, str]:
    """lax.conv dimension numbers for the current format. Weights stay OIHW in
    both formats (parameter-layout parity: serialization and imports never see
    the activation layout)."""
    if is_nhwc():
        return ("NHWC", "OIHW", "NHWC")
    return ("NCHW", "OIHW", "NCHW")


def spatial_window(kh: int, kw: int, one: int = 1) -> tuple[int, int, int, int]:
    """4-tuple (per-axis window/stride) with (kh, kw) on the spatial axes."""
    if is_nhwc():
        return (one, kh, kw, one)
    return (one, one, kh, kw)


def spatial_padding(ph, pw) -> tuple:
    """4-tuple of (lo, hi) pads with (ph, pw) on the spatial axes."""
    zero = (0, 0)
    if is_nhwc():
        return (zero, ph, pw, zero)
    return (zero, zero, ph, pw)


def bias_shape(n: int, ndim: int = 4) -> tuple[int, ...]:
    """Broadcast shape for a per-channel (n,) vector against a spatial tensor."""
    shape = [1] * ndim
    shape[channel_axis(ndim)] = n
    return tuple(shape)
