"""Static graph container — Torch-style ``inputs()`` node wiring over a functional core.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/Graph.scala``, ``StaticGraph.scala``,
``<dl>/utils/Node.scala`` — unverified, mount empty): the reference builds a DAG of modules
by calling ``layer.inputs(node1, node2, ...)`` which returns a ``Node`` wrapping the layer;
``Graph(input=..., output=...)`` topologically sorts the DAG and executes it in order on
``forward``, replaying reversed for ``backward`` with gradOutput routing.

TPU-native design: the topological order is computed once at construction; ``apply`` is a
pure function that walks the sorted nodes, feeding each module the (Table-packed, if n>1)
outputs of its predecessor nodes. The whole graph is ONE traced program under ``jit`` —
backward is ``jax.vjp`` of the composite, so no reverse-graph construction is needed and
XLA fuses across node boundaries (what the reference's mkldnn ``Fusion`` pass hand-did).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

from bigdl_tpu.nn.abstractnn import AbstractModule, Container, split_rng
from bigdl_tpu.utils.table import Table, T


class ModuleNode:
    """A node in the module DAG: wraps a module plus its predecessor nodes."""

    _counter = 0

    def __init__(self, module: Optional[AbstractModule],
                 prev_nodes: Sequence["ModuleNode"] = ()):
        ModuleNode._counter += 1
        self.id = ModuleNode._counter
        self.module = module
        self.prev_nodes: list[ModuleNode] = list(prev_nodes)

    def __repr__(self):
        return f"Node({self.module!r})"


def Input() -> ModuleNode:
    """Create a graph input placeholder node (reference ``Input()``)."""
    return ModuleNode(None, ())


def make_node(module: AbstractModule, nodes: Sequence) -> ModuleNode:
    """``layer.inputs(nodeA, nodeB)`` → new node wiring nodeA/nodeB into this layer."""
    flat: list[ModuleNode] = []
    for n in nodes:
        if isinstance(n, (list, tuple)):
            flat.extend(n)
        else:
            flat.append(n)
    return ModuleNode(module, flat)


class Graph(Container):
    """DAG of modules executed in topological order as one pure function.

    ``Graph(input_nodes, output_nodes)`` — either may be a single node or a list. Multiple
    graph inputs consume a ``Table`` input activity (element i → input node i); multiple
    outputs produce a ``Table``.
    """

    def __init__(self,
                 input: Union[ModuleNode, Sequence[ModuleNode]],
                 output: Union[ModuleNode, Sequence[ModuleNode]]):
        super().__init__()
        self.input_nodes = list(input) if isinstance(input, (list, tuple)) else [input]
        self.output_nodes = list(output) if isinstance(output, (list, tuple)) else [output]
        self.sorted_nodes = self._topo_sort()
        # children (for params/state nesting) = executable nodes in topo order
        self.exec_nodes = [n for n in self.sorted_nodes if n.module is not None]
        self.modules = [n.module for n in self.exec_nodes]
        self._node_child_name = {n.id: str(i) for i, n in enumerate(self.exec_nodes)}

    # ------------------------------------------------------------------ build
    def _topo_sort(self) -> list[ModuleNode]:
        """Kahn's algorithm from output nodes back through prev edges."""
        # collect reachable nodes
        seen: dict[int, ModuleNode] = {}
        stack = list(self.output_nodes)
        while stack:
            n = stack.pop()
            if n.id in seen:
                continue
            seen[n.id] = n
            stack.extend(n.prev_nodes)
        for inp in self.input_nodes:
            if inp.id not in seen:
                raise ValueError("Graph input node is not connected to any output")
        # in-degree over reachable subgraph
        indeg = {nid: 0 for nid in seen}
        succs: dict[int, list[ModuleNode]] = {nid: [] for nid in seen}
        for n in seen.values():
            for p in n.prev_nodes:
                indeg[n.id] += 1
                succs[p.id].append(n)
        ready = sorted([n for n in seen.values() if indeg[n.id] == 0], key=lambda n: n.id)
        order: list[ModuleNode] = []
        while ready:
            n = ready.pop(0)
            order.append(n)
            for s in succs[n.id]:
                indeg[s.id] -= 1
                if indeg[s.id] == 0:
                    ready.append(s)
        if len(order) != len(seen):
            raise ValueError("Graph contains a cycle")
        return order

    # ------------------------------------------------------------------ run
    def apply(self, params, state, input, *, training=False, rng=None):
        # map graph inputs
        values: dict[int, object] = {}
        if len(self.input_nodes) == 1:
            values[self.input_nodes[0].id] = input
        else:
            xs = input.values() if isinstance(input, Table) else list(input)
            if len(xs) != len(self.input_nodes):
                raise ValueError(
                    f"graph expects {len(self.input_nodes)} inputs, got {len(xs)}")
            for node, x in zip(self.input_nodes, xs):
                values[node.id] = x

        new_state = {}
        rngs = split_rng(rng, len(self.exec_nodes))
        ri = 0
        for node in self.sorted_nodes:
            if node.module is None:
                if node.id not in values:
                    raise ValueError("unbound Input() node in graph")
                continue
            if node.prev_nodes:
                preds = [values[p.id] for p in node.prev_nodes]
                x = preds[0] if len(preds) == 1 else T(*preds)
            elif node.id in values:
                # module node used directly as a graph input (reference allows
                # `layer.inputs()` with no predecessors as an input node)
                x = values[node.id]
            else:
                raise ValueError(f"{node} has no predecessors and is not a graph input")
            cname = self._node_child_name[node.id]
            out, s = node.module.apply(params[cname], state[cname], x,
                                       training=training, rng=rngs[ri])
            ri += 1
            values[node.id] = out
            new_state[cname] = s

        outs = [values[n.id] for n in self.output_nodes]
        out = outs[0] if len(outs) == 1 else T(*outs)
        return out, new_state

    def node(self, name: str) -> Optional[ModuleNode]:
        for n in self.exec_nodes:
            if n.module is not None and n.module.name == name:
                return n
        return None

    def __repr__(self):
        return (f"Graph(inputs={len(self.input_nodes)}, outputs={len(self.output_nodes)}, "
                f"nodes={len(self.exec_nodes)})")


# Reference alias: StaticGraph is the concrete eager-plan graph class.
StaticGraph = Graph


# --------------------------------------------------------------------- fusion
def _fusible_conv(m) -> bool:
    from bigdl_tpu.nn.convolution import SpatialConvolution
    return isinstance(m, SpatialConvolution)


def _fusible_bn(conv, m) -> bool:
    from bigdl_tpu.nn.normalization import SpatialBatchNormalization
    return (isinstance(m, SpatialBatchNormalization)
            and m.n_output == conv.n_output_plane and not m.sync)


def _is_relu(m) -> bool:
    from bigdl_tpu.nn.activation import ReLU
    return type(m) is ReLU  # ReLU6 etc. have different math


def _fuse_sequential(seq) -> int:
    """Collapse adjacent conv → bn (→ relu) children of a Sequential into
    :class:`~bigdl_tpu.kernels.conv_bn.FusedConvBNReLU` nodes, in place.
    Returns the number of pairs fused."""
    from bigdl_tpu.kernels.conv_bn import FusedConvBNReLU
    out, fused, i = [], 0, 0
    mods = seq.modules
    while i < len(mods):
        m = mods[i]
        if (_fusible_conv(m) and i + 1 < len(mods)
                and _fusible_bn(m, mods[i + 1])):
            relu = i + 2 < len(mods) and _is_relu(mods[i + 2])
            out.append(FusedConvBNReLU(m, mods[i + 1], relu=relu))
            fused += 1
            i += 3 if relu else 2
        else:
            out.append(m)
            i += 1
    if fused:
        seq.modules = out
        seq.__dict__.pop("_cached_fwd_jit", None)
    return fused


def _fuse_graph(g: Graph) -> tuple[Graph, int]:
    """Merge conv → bn (→ relu) chains of a module DAG into single fused
    nodes (the bn/relu must be the conv's only consumer). Rewires the node
    graph in place and rebuilds the Graph container around it."""
    from bigdl_tpu.kernels.conv_bn import FusedConvBNReLU

    succs: dict[int, list[ModuleNode]] = {}
    for n in g.sorted_nodes:
        for p in n.prev_nodes:
            succs.setdefault(p.id, []).append(n)

    def sole_successor(node):
        s = succs.get(node.id, [])
        return s[0] if len(s) == 1 else None

    fused = 0
    outputs = list(g.output_nodes)
    for node in g.sorted_nodes:
        conv = node.module
        if conv is None or not _fusible_conv(conv):
            continue
        bn_node = sole_successor(node)
        if bn_node is None or bn_node.module is None \
                or not _fusible_bn(conv, bn_node.module) \
                or len(bn_node.prev_nodes) != 1:
            continue
        relu_node = sole_successor(bn_node)
        if relu_node is not None and (relu_node.module is None
                                      or not _is_relu(relu_node.module)
                                      or len(relu_node.prev_nodes) != 1):
            relu_node = None
        tail = relu_node if relu_node is not None else bn_node
        node.module = FusedConvBNReLU(conv, bn_node.module,
                                      relu=relu_node is not None)
        fused += 1
        # consumers of the absorbed tail now read the fused node
        for consumer in succs.get(tail.id, []):
            consumer.prev_nodes = [node if p is tail else p
                                   for p in consumer.prev_nodes]
        succs[node.id] = succs.pop(tail.id, [])
        outputs = [node if o is tail else o for o in outputs]
    if not fused:
        return g, 0
    return Graph(g.input_nodes, outputs), fused


def fuse_conv_bn(model):
    """Graph-level conv-bn(-relu) fusion pass: walk the module tree (and any
    :class:`Graph` DAGs) replacing adjacent ``SpatialConvolution →
    SpatialBatchNormalization (→ ReLU)`` chains with one
    :class:`~bigdl_tpu.kernels.conv_bn.FusedConvBNReLU` module. Parameter
    and state arrays carry over untouched (the fused module owns the SAME
    child modules), so the fused model is bitwise-identical in fp32 on the
    training path and runs folded single-conv inference.

    Rewrites containers in place and returns the (possibly new, for a root
    Graph) fused model. Applied automatically by the Optimizer when
    ``BIGDL_CONVBN_FUSE=1``; off by default.
    """
    from bigdl_tpu.kernels.conv_bn import FusedConvBNReLU
    from bigdl_tpu.nn.containers import Sequential

    total = 0

    def walk(m):
        nonlocal total
        if isinstance(m, FusedConvBNReLU):
            return m  # already fused — don't descend into its children
        if isinstance(m, Graph):
            for node in m.exec_nodes:
                node.module = walk(node.module)
            m.modules = [n.module for n in m.exec_nodes]
            new_g, n = _fuse_graph(m)
            total += n
            return new_g
        if isinstance(m, Container):
            m.modules = [walk(c) for c in m.modules]
            if isinstance(m, Sequential):
                total += _fuse_sequential(m)
        return m

    model = walk(model)
    if total:
        import logging
        logging.getLogger("bigdl_tpu.nn").info(
            "conv-bn fusion pass: %d conv-bn(-relu) chains fused", total)
    return model
