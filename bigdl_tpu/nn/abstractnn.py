"""Module system core — Torch-style API over a pure functional JAX core.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/abstractnn/AbstractModule.scala`` —
unverified, mount empty): the reference ``AbstractModule[A, B, T]`` is a *mutable* module:
``forward`` caches ``output``, ``backward`` = ``updateGradInput`` + ``accGradParameters``
accumulating into per-module gradient buffers; ``parameters()`` exposes (weights, gradWeights);
``training()/evaluate()`` flip mode; ``getTimes()`` exposes per-module timing.

TPU-native design (SURVEY.md §7.1/§7.4): that mutable protocol cannot be the compute path on
TPU — XLA wants one traced, pure program per training step. So every module is split in two:

- **functional core** — ``apply(params, state, input, training=..., rng=...)`` is pure:
  ``params`` is a pytree of trainable arrays, ``state`` a pytree of non-trainable buffers
  (e.g. BatchNorm running stats); it returns ``(output, new_state)``. Composition (containers)
  nests these pytrees by child index. The trainer (``LocalOptimizer``/``DistriOptimizer``)
  compiles forward+loss+grad+update into ONE ``jit`` from this core; ``jax.value_and_grad``
  replaces hand-written ``updateGradInput``/``accGradParameters`` everywhere.
- **stateful facade** — the Torch-style methods users expect. ``forward`` runs the jitted core
  with the module's currently-held params and caches ``output``; ``backward(input, grad_out)``
  uses ``jax.vjp`` (recomputing forward — rematerialisation is the TPU-idiomatic trade) and
  *accumulates* parameter gradients into module-held buffers for API parity.

Params live on the module (created eagerly at construction, Torch semantics, via the global
``RandomGenerator``); the trainer checks them out as a pytree, trains functionally, and writes
them back.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.utils.table import Table

Activity = Any  # jnp.ndarray | Table | tuple/list — anything pytree-shaped


def _is_array(x) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray))


class RecordsInit(type):
    """Metaclass recording the constructor arguments of every instance as
    ``_init_args = (args, kwargs)``. The portable serializer (utils/serializer.py)
    rebuilds modules from these — a reflection-driven analog of the reference's
    per-layer protobuf converters (SURVEY.md §2.5 Protobuf serializer)."""

    def __call__(cls, *args, **kwargs):
        obj = super().__call__(*args, **kwargs)
        if "_init_args" not in obj.__dict__:
            obj.__dict__["_init_args"] = (args, kwargs)
        return obj


class AbstractModule(metaclass=RecordsInit):
    """Base class of all layers and containers."""

    _instance_counter = 0

    def __init__(self) -> None:
        AbstractModule._instance_counter += 1
        self.name: str = f"{type(self).__name__}{AbstractModule._instance_counter}"
        self.output: Activity = None
        self.grad_input: Activity = None
        self._training: bool = True
        self._params: dict[str, jnp.ndarray] = {}      # trainable leaves (leaf modules)
        self._grads: dict[str, jnp.ndarray] = {}       # accumulated gradients, same keys
        self._state: dict[str, jnp.ndarray] = {}       # non-trainable buffers
        self._forward_time: float = 0.0
        self._backward_time: float = 0.0
        self._apply_cache: dict = {}
        # scalar multipliers mirroring the reference's setScaleW/setScaleB
        self.scale_w: float = 1.0
        self.scale_b: float = 1.0

    # ------------------------------------------------------------ functional
    def apply(self, params: dict, state: dict, input: Activity, *,
              training: bool = False, rng: Optional[jax.Array] = None):
        """Pure forward. Override in subclasses. Returns ``(output, new_state)``."""
        raise NotImplementedError

    def needs_rng(self) -> bool:
        """True if apply consumes randomness in training mode (e.g. Dropout)."""
        return False

    def has_state(self) -> bool:
        return bool(self.get_state())

    # params / state checkout-checkin -------------------------------------
    def get_params(self) -> dict:
        return dict(self._params)

    def set_params(self, params: dict) -> None:
        self._params = dict(params)

    # per-layer LR multipliers (reference setScaleW/setScaleB): applied to
    # this module's weight/bias GRADIENTS inside the jitted step
    def set_scale_w(self, scale: float) -> "AbstractModule":
        self.scale_w = float(scale)
        return self

    def set_scale_b(self, scale: float) -> "AbstractModule":
        self.scale_b = float(scale)
        return self

    def grad_scales(self) -> dict:
        """Pytree matching get_params() of per-leaf gradient multipliers:
        bias-like leaves get scale_b, everything else scale_w; frozen modules
        contribute zeros."""
        if getattr(self, "_frozen", False):
            return {k: 0.0 for k in self._params}
        return {k: (self.scale_b if "bias" in k else self.scale_w)
                for k in self._params}

    def freeze(self) -> "AbstractModule":
        """Exclude this module's parameters from training updates (reference
        ``freeze`` — fine-tuning: freeze the pretrained trunk, train the
        head). Zeroes the gradients inside the jitted step; scale_w/scale_b
        are restored on ``unfreeze``."""
        self._frozen = True
        return self

    def unfreeze(self) -> "AbstractModule":
        self._frozen = False
        return self

    def is_frozen(self) -> bool:
        return getattr(self, "_frozen", False)

    def has_regularizers(self) -> bool:
        return (getattr(self, "w_regularizer", None) is not None
                or getattr(self, "b_regularizer", None) is not None)

    def regularizer_penalty(self, params: dict):
        """Scalar penalty over this module's params (optim/regularizer.py);
        called inside the jitted loss when any regularizer is attached."""
        import jax.numpy as jnp
        total = jnp.zeros((), jnp.float32)
        w_reg = getattr(self, "w_regularizer", None)
        b_reg = getattr(self, "b_regularizer", None)
        for k, v in params.items():
            reg = b_reg if "bias" in k else w_reg
            if reg is not None:
                total = total + reg.penalty(v)
        return total

    def get_state(self) -> dict:
        return dict(self._state)

    def set_state(self, state: dict) -> None:
        self._state = dict(state)

    def get_grads(self) -> dict:
        return {k: self._grads.get(k, jnp.zeros_like(v)) for k, v in self._params.items()}

    def set_grads(self, grads: dict) -> None:
        self._grads = dict(grads)

    # ------------------------------------------------------------- facade
    def __call__(self, input: Activity) -> Activity:
        return self.forward(input)

    def forward(self, input: Activity) -> Activity:
        t0 = time.perf_counter()
        params, state = self.get_params(), self.get_state()
        rng = None
        if self._training and self.needs_rng():
            from bigdl_tpu.utils.random_generator import RandomGenerator
            rng = RandomGenerator.next_key()
        out, new_state = self._jitted_apply()(params, state, input, self._training, rng)
        if self._training:
            self.set_state(new_state)
        self.output = out
        self._forward_time += time.perf_counter() - t0
        return self.output

    def _jitted_apply(self) -> Callable:
        key = ("apply",)
        if key not in self._apply_cache:
            def run(params, state, input, training, rng):
                return self.apply(params, state, input, training=training, rng=rng)
            self._apply_cache[key] = jax.jit(run, static_argnums=(3,))
        return self._apply_cache[key]

    def backward(self, input: Activity, grad_output: Activity) -> Activity:
        """updateGradInput + accGradParameters in one call (reference semantics)."""
        t0 = time.perf_counter()
        grad_input, grad_params = self._vjp(input, grad_output)
        self._accumulate_grads(grad_params)
        self.grad_input = grad_input
        self._backward_time += time.perf_counter() - t0
        return self.grad_input

    def update_grad_input(self, input: Activity, grad_output: Activity) -> Activity:
        grad_input, _ = self._vjp(input, grad_output)
        self.grad_input = grad_input
        return grad_input

    def acc_grad_parameters(self, input: Activity, grad_output: Activity) -> None:
        _, grad_params = self._vjp(input, grad_output)
        self._accumulate_grads(grad_params)

    def _vjp(self, input, grad_output):
        key = ("vjp",)
        if key not in self._apply_cache:
            def run(params, state, input, grad_output, training, rng):
                def f(p, x):
                    out, _ = self.apply(p, state, x, training=training, rng=rng)
                    return out
                _, vjp_fn = jax.vjp(f, params, input)
                gp, gi = vjp_fn(grad_output)
                return gi, gp
            self._apply_cache[key] = jax.jit(run, static_argnums=(4,))
        rng = None
        if self._training and self.needs_rng():
            from bigdl_tpu.utils.random_generator import RandomGenerator
            rng = RandomGenerator.next_key()
        return self._apply_cache[key](
            self.get_params(), self.get_state(), input, grad_output, self._training, rng)

    def _accumulate_grads(self, grad_params: dict) -> None:
        self._recursive_acc(self, grad_params)

    @staticmethod
    def _recursive_acc(module: "AbstractModule", grad_params: dict) -> None:
        if isinstance(module, Container):
            for name, child in module.named_children():
                if name in grad_params:
                    AbstractModule._recursive_acc(child, grad_params[name])
        else:
            for k, g in grad_params.items():
                if k in module._grads:
                    module._grads[k] = module._grads[k] + g
                else:
                    module._grads[k] = g

    # --------------------------------------------------------------- mode
    def training(self) -> "AbstractModule":
        self._training = True
        return self

    def evaluate(self, dataset=None, methods=None, batch_size=None):
        """No arguments: switch to eval mode (Torch parity). With a dataset and
        ValidationMethods: run distributed evaluation and return
        ``[(ValidationResult, method)]`` (reference ``model.evaluate(rdd, methods,
        batchSize)`` overload)."""
        self._training = False
        if dataset is None:
            return self
        from bigdl_tpu.optim.evaluator import Evaluator
        return Evaluator(self).test(dataset, methods, batch_size)

    def predict(self, data, batch_size=None):
        """Forward the model over samples/arrays/a DataSet; returns stacked outputs
        (reference ``model.predict``)."""
        from bigdl_tpu.optim.evaluator import Predictor
        self._training = False
        return Predictor(self).predict(data, batch_size)

    def predict_image(self, image_frame, batch_size=None):
        """Run the vision-transformed ``ImageFrame`` through the model and
        return stacked outputs (reference ``model.predictImage(imageFrame)``)."""
        from bigdl_tpu.optim.evaluator import Predictor
        self._training = False
        samples = image_frame.to_samples()
        if batch_size is None:
            batch_size = min(len(samples), 32) or 1
        return Predictor(self).predict(samples, batch_size)

    def predict_class(self, data, batch_size=None):
        """Argmax class index per sample (reference ``model.predictClass``; 0-based
        here — this framework uses 0-based labels throughout, unlike the 1-based
        Torch convention)."""
        from bigdl_tpu.optim.evaluator import Predictor
        self._training = False
        return Predictor(self).predict_class(data, batch_size)

    def is_training(self) -> bool:
        return self._training

    # ---------------------------------------------------------- parameters
    def parameters(self):
        """Return (weights, gradWeights) as two flat lists (reference ``parameters()``)."""
        ws, gs = [], []
        ptree, gtree = self.get_params(), self.get_grads_tree()
        wleaves = jax.tree_util.tree_leaves(ptree)
        gleaves = jax.tree_util.tree_leaves(gtree)
        ws.extend(wleaves)
        gs.extend(gleaves)
        return ws, gs

    def get_grads_tree(self) -> dict:
        return self.get_grads()

    def zero_grad_parameters(self) -> None:
        self._grads = {k: jnp.zeros_like(v) for k, v in self._params.items()}

    def n_parameters(self) -> int:
        return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(self.get_params()))

    # ------------------------------------------------------------- timing
    def get_times(self):
        """[(module, forward_s, backward_s)] — reference ``getTimes`` parity.

        Note: under async dispatch these are submission times; wrap with
        ``jax.block_until_ready`` externally for wall-clock accuracy (SURVEY.md §5.1).
        """
        return [(self, self._forward_time, self._backward_time)]

    def reset_times(self) -> None:
        self._forward_time = 0.0
        self._backward_time = 0.0

    # -------------------------------------------------------------- quantize
    def quantize(self, mode: str = "dynamic") -> "AbstractModule":
        """Return an int8-quantized copy for inference (reference
        ``module.quantize()`` — SURVEY.md §2.1 Quantized layers): Linear /
        SpatialConvolution become int8-weight modules. ``mode="dynamic"``
        (reference semantics) runs int8×int8→int32 contractions on the MXU;
        ``mode="weight_only"`` keeps activations in the compute dtype and
        dequantizes weights at use — most of bf16 speed (measured 0.77× on
        v5e) with half the weight HBM; see nn/quantized.py for the measured
        trade."""
        from bigdl_tpu.nn.quantized import quantize_module
        return quantize_module(self, mode)

    # -------------------------------------------------------------- graph
    def inputs(self, *nodes):
        """Torch-style node wiring: ``layer.inputs(nodeA, nodeB)`` returns a graph
        ``ModuleNode`` wrapping this layer with the given predecessor nodes (reference
        ``AbstractModule.inputs`` / ``Node`` wiring — SURVEY.md §2.1 Static graph)."""
        from bigdl_tpu.nn.graph import make_node
        return make_node(self, nodes)

    # -------------------------------------------------------------- misc
    def set_name(self, name: str) -> "AbstractModule":
        self.name = name
        return self

    def get_name(self) -> str:
        return self.name

    def reset(self) -> None:
        """Re-randomise parameters (reference ``reset()``). Overridden by leaf layers."""

    def clear_state(self) -> "AbstractModule":
        self.output = None
        self.grad_input = None
        return self

    def clone(self) -> "AbstractModule":
        import copy
        cache, self._apply_cache = self._apply_cache, {}
        try:
            return copy.deepcopy(self)
        finally:
            self._apply_cache = cache

    def __repr__(self) -> str:
        return f"{type(self).__name__}"

    # serialization --------------------------------------------------------
    # Two formats, mirroring the reference's split (SURVEY.md §2.5): ``save`` =
    # in-version pickle (fast, Python-bound, like Java serialization);
    # ``save_module`` = portable versioned archive (refactor- and
    # version-tolerant, like the protobuf ``saveModule``). ``load`` sniffs.
    def save(self, path: str, overwrite: bool = True) -> "AbstractModule":
        """Persist this module via pickle — reference ``Module.save``."""
        from bigdl_tpu.utils import file as _file
        _file.save(self, path, overwrite=overwrite)
        return self

    def save_module(self, path: str, overwrite: bool = True) -> "AbstractModule":
        """Persist in the portable versioned format — reference ``saveModule``."""
        from bigdl_tpu.utils import serializer
        serializer.save_module(self, path, overwrite=overwrite)
        return self

    @staticmethod
    def load(path: str) -> "AbstractModule":
        from bigdl_tpu.utils import file as _file
        from bigdl_tpu.utils import serializer
        if serializer.is_portable_file(path):
            obj = serializer.load_module(path)
        else:
            obj = _file.load(path)
        if not isinstance(obj, AbstractModule):
            raise TypeError(f"{path} does not contain a module (got {type(obj)})")
        return obj

    load_module = load  # reference ``Module.loadModule`` alias

    def save_torch(self, path: str) -> "AbstractModule":
        """Export as a Lua-Torch7 ``.t7`` nn model — reference ``saveTorch``."""
        from bigdl_tpu.utils import torchfile
        torchfile.save_torch(self, path)
        return self

    @staticmethod
    def load_torch(path: str) -> "AbstractModule":
        """Import a Lua-Torch7 ``.t7`` nn model — reference ``loadTorch``."""
        from bigdl_tpu.utils import torchfile
        return torchfile.load_torch(path)

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_cached_fwd_jit", None)  # jitted closures don't pickle
        d["_apply_cache"] = {}
        d["_params"] = {k: np.asarray(v) for k, v in self._params.items()}
        d["_grads"] = {k: np.asarray(v) for k, v in self._grads.items()}
        d["_state"] = {k: np.asarray(v) for k, v in self._state.items()}
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)


class TensorModule(AbstractModule):
    """Module whose input and output are single tensors."""


class Container(AbstractModule):
    """Base for composite modules; nests child params/state pytrees by child index."""

    def __init__(self, *modules: AbstractModule) -> None:
        super().__init__()
        self.modules: list[AbstractModule] = list(modules)

    def add(self, module: AbstractModule) -> "Container":
        self.modules.append(module)
        self.__dict__.pop("_cached_fwd_jit", None)  # structure changed
        return self

    def __len__(self) -> int:
        return len(self.modules)

    def __getitem__(self, i: int) -> AbstractModule:
        return self.modules[i]

    def named_children(self):
        return [(str(i), m) for i, m in enumerate(self.modules)]

    # nested pytree checkout/checkin --------------------------------------
    def get_params(self) -> dict:
        return {name: m.get_params() for name, m in self.named_children()}

    # container setScaleW/setScaleB propagate the SET to the whole subtree
    # (reference Container semantics)
    def set_scale_w(self, scale: float) -> "AbstractModule":
        self.scale_w = float(scale)
        for m in self.modules:
            m.set_scale_w(scale)
        return self

    def set_scale_b(self, scale: float) -> "AbstractModule":
        self.scale_b = float(scale)
        for m in self.modules:
            m.set_scale_b(scale)
        return self

    def grad_scales(self) -> dict:
        # no container-level short-circuit: freeze() already propagated to
        # children, and `model.freeze(); head.unfreeze()` must honor the
        # child's unfreeze (a parent-level zeros branch would ignore it)
        return {name: m.grad_scales() for name, m in self.named_children()}

    def freeze(self) -> "AbstractModule":
        self._frozen = True
        for m in self.modules:
            m.freeze()
        return self

    def unfreeze(self) -> "AbstractModule":
        self._frozen = False
        for m in self.modules:
            m.unfreeze()
        return self

    def has_regularizers(self) -> bool:
        return any(m.has_regularizers() for m in self.modules)

    def regularizer_penalty(self, params: dict):
        import jax.numpy as jnp
        total = jnp.zeros((), jnp.float32)
        for name, m in self.named_children():
            if m.has_regularizers():
                total = total + m.regularizer_penalty(params.get(name, {}))
        return total

    def set_params(self, params: dict) -> None:
        for name, m in self.named_children():
            if name in params:
                m.set_params(params[name])

    def get_state(self) -> dict:
        return {name: m.get_state() for name, m in self.named_children()}

    def set_state(self, state: dict) -> None:
        for name, m in self.named_children():
            if name in state:
                m.set_state(state[name])

    def get_grads(self) -> dict:
        return {name: m.get_grads() for name, m in self.named_children()}

    def get_grads_tree(self) -> dict:
        return self.get_grads()

    def zero_grad_parameters(self) -> None:
        for m in self.modules:
            m.zero_grad_parameters()

    def needs_rng(self) -> bool:
        return any(m.needs_rng() for m in self.modules)

    def training(self) -> "Container":
        super().training()
        for m in self.modules:
            m.training()
        return self

    def evaluate(self, dataset=None, methods=None, batch_size=None):
        for m in self.modules:
            m.evaluate()
        return super().evaluate(dataset, methods, batch_size)

    def reset(self) -> None:
        for m in self.modules:
            m.reset()

    def get_times(self):
        out = [(self, self._forward_time, self._backward_time)]
        for m in self.modules:
            out.extend(m.get_times())
        return out

    def reset_times(self) -> None:
        super().reset_times()
        for m in self.modules:
            m.reset_times()

    def find_module(self, name: str) -> Optional[AbstractModule]:
        if self.name == name:
            return self
        for m in self.modules:
            if m.name == name:
                return m
            if isinstance(m, Container):
                found = m.find_module(name)
                if found is not None:
                    return found
        return None


def split_rng(rng: Optional[jax.Array], n: int):
    """Split an optional rng into n optional keys."""
    if rng is None:
        return [None] * n
    return list(jax.random.split(rng, n))
