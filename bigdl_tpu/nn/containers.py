"""Composition containers.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/Sequential.scala``, ``Concat.scala``,
``ConcatTable.scala``, ``ParallelTable.scala``, ``CAddTable.scala``, ``JoinTable.scala`` —
unverified). TPU-native: containers compose the children's pure ``apply`` functions; the
whole composite stays one traced program under ``jit`` (XLA fuses across layer boundaries —
the reference needed explicit mkldnn fusion passes for that, SURVEY.md §2.1 "Fusion").
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import AbstractModule, Container, split_rng
from bigdl_tpu.utils.table import Table, T


class Sequential(Container):
    """Chain children; output of child i feeds child i+1."""

    def apply(self, params, state, input, *, training=False, rng=None):
        import jax

        x = input
        new_state = {}
        rngs = split_rng(rng, len(self.modules))
        for (name, m), r in zip(self.named_children(), rngs):
            # named_scope = the profiler-attribution analog of the reference's
            # per-module getTimes counters (SURVEY §5.1): trace rows group by
            # layer name in the TensorBoard trace viewer
            with jax.named_scope(m.name):
                x, s = m.apply(params[name], state[name], x,
                               training=training, rng=r)
            new_state[name] = s
        return x, new_state

    def __repr__(self):
        inner = "\n".join(f"  ({i}): {m!r}" for i, m in enumerate(self.modules))
        return f"Sequential(\n{inner}\n)"


class Concat(Container):
    """Apply each child to the same input; concatenate outputs along ``dimension``.

    The workhorse of Inception's branch blocks. ``dimension`` is 1-based counting the batch
    dim first (reference convention): default 2 = channel axis of NCHW. Under
    ``nn.layout`` NHWC mode, dimension 2 on a 4-D activation means "the channel
    axis" semantically, so it resolves to the last axis — this is what lets the
    Inception zoo run channels-last unmodified (spatial-glue rule — see the
    nn/layout.py module docstring). Concatenating 4-D NON-image tables along a
    literal second axis under NHWC mode is outside that rule: pass
    ``literal_dim=True`` to suppress the channel-axis resolution.
    """

    def __init__(self, dimension: int = 2, literal_dim: bool = False):
        super().__init__()
        self.dimension = dimension
        self.literal_dim = literal_dim

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], {}
        rngs = split_rng(rng, len(self.modules))
        for (name, m), r in zip(self.named_children(), rngs):
            o, s = m.apply(params[name], state[name], input, training=training, rng=r)
            outs.append(o)
            new_state[name] = s
        axis = self.dimension - 1
        if axis == 1 and outs and outs[0].ndim == 4 and not self.literal_dim:
            from bigdl_tpu.nn import layout
            axis = layout.channel_axis(4)
        return jnp.concatenate(outs, axis=axis), new_state

    def __repr__(self):
        inner = " | ".join(repr(m) for m in self.modules)
        return f"Concat(dim={self.dimension})[{inner}]"


class ConcatTable(Container):
    """Apply each child to the same input; output a Table of the results."""

    def apply(self, params, state, input, *, training=False, rng=None):
        outs, new_state = [], {}
        rngs = split_rng(rng, len(self.modules))
        for (name, m), r in zip(self.named_children(), rngs):
            o, s = m.apply(params[name], state[name], input, training=training, rng=r)
            outs.append(o)
            new_state[name] = s
        return T(*outs), new_state


class ParallelTable(Container):
    """Child i consumes input Table element i; outputs a Table."""

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        outs, new_state = [], {}
        rngs = split_rng(rng, len(self.modules))
        for (name, m), x, r in zip(self.named_children(), xs, rngs):
            o, s = m.apply(params[name], state[name], x, training=training, rng=r)
            outs.append(o)
            new_state[name] = s
        return T(*outs), new_state


class CAddTable(AbstractModule):
    """Element-wise sum of a Table of tensors (ResNet shortcut join)."""

    def __init__(self, inplace: bool = False):
        super().__init__()

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out, state


class CMulTable(AbstractModule):
    """Element-wise product of a Table of tensors."""

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        out = xs[0]
        for x in xs[1:]:
            out = out * x
        return out, state


class CSubTable(AbstractModule):
    """Element-wise difference x1 - x2 of a Table pair."""

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        return xs[0] - xs[1], state


class CDivTable(AbstractModule):
    """Element-wise quotient x1 / x2 of a Table pair."""

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        return xs[0] / xs[1], state


class CMaxTable(AbstractModule):
    """Element-wise maximum over a Table of tensors."""

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        out = xs[0]
        for x in xs[1:]:
            out = jnp.maximum(out, x)
        return out, state


class CMinTable(AbstractModule):
    """Element-wise minimum over a Table of tensors."""

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        out = xs[0]
        for x in xs[1:]:
            out = jnp.minimum(out, x)
        return out, state


class JoinTable(AbstractModule):
    """Concatenate a Table of tensors along ``dimension`` (1-based; n_input_dims lets
    batched input shift the axis, reference semantics)."""

    def __init__(self, dimension: int, n_input_dims: int = -1):
        super().__init__()
        self.dimension = dimension
        self.n_input_dims = n_input_dims

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        axis = self.dimension - 1
        if self.n_input_dims > 0 and xs[0].ndim == self.n_input_dims + 1:
            axis += 1  # leading batch dim present
        return jnp.concatenate(xs, axis=axis), state


class SelectTable(AbstractModule):
    """Pick element ``index`` (1-based) from the input Table."""

    def __init__(self, index: int):
        super().__init__()
        self.index = index

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        i = self.index - 1 if self.index > 0 else self.index
        return xs[i], state


class FlattenTable(AbstractModule):
    """Flatten nested Tables into one flat Table."""

    def apply(self, params, state, input, *, training=False, rng=None):
        flat = []

        def rec(x):
            if isinstance(x, Table):
                for v in x.values():
                    rec(v)
            elif isinstance(x, (list, tuple)):
                for v in x:
                    rec(v)
            else:
                flat.append(x)

        rec(input)
        return T(*flat), state


class Identity(AbstractModule):
    def apply(self, params, state, input, *, training=False, rng=None):
        return input, state


class Echo(AbstractModule):
    """Debug layer: prints shape at trace time, passes input through."""

    def apply(self, params, state, input, *, training=False, rng=None):
        shape = jax.tree_util.tree_map(lambda x: x.shape, input)
        print(f"[Echo {self.name}] {shape}")
        return input, state


class Bottle(Container):
    """Run the wrapped module on a view with leading dims collapsed: input
    (d1, ..., dk, rest...) is reshaped so the child sees ``n_input_dims`` dims,
    and the child's output gets the leading dims restored (reference
    ``<dl>/nn/Bottle.scala`` — unverified). One reshape in, one out — both free
    under XLA (layout-only)."""

    def __init__(self, module: AbstractModule, n_input_dims: int = 2):
        super().__init__(module)
        self.n_input_dims = n_input_dims

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        n_lead = x.ndim - (self.n_input_dims - 1)
        lead = x.shape[:n_lead]
        if n_lead > 1:
            x = x.reshape((-1,) + x.shape[n_lead:])
        out, new_s = self.modules[0].apply(params["0"], state["0"], x,
                                           training=training, rng=rng)
        if n_lead > 1:
            out = out.reshape(lead + out.shape[1:])
        return out, {"0": new_s}


class MapTable(Container):
    """Apply ONE shared child to every element of the input Table (shared params)."""

    def __init__(self, module: Optional[AbstractModule] = None):
        super().__init__(*( [module] if module is not None else [] ))

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        m = self.modules[0]
        outs = []
        s = state["0"]
        rngs = split_rng(rng, len(xs))
        for x, r in zip(xs, rngs):
            o, s = m.apply(params["0"], s, x, training=training, rng=r)
            outs.append(o)
        return T(*outs), {"0": s}


class NarrowTable(AbstractModule):
    """Select ``length`` consecutive entries of the input Table starting at
    ``offset`` (1-based; reference ``NarrowTable``). length=1 returns the bare
    element, matching the reference's unwrap behavior for singleton narrows."""

    def __init__(self, offset: int, length: int = 1):
        super().__init__()
        self.offset, self.length = offset, length

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        start = self.offset - 1
        length = self.length
        if length < 0:  # same convention as Narrow: count back from the end
            length = len(xs) - start + length + 1
        picked = xs[start:start + length]
        if len(picked) == 1:
            return picked[0], state
        return T(*picked), state


class Pack(AbstractModule):
    """Stack the entries of a Table along a NEW dim (1-based; reference
    ``Pack``)."""

    def __init__(self, dim: int = 1):
        super().__init__()
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        return jnp.stack(xs, axis=self.dim - 1), state


class CAveTable(AbstractModule):
    """Elementwise average of the Table entries (reference ``CAveTable``)."""

    def __init__(self, inplace: bool = False):
        super().__init__()

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out / float(len(xs)), state


class BifurcateSplitTable(AbstractModule):
    """Split a tensor into a Table of two halves along dim (1-based; reference
    ``BifurcateSplitTable`` — the dim's size must be even)."""

    def __init__(self, dimension: int):
        super().__init__()
        self.dimension = dimension

    def apply(self, params, state, input, *, training=False, rng=None):
        axis = self.dimension - 1 if self.dimension > 0 else input.ndim + self.dimension
        n = input.shape[axis]
        if n % 2 != 0:
            raise ValueError(
                f"BifurcateSplitTable: dim {self.dimension} has odd size {n}")
        a, b = jnp.split(input, 2, axis=axis)
        return T(a, b), state


class MixtureTable(AbstractModule):
    """Mixture-of-experts blend: input Table = (gater (N,E), experts); output =
    sum_e gater[:, e] * expert_e (reference ``MixtureTable``). Experts may be a
    Table of E tensors (stacked on a new expert axis) or a single pre-stacked
    tensor whose expert axis is ``dim`` (1-based counting batch first,
    default 2). The stack-and-contract is one einsum on the MXU."""

    def __init__(self, dim: int = 2):
        super().__init__()
        self.dim = dim

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        gater, experts = xs[0], xs[1]
        if isinstance(experts, Table):
            stacked = jnp.stack(experts.values(), axis=self.dim - 1)
        elif isinstance(experts, (list, tuple)):
            stacked = jnp.stack(list(experts), axis=self.dim - 1)
        else:
            stacked = experts                      # already (N, ..E.., ...)
        axis = self.dim - 1
        shape = [1] * stacked.ndim
        shape[0], shape[axis] = gater.shape[0], gater.shape[1]
        g = gater.reshape(shape)
        return jnp.sum(g * stacked, axis=axis), state


class MaskedSelect(AbstractModule):
    """Select input[0] values where the input[1] mask is nonzero.

    TPU-native redesign of the reference ``MaskedSelect``: the reference returns
    a dynamically-sized 1-D tensor, which XLA cannot express inside a traced
    program (no dynamic shapes on TPU). Eagerly (outside jit) this returns the
    exact torch-style dynamic result; inside a trace it raises with guidance to
    use a static-shape masking pattern (``jnp.where`` / sort-by-mask) instead.
    """

    def forward(self, input):
        # eager host path — bypasses the jitted-apply facade on purpose
        xs = input.values() if isinstance(input, Table) else list(input)
        import numpy as np
        xv = np.asarray(xs[0])
        mv = np.asarray(xs[1]).astype(bool)
        self.output = jnp.asarray(xv[mv])
        return self.output

    def apply(self, params, state, input, *, training=False, rng=None):
        raise TypeError(
            "MaskedSelect produces a data-dependent shape and cannot run "
            "inside jit on TPU; call .forward() eagerly (host) or restructure "
            "with jnp.where for a static-shape pipeline")


class Remat(Container):
    """Rematerialisation container: wraps one child in ``jax.checkpoint`` so its
    activations are recomputed during the backward pass instead of living in
    HBM across it (SURVEY.md build directives: trade FLOPs for memory). No
    reference counterpart — the reference kept every activation alive; on TPU
    this is the standard way to fit long-context / deep models."""

    def __init__(self, module: AbstractModule = None):
        super().__init__(*([module] if module is not None else []))

    def add(self, module: AbstractModule) -> "Remat":
        if self.modules:
            raise ValueError("Remat wraps exactly one module")
        return super().add(module)

    def apply(self, params, state, input, *, training=False, rng=None):
        if not self.modules:
            raise RuntimeError("Remat has no child module — add() one first")
        m = self.modules[0]

        def f(p, x):
            return m.apply(p, state["0"], x, training=training, rng=rng)

        out, new_s = jax.checkpoint(f)(params["0"], input)
        return out, {"0": new_s}

    def __repr__(self):
        return f"Remat({self.modules[0]!r})" if self.modules else "Remat()"
