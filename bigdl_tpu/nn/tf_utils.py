"""Graph-utility modules from the reference's ``<dl>/nn/tf/`` package
(SURVEY §2.1 layer zoo tail — expected ``Const.scala``, ``Fill.scala``,
``Shape.scala``, ``StrideSlice.scala``, ``SplitAndSelect.scala``,
unverified, mount empty): small plumbing layers the reference ships for
wiring TF-style graphs out of native modules. All are shape/metadata ops —
free under XLA once fused."""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.utils.table import Table


class Const(TensorModule):
    """Emit a stored constant, ignoring the input activity (the input exists
    only to give the node a place in the graph — reference ``Const``)."""

    def __init__(self, value):
        super().__init__()
        self.value = np.asarray(value)

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.asarray(self.value), state

    def __repr__(self):
        return f"Const(shape={tuple(self.value.shape)})"


class Fill(TensorModule):
    """Fill a static shape with a (possibly traced) scalar: input is
    ``Table(shape, value)`` where ``shape`` must be concrete at trace time
    (XLA needs static shapes — a Const/host array; reference ``Fill``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = list(input.values()) if isinstance(input, Table) else list(input)
        if len(xs) != 2:
            raise ValueError("Fill expects Table(shape, value)")
        shape, value = xs
        try:
            shape = tuple(int(s) for s in np.asarray(shape))
        except Exception:
            raise ValueError(
                "Fill needs a STATIC shape (traced shape tensors cannot size "
                "an XLA buffer) — feed it from a Const") from None
        return jnp.full(shape, jnp.asarray(value)), state

    def __repr__(self):
        return "Fill()"


class Shape(TensorModule):
    """The input's shape as an int32 vector (static under jit, so this
    compiles to a constant — reference ``Shape``)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return jnp.asarray(input.shape, jnp.int32), state

    def __repr__(self):
        return "Shape()"


class StrideSlice(TensorModule):
    """Strided slicing by per-dim ``(dim, start, stop, step)`` specs
    (reference ``StrideSlice(specs)``). Dims are 0-BASED over the full
    input (dim 0 = batch — slice it only on purpose); unspecified dims
    pass through whole."""

    def __init__(self, specs: Sequence[Sequence[int]]):
        super().__init__()
        self.specs = [tuple(int(v) for v in s) for s in specs]
        for s in self.specs:
            if len(s) != 4:
                raise ValueError(
                    f"each spec is (dim, start, stop, step), got {s}")
            if s[3] == 0:
                raise ValueError("slice step must be nonzero")

    def apply(self, params, state, input, *, training=False, rng=None):
        idx = [slice(None)] * input.ndim
        for dim, start, stop, step in self.specs:
            if not 0 <= dim < input.ndim:
                raise ValueError(
                    f"StrideSlice dim {dim} out of range for rank {input.ndim}")
            idx[dim] = slice(start, stop, step)
        return input[tuple(idx)], state

    def __repr__(self):
        return f"StrideSlice({self.specs})"


class SplitAndSelect(TensorModule):
    """Split the input into ``num_split`` equal chunks along ``dim`` and
    output chunk ``index`` (reference ``SplitAndSelect(dim, index,
    numSplit)``, 0-based here)."""

    def __init__(self, dim: int, index: int, num_split: int):
        super().__init__()
        self.dim, self.index, self.num_split = int(dim), int(index), int(num_split)
        if not 0 <= self.index < self.num_split:
            raise ValueError(
                f"index {index} out of range for {num_split} splits")

    def apply(self, params, state, input, *, training=False, rng=None):
        if input.shape[self.dim] % self.num_split:
            raise ValueError(
                f"dim {self.dim} size {input.shape[self.dim]} not divisible "
                f"by {self.num_split}")
        return jnp.split(input, self.num_split, axis=self.dim)[self.index], \
            state

    def __repr__(self):
        return (f"SplitAndSelect(dim={self.dim}, index={self.index}, "
                f"splits={self.num_split})")


from bigdl_tpu.utils.serializer import register as _register  # noqa: E402

for _cls in (Const, Fill, Shape, StrideSlice, SplitAndSelect):
    _register(_cls)
