"""Embedding layers.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/LookupTable.scala`` — unverified):
``LookupTable(nIndex, nOutput)`` maps 1-based integer indices to rows of a learnable
(nIndex, nOutput) weight; options paddingValue / maxNorm / normType.

TPU-native: the lookup is one gather (``weight[idx]``); its VJP is a scatter-add that XLA
emits natively — no sparse-gradient special-casing like Torch's. max-norm renorm is applied
functionally in the forward pass (matching Torch semantics of renorm-before-lookup).

Out-of-range behaviour differs from the reference: the reference raises on bad indices, but
a jitted gather *clamps* out-of-bounds indices and wraps negative ones, so an off-by-one in
user data silently reads a wrong row. ``BIGDL_CHECK_IDS=1`` turns on an explicit guard:
eager forwards assert host-side (raising ``IndexError`` with the offending range), and
inside jit the check is emitted through ``jax.experimental.checkify`` whenever a
functionalizing scope is active (``checkify_ids_scope`` — the Optimizer's
``set_check_numerics`` step enters it, so ``BIGDL_CHECK_IDS=1 BIGDL_CHECK_NUMERICS=1``
composes into one checked train step). Traced without such a scope the guard is skipped —
a bare ``checkify.check`` under plain ``jit`` is a trace error, not a runtime one.

Padding: ``padding_value=None`` (default) disables masking. A numeric value masks the
embedding of that id to zeros — including id 0 in ``zero_based=True`` mode (the historical
``!= 0.0`` guard made row 0 unmaskable). 1-based semantics are unchanged bitwise: ids are
1-based there, so ``padding_value=0`` still means "no padding row" and any non-zero value
masks the same row as before.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, RandomNormal

_IDS_CHECK_SCOPE = threading.local()


@contextlib.contextmanager
def checkify_ids_scope():
    """While active (per thread), a traced ``BIGDL_CHECK_IDS=1`` guard emits
    ``checkify.check`` calls — only enter around code that is being
    functionalized by ``checkify.checkify`` (the checked train step does)."""
    prev = getattr(_IDS_CHECK_SCOPE, "active", False)
    _IDS_CHECK_SCOPE.active = True
    try:
        yield
    finally:
        _IDS_CHECK_SCOPE.active = prev


def _ids_scope_active() -> bool:
    return getattr(_IDS_CHECK_SCOPE, "active", False)


def check_ids_enabled() -> bool:
    return os.environ.get("BIGDL_CHECK_IDS", "0") == "1"


class LookupTable(TensorModule):
    def __init__(self, n_index: int, n_output: int,
                 padding_value: Optional[float] = None,
                 max_norm: float = float("inf"), norm_type: float = 2.0,
                 should_scale_grad_by_freq: bool = False,
                 w_init: Optional[InitializationMethod] = None,
                 zero_based: bool = False):
        super().__init__()
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.w_init = w_init or RandomNormal(0.0, 1.0)
        self.zero_based = zero_based
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.asarray(
            self.w_init.init((self.n_index, self.n_output),
                             fan_in=self.n_index, fan_out=self.n_output))}
        self.zero_grad_parameters()

    # ---------------------------------------------------------- lookup core
    # Factored so parallel/embedding.py's ShardedEmbedding can reuse the exact
    # same id normalization / renorm / padding math on its dedup + sharded
    # paths (bitwise equality to this layer is a test invariant).
    def _ids(self, input):
        """Raw input → 0-based int32 row indices (guarded when enabled)."""
        idx = input.astype(jnp.int32)
        if not self.zero_based:
            idx = idx - 1  # reference/Torch indices are 1-based
        if check_ids_enabled():
            self._guard_ids(idx)
        return idx

    def _guard_ids(self, idx) -> None:
        if isinstance(idx, jax.core.Tracer):
            if _ids_scope_active():
                from jax.experimental import checkify
                checkify.check(
                    jnp.all((idx >= 0) & (idx < self.n_index)),
                    f"{self!r}: id out of range [0, {self.n_index}) after "
                    "base adjustment (min={mn}, max={mx})",
                    mn=jnp.min(idx), mx=jnp.max(idx))
            return
        a = np.asarray(idx)
        if a.size and (int(a.min()) < 0 or int(a.max()) >= self.n_index):
            raise IndexError(
                f"{self!r}: ids out of range — after base adjustment indices "
                f"span [{int(a.min())}, {int(a.max())}] but the table has "
                f"{self.n_index} rows (valid range [0, {self.n_index})). "
                "A jitted gather would silently clamp these "
                "(BIGDL_CHECK_IDS=1 caught it).")

    def _pad_index(self) -> Optional[int]:
        """Padding row as a 0-based index, or None when masking is off.
        1-based mode keeps the reference convention that padding_value=0
        means "no padding" (ids start at 1); zero-based mode can mask row 0."""
        if self.padding_value is None:
            return None
        p = int(self.padding_value)
        if not self.zero_based:
            return None if p == 0 else p - 1
        return p

    def _renorm(self, w):
        """Full-table max-norm renorm (Torch renorm-before-lookup semantics)."""
        if self.max_norm == float("inf"):
            return w
        norms = jnp.power(
            jnp.sum(jnp.power(jnp.abs(w), self.norm_type), axis=1, keepdims=True),
            1.0 / self.norm_type)
        scale = jnp.minimum(1.0, self.max_norm / (norms + 1e-7))
        return w * scale

    # renorm is row-local (each row scaled by its own norm), so applying the
    # identical formula to an already-gathered (U, D) row block is the same
    # arithmetic per row — what lets deduped gathers renorm U rows, not V
    _renorm_rows = _renorm

    def _mask_padding(self, out, idx):
        pad = self._pad_index()
        if pad is None:
            return out
        return jnp.where((idx == pad)[..., None], 0.0, out)

    def apply(self, params, state, input, *, training=False, rng=None):
        idx = self._ids(input)
        out = self._renorm(params["weight"])[idx]
        return self._mask_padding(out, idx), state

    def forward(self, input):
        # The jitted apply only ever sees Tracers, where the host-side guard
        # cannot fire; run the id normalization eagerly on the concrete batch
        # first so BIGDL_CHECK_IDS=1 raises before the gather clamps.
        if check_ids_enabled():
            self._ids(jnp.asarray(input))
        return super().forward(input)

    def __repr__(self):
        return f"LookupTable({self.n_index} -> {self.n_output})"


class HashBucketEmbedding(LookupTable):
    """Embedding over hashed ids: arbitrary (possibly unbounded) non-negative
    integer ids are mixed with a Fibonacci multiplicative hash and mapped into
    ``n_buckets`` rows. The analog of the reference recommendation examples'
    hashing trick for out-of-vocabulary users/items (SURVEY.md §2.5 Examples:
    NCF / Wide&Deep), without the host-side feature dictionary.

    Always zero-based (ids are raw hashes, not Torch 1-based vocab indices).
    """

    def __init__(self, n_buckets: int, n_output: int,
                 w_init: Optional[InitializationMethod] = None):
        super().__init__(n_buckets, n_output, w_init=w_init, zero_based=True)

    def _ids(self, input):
        h = input.astype(jnp.uint32)
        # murmur3-style 32-bit finalizer: full avalanche, so every bucket in
        # [0, n_buckets) is reachable for any n_buckets up to 2^32 — a handful
        # of fused integer ops on the VPU
        h = h ^ (h >> jnp.uint32(16))
        h = h * jnp.uint32(0x85EBCA6B)
        h = h ^ (h >> jnp.uint32(13))
        h = h * jnp.uint32(0xC2B2AE35)
        h = h ^ (h >> jnp.uint32(16))
        return (h % jnp.uint32(self.n_index)).astype(jnp.int32)

    def __repr__(self):
        return f"HashBucketEmbedding({self.n_index} buckets -> {self.n_output})"
