"""Object-detection support layers — SSD / Faster-R-CNN heads.

Reference parity (SURVEY.md §2.1 layer zoo, expected ``<dl>/nn/PriorBox.scala``,
``NormalizeScale.scala``, ``Anchor.scala``, ``Proposal.scala``,
``DetectionOutputSSD.scala`` — unverified, mount empty): the reference ships the
Caffe-lineage detection ops so SSD and Faster-R-CNN graphs imported from Caffe
run natively; Proposal/DetectionOutput use data-dependent candidate counts and
CPU greedy NMS.

TPU-native redesign: every data-dependent count becomes a STATIC budget with a
validity mask, so the whole post-processing chain stays inside one jitted
program instead of falling back to the host:

- prior/anchor generation depends only on feature-map *shape*, so it is computed
  with numpy at trace time and baked into the program as a constant — zero
  device cost per step.
- greedy NMS is the classic O(K²) masked recurrence over a score-sorted, fixed
  K candidate list (``lax.fori_loop`` over rows of a precomputed IoU matrix) —
  the standard shape-static TPU formulation (cf. TF's
  ``non_max_suppression_padded``).
- Proposal / DetectionOutputSSD emit fixed-size outputs padded with sentinel
  rows (score 0, label -1) instead of variable-length lists.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.abstractnn import AbstractModule
from bigdl_tpu.nn.initialization import InitializationMethod, ConstInitMethod
from bigdl_tpu.utils.table import Table


# --------------------------------------------------------------------- utils

def pairwise_iou(boxes_a: jnp.ndarray, boxes_b: jnp.ndarray) -> jnp.ndarray:
    """IoU matrix between two (…,4) corner-form box sets: (A, 4)×(B, 4)→(A, B)."""
    ax1, ay1, ax2, ay2 = jnp.split(boxes_a, 4, axis=-1)          # (A,1)
    bx1, by1, bx2, by2 = [v[:, 0] for v in jnp.split(boxes_b, 4, axis=-1)]
    ix1 = jnp.maximum(ax1, bx1[None, :])
    iy1 = jnp.maximum(ay1, by1[None, :])
    ix2 = jnp.minimum(ax2, bx2[None, :])
    iy2 = jnp.minimum(ay2, by2[None, :])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(ax2 - ax1, 0) * jnp.clip(ay2 - ay1, 0)
    area_b = jnp.clip(bx2 - bx1, 0) * jnp.clip(by2 - by1, 0)
    union = area_a + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def nms_mask(boxes: jnp.ndarray, scores: jnp.ndarray, iou_threshold: float,
             valid: Optional[jnp.ndarray] = None) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS over a FIXED-size candidate list.

    Returns ``(order, keep)``: ``order`` (K,) int32 score-descending candidate
    indices and ``keep`` (K,) bool aligned with ``order`` — ``order[keep]`` are
    the surviving boxes, highest score first. ``valid`` masks out padding
    candidates before sorting. Shape-static: K is the compile-time budget.
    """
    k = scores.shape[0]
    if valid is not None:
        scores = jnp.where(valid, scores, -jnp.inf)
    order = jnp.argsort(-scores)
    sb = boxes[order]
    iou = pairwise_iou(sb, sb)
    alive = jnp.isfinite(scores[order])

    def body(i, keep):
        # candidate i survives iff no higher-scored survivor overlaps it
        sup = jnp.any(keep & (jnp.arange(k) < i) & (iou[:, i] > iou_threshold))
        return keep.at[i].set(keep[i] & ~sup)

    keep = jax.lax.fori_loop(0, k, body, alive)
    return order, keep


def decode_ssd(priors: jnp.ndarray, variances: jnp.ndarray,
               deltas: jnp.ndarray) -> jnp.ndarray:
    """Caffe/SSD box decode: corner-form priors (P,4) + encoded deltas (P,4)
    → corner-form boxes (P,4). Variance-scaled center-size encoding."""
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) * 0.5
    pcy = (priors[:, 1] + priors[:, 3]) * 0.5
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    vx, vy, vw, vh = variances[:, 0], variances[:, 1], variances[:, 2], variances[:, 3]
    cx = pcx + dx * vx * pw
    cy = pcy + dy * vy * ph
    w = pw * jnp.exp(dw * vw)
    h = ph * jnp.exp(dh * vh)
    return jnp.stack([cx - w * 0.5, cy - h * 0.5, cx + w * 0.5, cy + h * 0.5], axis=1)


def encode_ssd(priors: jnp.ndarray, variances: jnp.ndarray,
               boxes: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`decode_ssd` (pinned by test): corner-form ``boxes``
    (P, 4) → variance-scaled center-size deltas against the priors. Training
    targets for MultiBoxCriterion."""
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) * 0.5
    pcy = (priors[:, 1] + priors[:, 3]) * 0.5
    bw = jnp.maximum(boxes[:, 2] - boxes[:, 0], 1e-8)
    bh = jnp.maximum(boxes[:, 3] - boxes[:, 1], 1e-8)
    bcx = (boxes[:, 0] + boxes[:, 2]) * 0.5
    bcy = (boxes[:, 1] + boxes[:, 3]) * 0.5
    dx = (bcx - pcx) / pw / variances[:, 0]
    dy = (bcy - pcy) / ph / variances[:, 1]
    dw = jnp.log(bw / pw) / variances[:, 2]
    dh = jnp.log(bh / ph) / variances[:, 3]
    return jnp.stack([dx, dy, dw, dh], axis=1)


def decode_rcnn(anchors: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """Faster-R-CNN box decode (unit variances, +1 width convention)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw * 0.5
    acy = anchors[:, 1] + ah * 0.5
    cx = acx + deltas[:, 0] * aw
    cy = acy + deltas[:, 1] * ah
    w = aw * jnp.exp(deltas[:, 2])
    h = ah * jnp.exp(deltas[:, 3])
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], axis=1)


def _generate_base_anchors(base_size: float, ratios: Sequence[float],
                           scales: Sequence[float]) -> np.ndarray:
    """py-faster-rcnn base anchor recipe: ratio-warp the base box (area kept,
    rounded), then scale. Returns (len(ratios)*len(scales), 4) corner boxes
    centered on the base box center."""
    w = h = float(base_size)
    cx = (w - 1.0) * 0.5
    cy = (h - 1.0) * 0.5
    out = []
    for r in ratios:
        size = w * h
        ws = round(math.sqrt(size / r))
        hs = round(ws * r)
        for s in scales:
            sw, sh = ws * s, hs * s
            out.append([cx - (sw - 1) * 0.5, cy - (sh - 1) * 0.5,
                        cx + (sw - 1) * 0.5, cy + (sh - 1) * 0.5])
    return np.array(out, dtype=np.float32)


# -------------------------------------------------------------------- layers

class NormalizeScale(AbstractModule):
    """Channelwise Lp normalization with a learned per-channel scale — the
    SSD conv4_3 trick (reference ``NormalizeScale`` = ``Normalize`` +
    learnable ``CMul``). Input (N, C, H, W) (or NHWC under the layout flag);
    each spatial position's channel vector is Lp-normalized then multiplied
    by ``weight[c]`` (initialized to ``scale``, typically 20)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10, scale: float = 20.0,
                 size: Optional[int] = None,
                 w_regularizer=None):
        super().__init__()
        self.p, self.eps, self.scale = float(p), float(eps), float(scale)
        self.size = size
        self.w_regularizer = w_regularizer
        if size is not None:
            self._params["weight"] = jnp.full((int(size),), self.scale, jnp.float32)

    def reset(self):
        if self.size is not None:
            self._params["weight"] = jnp.full((int(self.size),), self.scale, jnp.float32)

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        ca = layout.channel_axis(input.ndim) if input.ndim == 4 else -1
        if self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(jnp.square(input), axis=ca, keepdims=True) + self.eps)
        else:
            norm = jnp.power(jnp.sum(jnp.power(jnp.abs(input), self.p),
                                     axis=ca, keepdims=True) + self.eps, 1.0 / self.p)
        out = input / norm
        w = params.get("weight")
        if w is not None:
            shape = [1] * input.ndim
            shape[ca] = w.shape[0]
            out = out * w.reshape(shape)
        else:
            out = out * self.scale
        return out, state

    def __repr__(self):
        return f"NormalizeScale(p={self.p}, scale={self.scale}, size={self.size})"


class PriorBox(AbstractModule):
    """SSD prior (default box) generator. Input: the feature map the priors
    tile over; output ``(1, 2, H*W*num_priors*4)`` — row 0 the normalized
    corner-form priors, row 1 the per-coordinate variances (Caffe layout, so
    imported SSD graphs consume it unchanged).

    Priors depend only on the feature map SHAPE, so they are computed in numpy
    at trace time and enter the program as a compile-time constant."""

    def __init__(self, min_sizes: Sequence[float],
                 max_sizes: Optional[Sequence[float]] = None,
                 aspect_ratios: Sequence[float] = (),
                 flip: bool = True, clip: bool = False,
                 variances: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
                 step: float = 0.0, offset: float = 0.5,
                 img_h: int = 0, img_w: int = 0):
        super().__init__()
        self.min_sizes = [float(s) for s in min_sizes]
        self.max_sizes = [float(s) for s in (max_sizes or [])]
        if self.max_sizes and len(self.max_sizes) != len(self.min_sizes):
            raise ValueError("max_sizes must pair 1:1 with min_sizes")
        ars = [1.0]
        for ar in aspect_ratios:
            if any(abs(ar - a) < 1e-6 for a in ars):
                continue
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
        self.aspect_ratios = ars
        self.clip = clip
        self.variances = [float(v) for v in variances]
        self.step = float(step)
        self.offset = float(offset)
        self.img_h, self.img_w = int(img_h), int(img_w)

    @property
    def num_priors(self) -> int:
        return len(self.min_sizes) * len(self.aspect_ratios) + len(self.max_sizes)

    def _compute(self, layer_h: int, layer_w: int) -> np.ndarray:
        img_h, img_w = self.img_h, self.img_w
        if img_h <= 0 or img_w <= 0:
            raise ValueError("PriorBox needs img_h/img_w (network input size)")
        step_h = step_w = self.step
        if step_h <= 0:
            step_h = img_h / layer_h
            step_w = img_w / layer_w
        priors = []
        for y in range(layer_h):
            for x in range(layer_w):
                cx = (x + self.offset) * step_w
                cy = (y + self.offset) * step_h
                for i, ms in enumerate(self.min_sizes):
                    for j, ar in enumerate(self.aspect_ratios):
                        bw = ms * math.sqrt(ar)
                        bh = ms / math.sqrt(ar)
                        priors.append([(cx - bw / 2) / img_w, (cy - bh / 2) / img_h,
                                       (cx + bw / 2) / img_w, (cy + bh / 2) / img_h])
                        if j == 0 and self.max_sizes:
                            s = math.sqrt(ms * self.max_sizes[i])
                            priors.append([(cx - s / 2) / img_w, (cy - s / 2) / img_h,
                                           (cx + s / 2) / img_w, (cy + s / 2) / img_h])
        arr = np.array(priors, dtype=np.float32)
        if self.clip:
            arr = np.clip(arr, 0.0, 1.0)
        return arr

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        if input.ndim != 4:
            raise ValueError("PriorBox expects a 4-D feature map")
        hax, wax = layout.spatial_axes(4)
        layer_h, layer_w = int(input.shape[hax]), int(input.shape[wax])
        priors = self._compute(layer_h, layer_w).reshape(-1)
        var = np.tile(np.array(self.variances, np.float32),
                      priors.shape[0] // 4)
        out = jnp.asarray(np.stack([priors, var])[None])   # (1, 2, P*4)
        return out, state

    def __repr__(self):
        return (f"PriorBox(min={self.min_sizes}, max={self.max_sizes}, "
                f"ars={self.aspect_ratios}, num_priors={self.num_priors})")


class Anchor(AbstractModule):
    """RPN anchor generator (reference ``Anchor``): all base anchors shifted
    over the feature-map grid. ``generate(h, w, stride)`` (or calling the
    module on a feature map) returns ``(h*w*A, 4)`` image-space corner boxes,
    row-major over (y, x, a) — computed at trace time, constant on device."""

    def __init__(self, ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 scales: Sequence[float] = (8.0, 16.0, 32.0),
                 base_size: float = 16.0):
        super().__init__()
        self.ratios = [float(r) for r in ratios]
        self.scales = [float(s) for s in scales]
        self.base_size = float(base_size)
        self._base = _generate_base_anchors(base_size, self.ratios, self.scales)

    @property
    def num_anchors(self) -> int:
        return len(self.ratios) * len(self.scales)

    def generate(self, height: int, width: int, stride: Optional[float] = None) -> np.ndarray:
        stride = float(stride if stride is not None else self.base_size)
        sx = np.arange(width, dtype=np.float32) * stride
        sy = np.arange(height, dtype=np.float32) * stride
        shifts = np.stack(np.meshgrid(sx, sy), axis=-1).reshape(-1, 2)  # (H*W, 2) xy
        shifts4 = np.concatenate([shifts, shifts], axis=1)              # x1 y1 x2 y2
        return (self._base[None, :, :] + shifts4[:, None, :]).reshape(-1, 4)

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        hax, wax = layout.spatial_axes(4)
        h, w = int(input.shape[hax]), int(input.shape[wax])
        return jnp.asarray(self.generate(h, w)), state

    def __repr__(self):
        return f"Anchor(ratios={self.ratios}, scales={self.scales}, base={self.base_size})"


class Proposal(AbstractModule):
    """RPN proposal layer (reference ``Proposal``): decode RPN deltas onto the
    anchor grid, clip to the image, drop sub-minimum boxes, keep the
    ``pre_nms_topn`` highest-scored, greedy-NMS at 0.7, emit the top
    ``post_nms_topn`` as ROIs.

    Input: Table ``(scores (1, 2A, H, W), deltas (1, 4A, H, W),
    im_info (1, ≥3) = [img_h, img_w, scale…])``. Output: Table
    ``(rois (post_nms_topn, 5), valid (post_nms_topn,))`` — rois rows are
    ``[batch_idx, x1, y1, x2, y2]``; the static budget is padded and ``valid``
    marks real rows (the reference returns a variable-length tensor; a fixed
    budget + mask is the jit-stable equivalent and what RoiPooling consumes)."""

    def __init__(self, pre_nms_topn: int = 6000, post_nms_topn: int = 300,
                 ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 scales: Sequence[float] = (8.0, 16.0, 32.0),
                 rpn_min_size: float = 16.0, nms_thresh: float = 0.7,
                 feat_stride: float = 16.0):
        super().__init__()
        self.pre_nms_topn = int(pre_nms_topn)
        self.post_nms_topn = int(post_nms_topn)
        self.anchor = Anchor(ratios, scales, base_size=feat_stride)
        self.rpn_min_size = float(rpn_min_size)
        self.nms_thresh = float(nms_thresh)
        self.feat_stride = float(feat_stride)

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn import layout
        xs = input.values() if isinstance(input, Table) else list(input)
        scores, deltas, im_info = xs[0], xs[1], xs[2]
        if layout.is_nhwc():
            # RPN wire format below is channel-first (Caffe parity); accept the
            # NHWC conv outputs the layout flag produces by transposing once.
            scores = scores.transpose(0, 3, 1, 2)
            deltas = deltas.transpose(0, 3, 1, 2)
        if scores.shape[0] != 1:
            raise ValueError(
                f"Proposal is single-image (reference contract): got batch "
                f"{scores.shape[0]}; vmap/loop over images instead")
        a = self.anchor.num_anchors
        h, w = int(scores.shape[2]), int(scores.shape[3])
        anchors = jnp.asarray(self.anchor.generate(h, w, self.feat_stride))  # (H*W*A,4)
        # foreground scores are the second A channels: (1, 2A, H, W) → (H*W*A,)
        fg = scores[0, a:].transpose(1, 2, 0).reshape(-1)
        # deltas (1, 4A, H, W) → (H*W*A, 4)
        d = deltas[0].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        boxes = decode_rcnn(anchors, d)
        img_h, img_w = im_info.reshape(-1)[0], im_info.reshape(-1)[1]
        scale = im_info.reshape(-1)[2] if im_info.size > 2 else jnp.float32(1.0)
        boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, img_w - 1),
                           jnp.clip(boxes[:, 1], 0, img_h - 1),
                           jnp.clip(boxes[:, 2], 0, img_w - 1),
                           jnp.clip(boxes[:, 3], 0, img_h - 1)], axis=1)
        min_sz = self.rpn_min_size * scale
        ok = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_sz)
              & (boxes[:, 3] - boxes[:, 1] + 1 >= min_sz))
        fg = jnp.where(ok, fg, -jnp.inf)
        k = min(self.pre_nms_topn, boxes.shape[0])
        top_scores, top_idx = jax.lax.top_k(fg, k)
        cand = boxes[top_idx]
        order, keep = nms_mask(cand, top_scores, self.nms_thresh,
                               valid=jnp.isfinite(top_scores))
        # survivors are already score-sorted along `order`; take the first
        # post_nms_topn of them, padding the static budget with invalid rows
        n_out = self.post_nms_topn
        surv_pos = jnp.nonzero(keep, size=n_out, fill_value=-1)[0]
        valid = surv_pos >= 0
        sel = order[jnp.clip(surv_pos, 0)]
        rois_boxes = jnp.where(valid[:, None], cand[sel], 0.0)
        rois = jnp.concatenate([jnp.zeros((n_out, 1), rois_boxes.dtype), rois_boxes], axis=1)
        return Table(rois, valid), state

    def __repr__(self):
        return (f"Proposal(pre={self.pre_nms_topn}, post={self.post_nms_topn}, "
                f"nms={self.nms_thresh})")


class DetectionOutputSSD(AbstractModule):
    """SSD detection head post-processing (reference ``DetectionOutputSSD``):
    decode location predictions against the priors, per-class score threshold
    + greedy NMS, then keep the global top-k.

    Input: Table ``(loc (N, P*4), conf (N, P*n_classes), priors (1, 2, P*4))``
    (the Caffe/reference wire format — conf already softmaxed unless
    ``conf_post_process``). Output ``(N, keep_topk, 6)`` rows
    ``[label, score, xmin, ymin, xmax, ymax]``; padding rows have label -1,
    score 0. Fixed budgets replace the reference's variable-length output."""

    def __init__(self, n_classes: int, share_location: bool = True,
                 bg_label: int = 0, nms_thresh: float = 0.45,
                 nms_topk: int = 400, keep_topk: int = 200,
                 conf_thresh: float = 0.01, conf_post_process: bool = True):
        super().__init__()
        if not share_location:
            raise NotImplementedError(
                "per-class location predictions (share_location=False) are not "
                "supported; every public SSD topology shares locations")
        self.n_classes = int(n_classes)
        self.bg_label = int(bg_label)
        self.nms_thresh = float(nms_thresh)
        self.nms_topk = int(nms_topk)
        self.keep_topk = int(keep_topk)
        self.conf_thresh = float(conf_thresh)
        self.conf_post_process = bool(conf_post_process)

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        loc, conf, priors = xs[0], xs[1], xs[2]
        n = loc.shape[0]
        p = loc.shape[1] // 4
        pri = priors.reshape(2, -1, 4)   # accepts (1,2,P*4) and (2,P*4) wire forms
        prior_boxes, prior_var = pri[0], pri[1]
        conf = conf.reshape(n, p, self.n_classes)
        if self.conf_post_process:
            conf = jax.nn.softmax(conf, axis=-1)

        cls_ids = [c for c in range(self.n_classes) if c != self.bg_label]
        k = min(self.nms_topk, p)

        def one_image(loc_i, conf_i):
            boxes = decode_ssd(prior_boxes, prior_var, loc_i.reshape(p, 4))

            def one_class(scores_c):
                s = jnp.where(scores_c >= self.conf_thresh, scores_c, -jnp.inf)
                top_s, top_i = jax.lax.top_k(s, k)
                cand = boxes[top_i]
                order, keep = nms_mask(cand, top_s, self.nms_thresh,
                                       valid=jnp.isfinite(top_s))
                sel_scores = jnp.where(keep, top_s[order], -jnp.inf)
                return cand[order], sel_scores

            cls_scores = conf_i[:, jnp.array(cls_ids)].T        # (C', P)
            cboxes, cscores = jax.vmap(one_class)(cls_scores)   # (C', k, 4), (C', k)
            labels = jnp.broadcast_to(jnp.array(cls_ids, jnp.float32)[:, None],
                                      cscores.shape)
            flat_s = cscores.reshape(-1)
            flat_b = cboxes.reshape(-1, 4)
            flat_l = labels.reshape(-1)
            kk = min(self.keep_topk, flat_s.shape[0])
            top_s, top_i = jax.lax.top_k(flat_s, kk)
            good = jnp.isfinite(top_s)
            row = jnp.concatenate([
                jnp.where(good, flat_l[top_i], -1.0)[:, None],
                jnp.where(good, top_s, 0.0)[:, None],
                jnp.where(good[:, None], flat_b[top_i], 0.0)], axis=1)
            if kk < self.keep_topk:
                pad = jnp.zeros((self.keep_topk - kk, 6), row.dtype).at[:, 0].set(-1.0)
                row = jnp.concatenate([row, pad], axis=0)
            return row

        return jax.vmap(one_image)(loc, conf), state

    def __repr__(self):
        return (f"DetectionOutputSSD(classes={self.n_classes}, "
                f"nms={self.nms_thresh}, keep={self.keep_topk})")
