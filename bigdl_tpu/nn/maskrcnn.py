"""Mask-R-CNN module family (SURVEY §2.1 layer zoo tail — expected
``<dl>/nn/{RoiAlign,FPN,Pooler,RegionProposal,BoxHead,MaskHead,
DetectionOutputFrcnn}.scala``, unverified, mount empty).

TPU-first shape discipline throughout: every stage runs on FIXED budgets
(R rois, per-class NMS over static candidate lists) so the whole detector
traces once — the same redesign :mod:`bigdl_tpu.nn.detection` applies to
SSD. Heads are Containers over stock conv/linear modules, so params,
serialization, freeze/LoRA and the optimizer see nothing new."""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.abstractnn import AbstractModule, Container
from bigdl_tpu.nn.convolution import (SpatialConvolution,
                                      SpatialFullConvolution)
from bigdl_tpu.nn.detection import decode_rcnn, nms_mask
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.roi import RoiPooling
from bigdl_tpu.utils.table import Table


class RoiAlign(RoiPooling):
    """Reference-named RoiAlign (``RoiAlign(spatialScale, samplingRatio,
    pooledH, pooledW)``): the ALIGNED coordinate transform — continuous
    coordinates shift by -0.5 so sample points sit at pixel centers (the
    Mask-R-CNN fix to RoiPooling's quantization). The underlying fixed-
    budget bilinear sampler is shared with :class:`RoiPooling`."""

    def __init__(self, spatial_scale: float, sampling_ratio: int,
                 pooled_h: int, pooled_w: int, mode: str = "avg"):
        super().__init__(pooled_h, pooled_w, spatial_scale=spatial_scale,
                         sampling_ratio=sampling_ratio, mode=mode)

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = input.values() if isinstance(input, Table) else list(input)
        feats, rois = xs[0], xs[1]
        # aligned=True: image-space box * scale - 0.5 (pixel-center grid)
        r = rois.astype(jnp.float32)
        shifted = jnp.concatenate(
            [r[:, :1], r[:, 1:] - 0.5 / self.spatial_scale], axis=1)
        return super().apply(params, state, Table(feats, shifted),
                             training=training, rng=rng)

    def __repr__(self):
        return (f"RoiAlign(scale={self.spatial_scale}, "
                f"{self.pooled_h}x{self.pooled_w})")


class FPN(Container):
    """Feature Pyramid Network (reference ``FPN(inChannels, outChannels,
    topBlocks)``): per-level lateral 1x1 convs, top-down nearest-neighbour
    upsampling, 3x3 output convs; ``top_blocks=1`` appends a stride-2
    max-pooled P6. Input: Table(C2..C5) fine→coarse; output Table(P2..P5
    [, P6]) in the same order."""

    def __init__(self, in_channels: Sequence[int], out_channels: int,
                 top_blocks: int = 0):
        in_channels = list(in_channels)
        laterals = [SpatialConvolution(c, out_channels, 1, 1)
                    for c in in_channels]
        outputs = [SpatialConvolution(out_channels, out_channels, 3, 3,
                                      pad_w=1, pad_h=1)
                   for _ in in_channels]
        super().__init__(*(laterals + outputs))
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.top_blocks = int(top_blocks)

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = list(input.values()) if isinstance(input, Table) else list(input)
        n_lvl = len(self.in_channels)
        if len(xs) != n_lvl:
            raise ValueError(f"FPN expects {n_lvl} levels, got {len(xs)}")
        new_state = dict(state)

        def run(i, x):
            out, s = self.modules[i].apply(params[str(i)], state[str(i)], x,
                                           training=training, rng=None)
            new_state[str(i)] = s
            return out

        lat = [run(i, x) for i, x in enumerate(xs)]
        # top-down: coarsest lateral is the seed; upsample 2x and add
        merged = [None] * n_lvl
        merged[-1] = lat[-1]
        for i in range(n_lvl - 2, -1, -1):
            up = merged[i + 1]
            up = jnp.repeat(jnp.repeat(up, 2, axis=2), 2, axis=3)
            up = up[:, :, : lat[i].shape[2], : lat[i].shape[3]]
            merged[i] = lat[i] + up
        outs = [run(n_lvl + i, m) for i, m in enumerate(merged)]
        if self.top_blocks:
            p6 = jax.lax.reduce_window(
                outs[-1], -jnp.inf, jax.lax.max, (1, 1, 1, 1), (1, 1, 2, 2),
                "VALID")
            outs.append(p6)
        return Table(*outs), new_state

    def __repr__(self):
        return (f"FPN({self.in_channels} -> {self.out_channels}, "
                f"top_blocks={self.top_blocks})")


class Pooler(AbstractModule):
    """Multi-level ROI feature extractor (reference ``Pooler(resolution,
    scales, samplingRatio)``): each ROI maps to a pyramid level by the FPN
    heuristic ``level = floor(k0 + log2(sqrt(area)/224))``, is RoiAligned
    there, and the per-level results merge by mask — shape-static (every
    ROI is sampled at every level; XLA fuses the selects).

    Input: Table(Table(features...), rois (R, 5)); output
    (R, C, resolution, resolution)."""

    def __init__(self, resolution: int, scales: Sequence[float],
                 sampling_ratio: int):
        super().__init__()
        self.resolution = int(resolution)
        self.scales = [float(s) for s in scales]
        self.sampling_ratio = int(sampling_ratio)
        self._aligners = [RoiAlign(s, sampling_ratio, resolution, resolution)
                          for s in self.scales]
        # canonical level assignment (FPN paper): k = floor(4 + log2(√area/224)),
        # index = k - finest_level, finest_level from the largest scale
        self.finest_level = int(round(-math.log2(max(self.scales))))
        self.canonical = 224.0

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = list(input.values()) if isinstance(input, Table) else list(input)
        feat_t, rois = xs[0], xs[1]
        feats = (list(feat_t.values()) if isinstance(feat_t, Table)
                 else list(feat_t))
        if len(feats) != len(self.scales):
            raise ValueError(
                f"Pooler has {len(self.scales)} scales but got "
                f"{len(feats)} feature levels")
        r = rois.astype(jnp.float32)
        area = jnp.maximum(r[:, 3] - r[:, 1], 0) * jnp.maximum(
            r[:, 4] - r[:, 2], 0)
        k = jnp.floor(4.0 + jnp.log2(jnp.sqrt(area) / self.canonical + 1e-6))
        target = jnp.clip(k - self.finest_level,
                          0, len(feats) - 1).astype(jnp.int32)
        pooled = []
        for lvl, (f, al) in enumerate(zip(feats, self._aligners)):
            out, _ = al.apply({}, {}, Table(f, rois), training=training)
            pooled.append(out)
        stacked = jnp.stack(pooled)                     # (L, R, C, res, res)
        sel = jax.nn.one_hot(target, len(feats),
                             dtype=stacked.dtype)       # (R, L)
        return jnp.einsum("lrchw,rl->rchw", stacked, sel), state

    def __repr__(self):
        return (f"Pooler(res={self.resolution}, scales={self.scales}, "
                f"sampling={self.sampling_ratio})")


class BoxHead(Container):
    """Fast-R-CNN box head (reference ``BoxHead``): Pooler → two FC layers →
    class logits + per-class box deltas. Input: Table(Table(features...),
    rois (R, 5)); output Table(cls_logits (R, n_classes), bbox_deltas
    (R, 4·n_classes))."""

    def __init__(self, in_channels: int, resolution: int,
                 scales: Sequence[float], sampling_ratio: int,
                 n_classes: int, representation: int = 1024):
        fc1 = Linear(in_channels * resolution * resolution, representation)
        fc2 = Linear(representation, representation)
        cls = Linear(representation, n_classes)
        bbox = Linear(representation, 4 * n_classes)
        super().__init__(fc1, fc2, cls, bbox)
        self.in_channels = in_channels
        self.resolution = resolution
        self.scales = [float(s) for s in scales]
        self.sampling_ratio = sampling_ratio
        self.n_classes = n_classes
        self.representation = representation
        self.pooler = Pooler(resolution, scales, sampling_ratio)

    def apply(self, params, state, input, *, training=False, rng=None):
        feats_rois = input
        pooled, _ = self.pooler.apply({}, {}, feats_rois, training=training)
        x = pooled.reshape(pooled.shape[0], -1)
        new_state = dict(state)

        def run(i, x, act=False):
            out, s = self.modules[i].apply(params[str(i)], state[str(i)], x,
                                           training=training, rng=None)
            new_state[str(i)] = s
            return jax.nn.relu(out) if act else out

        x = run(0, x, act=True)
        x = run(1, x, act=True)
        return Table(run(2, x), run(3, x)), new_state

    def __repr__(self):
        return (f"BoxHead(in={self.in_channels}, res={self.resolution}, "
                f"classes={self.n_classes})")


class MaskHead(Container):
    """Mask-R-CNN mask head (reference ``MaskHead``): Pooler → 4 SAME 3x3
    convs (ReLU) → 2x deconv (ReLU) → 1x1 conv to per-class masks. Input:
    Table(Table(features...), rois (R, 5)); output (R, n_classes,
    2·resolution, 2·resolution) mask logits."""

    def __init__(self, in_channels: int, resolution: int,
                 scales: Sequence[float], sampling_ratio: int,
                 n_classes: int, layers: Sequence[int] = (256, 256, 256, 256),
                 dilation: int = 1):
        mods = []
        prev = in_channels
        for width in layers:
            mods.append(SpatialConvolution(
                prev, width, 3, 3, pad_w=dilation, pad_h=dilation))
            prev = width
        mods.append(SpatialFullConvolution(prev, prev, 2, 2, dw=2, dh=2))
        mods.append(SpatialConvolution(prev, n_classes, 1, 1))
        super().__init__(*mods)
        self.in_channels = in_channels
        self.resolution = resolution
        self.scales = [float(s) for s in scales]
        self.sampling_ratio = sampling_ratio
        self.n_classes = n_classes
        self.layers = list(layers)
        self.dilation = dilation
        self.pooler = Pooler(resolution, scales, sampling_ratio)

    def apply(self, params, state, input, *, training=False, rng=None):
        x, _ = self.pooler.apply({}, {}, input, training=training)
        new_state = dict(state)
        for i, m in enumerate(self.modules):
            x, s = m.apply(params[str(i)], state[str(i)], x,
                           training=training, rng=None)
            new_state[str(i)] = s
            if i < len(self.modules) - 1:   # all but the mask predictor
                x = jax.nn.relu(x)
        return x, new_state

    def __repr__(self):
        return (f"MaskHead(in={self.in_channels}, res={self.resolution}, "
                f"classes={self.n_classes})")


class RegionProposal(Container):
    """Multi-level RPN (reference ``RegionProposal``): a shared 3x3 conv +
    objectness/bbox 1x1 heads over every FPN level, per-level Proposal
    decode (fixed budgets), concatenated. Single-image contract like
    :class:`~bigdl_tpu.nn.detection.Proposal`. Input:
    Table(Table(features...), im_info (1, 3)); output Table(rois (K, 5),
    valid (K,)) with K = per-level post-NMS budget × levels."""

    def __init__(self, in_channels: int,
                 anchor_sizes: Sequence[float] = (32, 64, 128, 256, 512),
                 aspect_ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 feat_strides: Sequence[float] = (4, 8, 16, 32, 64),
                 pre_nms_topn: int = 2000, post_nms_topn: int = 1000,
                 nms_thresh: float = 0.7, rpn_min_size: float = 0.0):
        from bigdl_tpu.nn.detection import Proposal

        if len(anchor_sizes) != len(feat_strides):
            raise ValueError("one anchor size per pyramid level")
        a = len(aspect_ratios)
        conv = SpatialConvolution(in_channels, in_channels, 3, 3,
                                  pad_w=1, pad_h=1)
        cls = SpatialConvolution(in_channels, 2 * a, 1, 1)
        bbox = SpatialConvolution(in_channels, 4 * a, 1, 1)
        super().__init__(conv, cls, bbox)
        self.in_channels = in_channels
        self.anchor_sizes = [float(s) for s in anchor_sizes]
        self.aspect_ratios = [float(r) for r in aspect_ratios]
        self.feat_strides = [float(s) for s in feat_strides]
        self.pre_nms_topn, self.post_nms_topn = pre_nms_topn, post_nms_topn
        n_lvl = len(feat_strides)
        self._proposals = [
            Proposal(pre_nms_topn=pre_nms_topn // n_lvl,
                     post_nms_topn=post_nms_topn // n_lvl,
                     ratios=aspect_ratios,
                     scales=[self.anchor_sizes[i] / self.feat_strides[i]],
                     rpn_min_size=rpn_min_size, nms_thresh=nms_thresh,
                     feat_stride=self.feat_strides[i])
            for i in range(n_lvl)]

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = list(input.values()) if isinstance(input, Table) else list(input)
        feat_t, im_info = xs[0], xs[1]
        feats = (list(feat_t.values()) if isinstance(feat_t, Table)
                 else list(feat_t))
        new_state = dict(state)
        all_rois, all_valid = [], []
        for lvl, f in enumerate(feats):
            h, s = self.modules[0].apply(params["0"], state["0"], f,
                                         training=training, rng=None)
            new_state["0"] = s
            h = jax.nn.relu(h)
            scores, s = self.modules[1].apply(params["1"], state["1"], h,
                                              training=training, rng=None)
            new_state["1"] = s
            deltas, s = self.modules[2].apply(params["2"], state["2"], h,
                                              training=training, rng=None)
            new_state["2"] = s
            out, _ = self._proposals[lvl].apply(
                {}, {}, Table(scores, deltas, im_info), training=training)
            rois, valid = out.values()
            all_rois.append(rois)
            all_valid.append(valid)
        return Table(jnp.concatenate(all_rois),
                     jnp.concatenate(all_valid)), new_state

    def __repr__(self):
        return (f"RegionProposal(in={self.in_channels}, "
                f"levels={len(self.feat_strides)})")


class DetectionOutputFrcnn(AbstractModule):
    """Faster-R-CNN detection decode (reference ``DetectionOutputFrcnn``):
    softmax class scores + per-class box deltas against the proposal rois,
    per-class NMS on fixed budgets, global top-``max_per_image``. Input:
    Table(cls_logits (R, C), bbox_deltas (R, 4C), rois (R, 5),
    im_info (1, 3)[, roi_valid (R,)]); output Table(dets
    (max_per_image, 6) ``[label, score, x1, y1, x2, y2]``, valid
    (max_per_image,)). Class 0 is background."""

    def __init__(self, n_classes: int, score_thresh: float = 0.05,
                 nms_thresh: float = 0.5, max_per_image: int = 100):
        super().__init__()
        self.n_classes = int(n_classes)
        self.score_thresh = float(score_thresh)
        self.nms_thresh = float(nms_thresh)
        self.max_per_image = int(max_per_image)

    def apply(self, params, state, input, *, training=False, rng=None):
        xs = list(input.values()) if isinstance(input, Table) else list(input)
        logits, deltas, rois, im_info = xs[0], xs[1], xs[2], xs[3]
        roi_valid = xs[4] if len(xs) > 4 else None
        r = logits.shape[0]
        c = self.n_classes
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        info = im_info.reshape(-1)
        img_h, img_w = info[0], info[1]
        boxes_all = []
        scores_all = []
        labels_all = []
        for cls in range(1, c):   # skip background
            d = deltas[:, 4 * cls: 4 * cls + 4]
            boxes = decode_rcnn(rois[:, 1:], d)
            boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, img_w - 1),
                               jnp.clip(boxes[:, 1], 0, img_h - 1),
                               jnp.clip(boxes[:, 2], 0, img_w - 1),
                               jnp.clip(boxes[:, 3], 0, img_h - 1)], axis=1)
            sc = probs[:, cls]
            ok = sc >= self.score_thresh
            if roi_valid is not None:
                ok = ok & roi_valid
            order, keep = nms_mask(boxes, sc, self.nms_thresh, valid=ok)
            boxes_all.append(boxes[order])
            scores_all.append(jnp.where(keep, sc[order], -jnp.inf))
            labels_all.append(jnp.full((r,), cls, jnp.int32))
        boxes = jnp.concatenate(boxes_all)          # ((C-1)·R, 4)
        scores = jnp.concatenate(scores_all)
        labels = jnp.concatenate(labels_all)
        k = self.max_per_image
        if scores.shape[0] < k:   # static budget > candidates: pad invalid
            pad = k - scores.shape[0]
            boxes = jnp.concatenate([boxes, jnp.zeros((pad, 4))])
            scores = jnp.concatenate([scores, jnp.full((pad,), -jnp.inf)])
            labels = jnp.concatenate([labels, jnp.zeros((pad,), jnp.int32)])
        top = jnp.argsort(-scores)[:k]
        dets = jnp.concatenate([
            labels[top][:, None].astype(jnp.float32),
            scores[top][:, None], boxes[top]], axis=1)
        valid = jnp.isfinite(scores[top])
        dets = jnp.where(valid[:, None], dets, 0.0)
        return Table(dets, valid), state

    def __repr__(self):
        return (f"DetectionOutputFrcnn(classes={self.n_classes}, "
                f"nms={self.nms_thresh}, max={self.max_per_image})")


from bigdl_tpu.utils.serializer import register as _register  # noqa: E402

for _cls in (RoiAlign, FPN, Pooler, BoxHead, MaskHead, RegionProposal,
             DetectionOutputFrcnn):
    _register(_cls)
