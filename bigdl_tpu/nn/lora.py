"""LoRA — low-rank adaptation for fine-tuning.

No reference counterpart (pre-dates it); this is the modern fine-tuning
companion to ``freeze()``: instead of updating a pretrained ``W`` (out, in),
train only a rank-``r`` residual ``B @ A`` (``A`` (r, in), ``B`` (out, r)) —
``out = x Wᵀ + (x Aᵀ) Bᵀ · α/r``. Trainable parameters drop from ``out·in``
to ``r·(out+in)`` per adapted layer; the frozen base rides the gradient-
scale machinery (scale 0 → ``stop_gradient`` before the forward, so XLA
dead-codes the frozen backward entirely — byte-identical through training
AND no frozen backward compute, both pinned by test). Optimizer slots for
frozen leaves are trimmed to 0-size arrays (``OptimMethod.init_state_trimmed``
/ ``update_trimmed``), so slot memory is ~adapter-only — Adam on a LoRA'd
model no longer pays 2x base-param memory for moments that never move.

``apply_lora(model, rank)`` swaps every ``nn.Linear`` in the module tree
(containers and Graph nodes) for a :class:`LoRALinear` carrying the original
weights; ``merge_lora(model)`` bakes ``W + BA·α/r`` back into plain Linears
for serving (merged forward == adapted forward, pinned by test).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.abstractnn import AbstractModule, Container, TensorModule
from bigdl_tpu.nn.graph import Graph
from bigdl_tpu.nn.initialization import RandomNormal
from bigdl_tpu.nn.linear import Linear


class LoRALinear(TensorModule):
    """A Linear whose base weights are frozen and whose update lives in a
    trainable rank-``rank`` residual. Construct via :meth:`from_linear`."""

    def __init__(self, input_size: int, output_size: int, rank: int,
                 alpha: Optional[float] = None, with_bias: bool = True):
        super().__init__()
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank!r}")
        self.input_size, self.output_size = int(input_size), int(output_size)
        self.rank = int(rank)
        self.alpha = float(alpha) if alpha is not None else float(rank)
        self.with_bias = with_bias
        self.reset()

    def reset(self) -> None:
        # base starts zero (from_linear overwrites with the pretrained
        # weights); A gaussian / B zero is the standard init — the adapter
        # starts as an exact identity of the base
        p = {"weight": jnp.zeros((self.output_size, self.input_size),
                                 jnp.float32),
             "lora_a": jnp.asarray(RandomNormal(0.0, 0.02).init(
                 (self.rank, self.input_size),
                 fan_in=self.input_size, fan_out=self.rank)),
             "lora_b": jnp.zeros((self.output_size, self.rank), jnp.float32)}
        if self.with_bias:
            p["bias"] = jnp.zeros((self.output_size,), jnp.float32)
        self._params = p
        self.zero_grad_parameters()

    @classmethod
    def from_linear(cls, lin: Linear, rank: int,
                    alpha: Optional[float] = None) -> "LoRALinear":
        m = cls(lin.input_size, lin.output_size, rank, alpha,
                with_bias=lin.with_bias)
        base = lin.get_params()
        p = m.get_params()
        p["weight"] = base["weight"]
        if "bias" in base:
            p["bias"] = base["bias"]
        m.set_params(p)
        m.set_name(lin.name)
        return m

    def grad_scales(self) -> dict:
        # base weight/bias frozen; only the adapter trains (whole-module
        # freeze() still wins if requested)
        if self.is_frozen():
            return {k: 0.0 for k in self._params}
        return {k: (self.scale_w if k.startswith("lora") else 0.0)
                for k in self._params}

    def merged_weight(self, params) -> jnp.ndarray:
        return params["weight"] + (params["lora_b"] @ params["lora_a"]
                                   * (self.alpha / self.rank))

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.nn.linear import normalize_linear_input
        x, restore = normalize_linear_input(input)
        out = (x @ params["weight"].T
               + (x @ params["lora_a"].T) @ params["lora_b"].T
               * (self.alpha / self.rank))
        if self.with_bias:
            out = out + params["bias"]
        return restore(out), state

    def to_linear(self) -> Linear:
        """Bake the adapter into a plain Linear (serving form)."""
        lin = Linear(self.input_size, self.output_size,
                     with_bias=self.with_bias)
        p = self.get_params()
        merged = {"weight": self.merged_weight(p)}
        if self.with_bias:
            merged["bias"] = p["bias"]
        lin.set_params(merged)
        lin.set_name(self.name)
        return lin

    def __repr__(self):
        return (f"LoRALinear({self.input_size} -> {self.output_size}, "
                f"rank={self.rank}, alpha={self.alpha})")


def _patch_init_args(parent: AbstractModule, old, new) -> None:
    """Wrapper containers (TimeDistributed, Bottle, …) record their child in
    ``_init_args``; after a swap the recorded reference must follow, or the
    serializer re-encodes the STALE child (whose arrays the jit may have
    donated and deleted)."""
    args, kwargs = parent._init_args
    parent._init_args = (
        tuple(new if a is old else a for a in args),
        {k: (new if v is old else v) for k, v in kwargs.items()})


def _swap_modules(root: AbstractModule, replace) -> int:
    """Walk the container/Graph tree, calling ``replace(m)`` on every module;
    a non-None return swaps the module in place. Returns the swap count."""
    count = 0

    def walk(m):
        nonlocal count
        if isinstance(m, Graph):
            for node in m.exec_nodes:
                new = replace(node.module)
                if new is not None:
                    node.module = new
                    count += 1
                else:
                    walk(node.module)
            m.modules = [n.module for n in m.exec_nodes]
        elif isinstance(m, Container):
            for i, c in enumerate(m.modules):
                new = replace(c)
                if new is not None:
                    m.modules[i] = new
                    _patch_init_args(m, c, new)
                    count += 1
                else:
                    walk(c)

    walk(root)
    return count


def apply_lora(model: AbstractModule, rank: int,
               alpha: Optional[float] = None,
               freeze_rest: bool = True) -> int:
    """Swap every ``nn.Linear`` under ``model`` for a LoRA adapter carrying
    the original (now frozen) weights. Returns the number of adapted layers.

    ``freeze_rest=True`` (the LoRA convention) additionally freezes every
    OTHER module — convs, norms, embeddings — so ONLY the adapters train;
    ``freeze_rest=False`` leaves non-Linear layers trainable (partial
    fine-tuning). Set the model on the Optimizer AFTER adapting so the
    compiled step sees the new structure."""
    from bigdl_tpu.nn.attention import MultiHeadAttention

    if type(model) is Linear:
        raise ValueError(
            "apply_lora cannot swap a bare nn.Linear root in place — use "
            "LoRALinear.from_linear(model, rank) directly")
    # validate BEFORE freezing so a raise leaves the model untouched
    found = []

    def probe(m):
        if type(m) is Linear or (isinstance(m, MultiHeadAttention)
                                 and not getattr(m, 'lora_rank', None)):
            found.append(m)
        return None   # never swaps — count only

    _swap_modules(model, probe)
    if isinstance(model, MultiHeadAttention) and not getattr(model, 'lora_rank', None):
        found.append(model)
    if not found:
        raise ValueError(
            "apply_lora found no nn.Linear or MultiHeadAttention to adapt")
    if freeze_rest:
        model.freeze()

    n = 0

    def adapt(m):
        nonlocal n
        if type(m) is Linear:
            n += 1
            return LoRALinear.from_linear(m, rank, alpha)
        if isinstance(m, MultiHeadAttention) and not getattr(m, 'lora_rank', None):
            # in place: unfreeze the module (freeze_rest froze it), attach
            # adapters — grad_scales then freezes the base leaves only
            m.unfreeze()
            m.add_lora(rank, alpha)
            n += 1
        return None

    adapt(model)            # the root itself may be an adaptable attention
    _swap_modules(model, adapt)
    return n


def merge_lora(model: AbstractModule) -> int:
    """Bake every LoRA adapter under ``model`` back into a plain Linear
    (merged forward == adapted forward). Returns the merge count."""
    from bigdl_tpu.nn.attention import MultiHeadAttention

    if isinstance(model, LoRALinear):
        raise ValueError(
            "merge_lora cannot swap a bare LoRALinear root in place — use "
            "model.to_linear() directly")
    n = 0

    def merge(m):
        nonlocal n
        if isinstance(m, LoRALinear):
            n += 1
            return m.to_linear()
        if isinstance(m, MultiHeadAttention) and getattr(m, 'lora_rank', None):
            m.merge_lora()
            n += 1
        return None

    merge(model)            # the root itself may be an adapted attention
    _swap_modules(model, merge)
    return n
