"""Normalization + regularisation layers.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/BatchNormalization.scala``,
``SpatialBatchNormalization.scala``, ``Dropout.scala``, ``SpatialCrossMapLRN.scala``,
``Normalize.scala`` — unverified, mount empty): BatchNorm keeps running mean/var with
``momentum`` mixing (Torch convention: ``running = (1-momentum)*running + momentum*batch``),
normalises with biased batch variance in training and running stats in eval; affine
weight/bias optional. Dropout scales by ``1/(1-p)`` at train time.

TPU-native design: running stats are non-trainable buffers in the module ``state`` pytree —
the trainer threads them through the jitted step functionally, so there is no mutable-buffer
aliasing problem under ``jit``. Batch stats are computed per *program*: under plain
``jit`` over a mesh the global-batch reduction XLA emits matches the full-batch statistics,
and per-replica statistics (the reference's per-core BN, SURVEY.md §7.4) arise only inside
``shard_map`` bodies — there, ``BatchNormalization(sync=True)`` pmean's the batch moments
over the named mesh axis for global-batch statistics (tests/test_sync_batchnorm.py).

Dropout randomness comes from the ``rng`` key threaded by the trainer (per-step
``fold_in``; on a mesh XLA splits the key per shard automatically since the mask is computed
on the sharded activation shape).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.abstractnn import TensorModule
from bigdl_tpu.nn.initialization import InitializationMethod, Ones, RandomUniform, Zeros


class BatchNormalization(TensorModule):
    """BN over the feature axis of (N, F) input (reference ``nn.BatchNormalization``)."""

    _feature_axis = 1  # axis holding n_output; reduce over all other axes

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True,
                 init_weight: Optional[InitializationMethod] = None,
                 init_bias: Optional[InitializationMethod] = None,
                 sync: bool = False, sync_axis: str = "data"):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.init_weight = init_weight or RandomUniform(0.0, 1.0)
        self.init_bias = init_bias or Zeros()
        # Cross-replica sync-BN (SURVEY.md §7.4): with sync=True, batch
        # statistics are pmean'd over the named mesh axis, so per-shard batches
        # normalise with GLOBAL-batch statistics. Only meaningful inside a
        # shard_map body where `sync_axis` is bound (parallel/sharding.py); the
        # plain SPMD-jit DistriOptimizer path already computes global-batch
        # statistics by construction (the reduce spans the whole logical batch).
        # Default False = per-program stats (reference's per-worker BN).
        self.sync = sync
        self.sync_axis = sync_axis
        self.reset()

    def reset(self) -> None:
        n = self.n_output
        if self.affine:
            self._params = {
                "weight": jnp.asarray(self.init_weight.init((n,), n, n)),
                "bias": jnp.asarray(self.init_bias.init((n,), n, n)),
            }
        else:
            self._params = {}
        self._state = {
            "running_mean": jnp.zeros((n,), jnp.float32),
            "running_var": jnp.ones((n,), jnp.float32),
        }
        self.zero_grad_parameters()

    def _reduce_axes(self, x):
        return tuple(a for a in range(x.ndim) if a != self._feature_axis)

    def _bshape(self, x):
        return tuple(self.n_output if a == self._feature_axis else 1
                     for a in range(x.ndim))

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        axes = self._reduce_axes(x)
        shape = self._bshape(x)
        # fp32 island under mixed precision: batch statistics are reductions over
        # the whole batch — computing them in bf16 loses ~3 decimal digits (and
        # measures SLOWER on v5e: the converts break the conv-epilogue fusion),
        # and the running buffers are fp32 masters anyway. Normalisation happens
        # in fp32; only the (cheap, fusable) elementwise tail is cast back.
        x32 = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
        if training:
            import os
            if os.environ.get("BIGDL_BN_TWO_PASS", "0") == "1":
                # torch-exact accumulation order (centered two-pass variance);
                # raw second moment reconstructed only if sync needs it
                mean = jnp.mean(x32, axis=axes)
                var = jnp.var(x32, axis=axes)  # biased (Torch)
                mean2 = var + jnp.square(mean) if self.sync else None
            else:
                # Default: single-pass statistics (flax-style E[x^2]-E[x]^2 in
                # fp32) — one read of the activation instead of two. Worth ~10%
                # end-to-end on ResNet-50/v5e because both moments fuse into the
                # producing conv's epilogue (docs/performance.md, round 4).
                mean = jnp.mean(x32, axis=axes)
                mean2 = jnp.mean(jnp.square(x32), axis=axes)
            if self.sync:
                # global-batch statistics across the named mesh axis; combining
                # raw moments (not variances) is exact for equal shard sizes
                mean, mean2 = jax.lax.pmean((mean, mean2), self.sync_axis)
            if mean2 is not None:
                var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            n = x.size // self.n_output
            if self.sync:
                n = n * jax.lax.axis_size(self.sync_axis)  # static axis size
            unbiased = var * (n / max(n - 1, 1))
            m = self.momentum
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = jax.lax.rsqrt(var + self.eps).reshape(shape)
        out = (x32 - mean.reshape(shape)) * inv
        if self.affine:
            w = params["weight"].astype(jnp.float32)
            b = params["bias"].astype(jnp.float32)
            out = out * w.reshape(shape) + b.reshape(shape)
        return out.astype(x.dtype), new_state

    def __repr__(self):
        return f"{type(self).__name__}({self.n_output})"


class LayerNorm(TensorModule):
    """LayerNorm over the last axis, served by the fused Pallas kernel on TPU
    (kernels/layernorm.py) and the jnp reference elsewhere. Not in the
    reference's zoo (pre-dates it) — provided for the attention stack."""

    def __init__(self, n_output: int, eps: float = 1e-5):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.ones((self.n_output,), jnp.float32),
                        "bias": jnp.zeros((self.n_output,), jnp.float32)}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        from bigdl_tpu.kernels import fused_layer_norm
        return fused_layer_norm(input, params["weight"], params["bias"],
                                self.eps), state

    def __repr__(self):
        return f"LayerNorm({self.n_output})"


class RMSNorm(TensorModule):
    """Root-mean-square norm over the last axis (no centering, no bias) —
    the llama-family LayerNorm variant; one fewer reduction pass than
    LayerNorm, which is exactly the kind of HBM saving that matters on TPU.
    No reference counterpart (pre-dates it); pairs with the transformer
    stack's ``norm="rms"`` option."""

    def __init__(self, n_output: int, eps: float = 1e-6):
        super().__init__()
        self.n_output = n_output
        self.eps = eps
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.ones((self.n_output,), jnp.float32)}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        ms = jnp.mean(jnp.square(input.astype(jnp.float32)), axis=-1,
                      keepdims=True)
        out = input * jax.lax.rsqrt(ms + self.eps).astype(input.dtype)
        return out * params["weight"], state

    def __repr__(self):
        return f"RMSNorm({self.n_output}, eps={self.eps})"


class SpatialBatchNormalization(BatchNormalization):
    """BN over the channel axis of spatial input (reference
    ``nn.SpatialBatchNormalization``; channel axis follows ``nn.layout``)."""

    def folded_scale_shift(self, params, state):
        """Per-channel (scale, shift) with ``bn(y) == y*scale + shift`` under
        the running statistics — what the conv-bn fusion kernel folds into
        the adjacent conv's weights (kernels/conv_bn.py)."""
        from bigdl_tpu.kernels.conv_bn import fold_bn_scale_shift
        return fold_bn_scale_shift(params, state, self.eps)

    def _reduce_axes(self, x):
        from bigdl_tpu.nn import layout
        ca = layout.channel_axis(x.ndim)
        return tuple(a for a in range(x.ndim) if a != ca)

    def _bshape(self, x):
        from bigdl_tpu.nn import layout
        ca = layout.channel_axis(x.ndim)
        return tuple(self.n_output if a == ca else 1 for a in range(x.ndim))


class Dropout(TensorModule):
    """Inverted dropout (reference ``nn.Dropout``: ``initP`` keep-drop prob, scale)."""

    def __init__(self, init_p: float = 0.5, inplace: bool = False, scale: bool = True):
        super().__init__()
        if not 0.0 <= init_p < 1.0:
            raise ValueError("dropout probability must be in [0, 1)")
        self.p = init_p
        self.scale = scale

    def needs_rng(self) -> bool:
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.p == 0.0:
            return input, state
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, input.shape)
        out = jnp.where(mask, input, 0.0)
        if self.scale:
            out = out / keep
        return out, state

    def set_p(self, p: float) -> "Dropout":
        if not 0.0 <= p < 1.0:
            raise ValueError("dropout probability must be in [0, 1)")
        self.p = p
        self._apply_cache = {}  # p is baked into the jit trace — invalidate
        return self

    def __repr__(self):
        return f"Dropout({self.p})"


class SpatialDropout2D(TensorModule):
    """Drop whole channels of spatial input (reference ``nn.SpatialDropout2D``;
    channel axis follows ``nn.layout``)."""

    def __init__(self, init_p: float = 0.5):
        super().__init__()
        self.p = init_p

    def needs_rng(self) -> bool:
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.p == 0.0:
            return input, state
        from bigdl_tpu.nn import layout
        keep = 1.0 - self.p
        ca = layout.channel_axis(input.ndim)
        mask_shape = tuple(
            input.shape[a] if a == ca or (a == 0 and input.ndim == 4) else 1
            for a in range(input.ndim))
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, input / keep, 0.0), state


class GaussianDropout(TensorModule):
    """Multiplicative unit-mean gaussian noise (reference ``nn.GaussianDropout``)."""

    def __init__(self, rate: float):
        super().__init__()
        self.rate = rate

    def needs_rng(self) -> bool:
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.rate == 0.0:
            return input, state
        stddev = jnp.sqrt(self.rate / (1.0 - self.rate))
        noise = 1.0 + stddev * jax.random.normal(rng, input.shape)
        return input * noise, state


class GaussianNoise(TensorModule):
    """Additive zero-mean gaussian noise (reference ``nn.GaussianNoise``)."""

    def __init__(self, stddev: float):
        super().__init__()
        self.stddev = stddev

    def needs_rng(self) -> bool:
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training:
            return input, state
        return input + self.stddev * jax.random.normal(rng, input.shape), state


class SpatialCrossMapLRN(TensorModule):
    """Local response normalisation across channels (reference ``nn.SpatialCrossMapLRN``;
    used by Inception-v1/AlexNet-era models).

    ``out = x / (k + alpha/size * sum_{size local channels} x^2) ** beta``

    TPU-native: the windowed channel sum is one ``reduce_window`` — XLA fuses the whole
    expression; no im2col-style workspace needed.
    """

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def apply(self, params, state, input, *, training=False, rng=None):
        sq = jnp.square(input)
        # Windowed sum over the channel axis of NCHW; Torch pads size//2 before and
        # (size-1)//2 after, which matters for even window sizes. Formulated as a banded
        # C×C 0/1 matmul on the MXU rather than a padded reduce_window or cumsum+gather:
        # both of those miscompile on the axon TPU backend when fused next to a conv
        # (reduce_window loses its padding; the cumsum concat trips
        # space_to_batch_converter), while a matmul is the op TPUs are built around.
        from bigdl_tpu.nn import layout
        pre, post = self.size // 2, (self.size - 1) // 2
        c = sq.shape[layout.channel_axis(sq.ndim)]
        idx = jnp.arange(c)
        # band[i, j] = 1 where channel i falls in j's window [j - pre, j + post]
        band = ((idx[:, None] >= idx[None, :] - pre)
                & (idx[:, None] <= idx[None, :] + post)).astype(sq.dtype)
        eq = "nhwi,ij->nhwj" if layout.is_nhwc() else "nihw,ij->njhw"
        summed = jnp.einsum(eq, sq, band)
        denom = jnp.power(self.k + (self.alpha / self.size) * summed, self.beta)
        return input / denom, state

    def __repr__(self):
        return (f"SpatialCrossMapLRN({self.size}, {self.alpha}, "
                f"{self.beta}, {self.k})")


class Normalize(TensorModule):
    """Lp-normalise over the feature axis (reference ``nn.Normalize``)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10):
        super().__init__()
        self.p = p
        self.eps = eps

    def apply(self, params, state, input, *, training=False, rng=None):
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(input), axis=1, keepdims=True)
        else:
            norm = jnp.power(
                jnp.sum(jnp.power(jnp.abs(input), self.p), axis=1, keepdims=True),
                1.0 / self.p)
        return input / (norm + self.eps), state


class CMul(TensorModule):
    """Learnable per-element scale broadcast over the input (reference ``nn.CMul``)."""

    def __init__(self, size: tuple[int, ...]):
        super().__init__()
        self.size = tuple(size)
        self.reset()

    def reset(self) -> None:
        import numpy as np
        fan_in = int(np.prod(self.size))
        self._params = {"weight": jnp.asarray(
            RandomUniform().init(self.size, fan_in, fan_in))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        return input * params["weight"], state


class CAdd(TensorModule):
    """Learnable per-element bias broadcast over the input (reference ``nn.CAdd``)."""

    def __init__(self, size: tuple[int, ...]):
        super().__init__()
        self.size = tuple(size)
        self.reset()

    def reset(self) -> None:
        import numpy as np
        fan_in = int(np.prod(self.size))
        self._params = {"bias": jnp.asarray(
            RandomUniform().init(self.size, fan_in, fan_in))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        return input + params["bias"], state


class Mul(TensorModule):
    """Single learnable scalar gain (reference ``nn.Mul``)."""

    def __init__(self):
        super().__init__()
        self.reset()

    def reset(self) -> None:
        self._params = {"weight": jnp.asarray(RandomUniform().init((1,), 1, 1))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        return input * params["weight"][0], state


class Add(TensorModule):
    """Learnable bias vector added to (N, F) input (reference ``nn.Add``)."""

    def __init__(self, input_size: int):
        super().__init__()
        self.input_size = input_size
        self.reset()

    def reset(self) -> None:
        self._params = {"bias": jnp.asarray(
            RandomUniform().init((self.input_size,), self.input_size, self.input_size))}
        self.zero_grad_parameters()

    def apply(self, params, state, input, *, training=False, rng=None):
        return input + params["bias"], state


class SpatialWithinChannelLRN(TensorModule):
    """Within-channel local response normalisation (reference
    ``SpatialWithinChannelLRN``; Caffe WITHIN_CHANNEL mode):
    ``out = x / (1 + alpha/size^2 * sum_{size x size window} x^2) ** beta``
    per channel, SAME spatial padding. One ``reduce_window`` — XLA fuses it."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75):
        super().__init__()
        if size % 2 == 0:
            raise ValueError("LRN window size must be odd")
        self.size, self.alpha, self.beta = size, alpha, beta

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        sq = jnp.square(x)
        s = self.size
        window = (1, 1, s, s)
        sums = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, window, (1, 1, 1, 1), "SAME")
        denom = (1.0 + (self.alpha / (s * s)) * sums) ** self.beta
        out = x / denom
        if squeeze:
            out = out[0]
        return out, state


def _check_odd_kernel(kernel, who: str) -> None:
    kh, kw = kernel.shape
    if kh % 2 == 0 or kw % 2 == 0:
        raise ValueError(
            f"{who}: kernel must have odd dimensions for SAME-centered "
            f"neighborhoods, got {kh}x{kw}")


def _neighborhood_mean(x, kernel, channels):
    """Border-corrected weighted neighborhood mean over ALL channels of NCHW
    ``x``: conv with the (normalised) kernel summed across channels, divided by
    the conv of ones (edge correction), giving a (N, 1, H, W) mean map."""
    kh, kw = kernel.shape
    k = (kernel / (kernel.sum() * channels)).astype(x.dtype)
    w = jnp.broadcast_to(k[None, None], (1, channels, kh, kw))
    pad = [(kh // 2, kh // 2), (kw // 2, kw // 2)]
    mean = jax.lax.conv_general_dilated(
        x, w, (1, 1), pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ones = jnp.ones_like(x)
    coef = jax.lax.conv_general_dilated(
        ones, w, (1, 1), pad, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return mean / coef


class SpatialSubtractiveNormalization(TensorModule):
    """Subtract the weighted neighborhood mean (reference
    ``SpatialSubtractiveNormalization(nInputPlane, kernel)``; lua-torch
    semantics with border coefficient correction). Default kernel: 9x9 ones."""

    def __init__(self, n_input_plane: int = 1, kernel=None):
        super().__init__()
        self.n_input_plane = n_input_plane
        import numpy as _np
        self.kernel = _np.asarray(
            kernel if kernel is not None else _np.ones((9, 9)), _np.float32)
        if self.kernel.ndim == 1:  # separable 1-D kernel → outer product
            self.kernel = _np.outer(self.kernel, self.kernel).astype(_np.float32)
        _check_odd_kernel(self.kernel, "SpatialSubtractiveNormalization")

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        mean = _neighborhood_mean(x, jnp.asarray(self.kernel), self.n_input_plane)
        out = x - mean  # (N,1,H,W) broadcasts over channels
        if squeeze:
            out = out[0]
        return out, state


class SpatialDivisiveNormalization(TensorModule):
    """Divide by the local std-dev estimate (reference
    ``SpatialDivisiveNormalization``). With ``threshold`` given, lua-torch
    Threshold semantics: stds <= threshold are replaced by ``thresval``
    (default = threshold). Without it, the divisor is floored by its
    per-sample mean — a robust default for zero-variance regions."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float | None = None, thresval: float | None = None):
        super().__init__()
        self.n_input_plane = n_input_plane
        import numpy as _np
        self.kernel = _np.asarray(
            kernel if kernel is not None else _np.ones((9, 9)), _np.float32)
        if self.kernel.ndim == 1:
            self.kernel = _np.outer(self.kernel, self.kernel).astype(_np.float32)
        _check_odd_kernel(self.kernel, "SpatialDivisiveNormalization")
        self.threshold = threshold
        self.thresval = thresval if thresval is not None else threshold

    def apply(self, params, state, input, *, training=False, rng=None):
        x = input
        squeeze = x.ndim == 3
        if squeeze:
            x = x[None]
        var = _neighborhood_mean(jnp.square(x), jnp.asarray(self.kernel),
                                 self.n_input_plane)
        localstd = jnp.sqrt(jnp.maximum(var, 0.0))            # (N,1,H,W)
        if self.threshold is not None:
            divisor = jnp.where(localstd > self.threshold, localstd,
                                self.thresval)
        else:
            floor = jnp.mean(localstd, axis=(1, 2, 3), keepdims=True)
            divisor = jnp.maximum(localstd, floor)
        out = x / divisor
        if squeeze:
            out = out[0]
        return out, state


class SpatialContrastiveNormalization(TensorModule):
    """Subtractive then divisive normalisation (reference
    ``SpatialContrastiveNormalization``)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float | None = None, thresval: float | None = None):
        super().__init__()
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval)

    def apply(self, params, state, input, *, training=False, rng=None):
        out, _ = self.sub.apply({}, {}, input, training=training, rng=None)
        out, _ = self.div.apply({}, {}, out, training=training, rng=None)
        return out, state


class SpatialDropout1D(TensorModule):
    """Drop whole feature channels of (N, T, C) input (reference
    ``SpatialDropout1D``; keras temporal convention)."""

    def __init__(self, init_p: float = 0.5):
        super().__init__()
        self.p = init_p

    def needs_rng(self) -> bool:
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.p == 0.0:
            return input, state
        keep = 1.0 - self.p
        shape = (input.shape[0], 1, input.shape[-1]) if input.ndim == 3 \
            else (1, input.shape[-1])
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, input / keep, 0.0), state


class SpatialDropout3D(TensorModule):
    """Drop whole channels of NCDHW input (reference ``SpatialDropout3D``)."""

    def __init__(self, init_p: float = 0.5):
        super().__init__()
        self.p = init_p

    def needs_rng(self) -> bool:
        return True

    def apply(self, params, state, input, *, training=False, rng=None):
        if not training or self.p == 0.0:
            return input, state
        keep = 1.0 - self.p
        mask_shape = input.shape[:2] + (1,) * (input.ndim - 2)
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, input / keep, 0.0), state
