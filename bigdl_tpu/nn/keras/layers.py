"""Keras-1.2-style shape-inferring layers over the nn module zoo.

Reference parity (SURVEY.md §2.1, expected ``<dl>/nn/keras/`` — unverified): the
reference wraps its Torch-style layers in Keras layers that infer weight shapes from
the incoming activation shape; models are wired with ``Sequential.add`` or the
functional ``layer(node)`` API and trained via ``compile/fit``.

Design: a ``KerasLayer`` is a *builder* — ``build(input_shape)`` (batch dim excluded)
returns the concrete nn module, ``compute_output_shape`` propagates shapes. Data layout
is channels-first (NCHW), the framework-wide convention (TPU/XLA handles layout
assignment internally, so no 'tf' dim-ordering variant is needed).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

from bigdl_tpu import nn as N


def _resolve_init(init):
    """Keras-1.2 init strings → InitializationMethods; objects/None pass
    through (None lets each native layer keep its default)."""
    if init is None or not isinstance(init, str):
        return init
    from bigdl_tpu.nn.initialization import (
        MsraFiller, Ones, RandomNormal, RandomUniform, Xavier, Zeros,
    )
    table = {
        "glorot_uniform": Xavier, "glorot_normal": Xavier,
        "he_normal": MsraFiller, "he_uniform": MsraFiller,
        "uniform": RandomUniform, "normal": RandomNormal,
        "zero": Zeros, "one": Ones,
    }
    if init not in table:
        raise ValueError(f"unknown keras init {init!r}; have {sorted(table)}")
    return table[init]()


def _act(name: Optional[str]):
    if name is None or name == "linear":
        return None
    table = {
        "relu": N.ReLU, "tanh": N.Tanh, "sigmoid": N.Sigmoid,
        "hard_sigmoid": N.HardSigmoid, "softmax": N.SoftMax,
        "softplus": N.SoftPlus, "softsign": N.SoftSign, "elu": N.ELU,
        "gelu": N.GELU, "swish": N.Swish, "log_softmax": N.LogSoftMax,
    }
    if name not in table:
        raise ValueError(f"unknown activation {name!r}")
    return table[name]()


def _pair(v) -> tuple:
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


class KerasLayer:
    """Shape-inferring builder for one nn module."""

    def __init__(self, input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        self.input_shape = tuple(input_shape) if input_shape is not None else None
        self.name = name or f"{type(self).__name__.lower()}_{id(self) % 100000}"

    def build(self, input_shape: tuple) -> "N.AbstractModule":
        raise NotImplementedError

    def compute_output_shape(self, input_shape: tuple) -> tuple:
        raise NotImplementedError

    def _with_activation(self, module, activation: Optional[str]):
        act = _act(activation)
        if act is None:
            return module
        return N.Sequential().add(module).add(act)

    # functional API: layer(node) → new node with propagated shape
    def __call__(self, node):
        from bigdl_tpu.nn.keras.topology import KerasNode, merge_nodes
        if isinstance(node, (list, tuple)):
            node = merge_nodes(node)
        if not isinstance(node, KerasNode):
            raise TypeError("functional call expects Input()/layer output node(s)")
        module = self.build(node.shape)
        from bigdl_tpu.nn.graph import make_node
        return KerasNode(make_node(module, [node.node]),
                         self.compute_output_shape(node.shape))


class Dense(KerasLayer):
    def __init__(self, output_dim: int, activation: Optional[str] = None,
                 bias: bool = True, init=None, W_regularizer=None,
                 b_regularizer=None, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias
        self.init = init
        self.W_regularizer = W_regularizer
        self.b_regularizer = b_regularizer

    def build(self, input_shape):
        if len(input_shape) != 1:
            raise ValueError(
                f"Dense expects 1-D (features,) input shape, got {input_shape}; "
                "add Flatten() first")
        lin = N.Linear(input_shape[0], self.output_dim, with_bias=self.bias,
                       w_init=_resolve_init(self.init),
                       w_regularizer=self.W_regularizer,
                       b_regularizer=self.b_regularizer)
        return self._with_activation(lin, self.activation)

    def compute_output_shape(self, input_shape):
        return (self.output_dim,)


class Activation(KerasLayer):
    def __init__(self, activation: str, **kw):
        super().__init__(**kw)
        self.activation = activation

    def build(self, input_shape):
        act = _act(self.activation)
        return act if act is not None else N.Identity()

    def compute_output_shape(self, input_shape):
        return input_shape


class Dropout(KerasLayer):
    def __init__(self, p: float, **kw):
        super().__init__(**kw)
        self.p = p

    def build(self, input_shape):
        return N.Dropout(self.p)

    def compute_output_shape(self, input_shape):
        return input_shape


class Flatten(KerasLayer):
    def build(self, input_shape):
        return N.Reshape([int(math.prod(input_shape))])

    def compute_output_shape(self, input_shape):
        return (int(math.prod(input_shape)),)


class Reshape(KerasLayer):
    def __init__(self, target_shape: Sequence[int], **kw):
        super().__init__(**kw)
        self.target_shape = tuple(target_shape)

    def build(self, input_shape):
        return N.Reshape(list(self.target_shape))

    def compute_output_shape(self, input_shape):
        if math.prod(self.target_shape) != math.prod(input_shape):
            raise ValueError(
                f"cannot reshape {input_shape} into {self.target_shape}")
        return self.target_shape


class Convolution2D(KerasLayer):
    """2-D conv on (channels, h, w). ``border_mode``: 'valid' or 'same'."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None, border_mode: str = "valid",
                 subsample=(1, 1), bias: bool = True, init=None,
                 W_regularizer=None, b_regularizer=None, **kw):
        super().__init__(**kw)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode!r}")
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = _pair(subsample)
        self.bias = bias
        self.init = init
        self.W_regularizer = W_regularizer
        self.b_regularizer = b_regularizer

    def build(self, input_shape):
        c = input_shape[0]
        kh, kw = self.nb_row, self.nb_col
        pre_pad = None
        pw = ph = 0
        if self.border_mode == "same":
            if kh % 2 == 1 and kw % 2 == 1:
                pw, ph = (kw - 1) // 2, (kh - 1) // 2  # symmetric pad suffices
            else:
                # even kernel: SAME needs asymmetric (k-1)//2 / k//2 padding,
                # which the conv's symmetric pad can't express — pad explicitly.
                # Total pad k-1 yields out = ceil(in/stride) for every stride.
                pre_pad = N.SpatialZeroPadding((kw - 1) // 2, kw // 2,
                                               (kh - 1) // 2, kh // 2)
        conv = N.SpatialConvolution(
            c, self.nb_filter, kw, kh,
            self.subsample[1], self.subsample[0], pw, ph,
            with_bias=self.bias, w_init=_resolve_init(self.init),
            w_regularizer=self.W_regularizer,
            b_regularizer=self.b_regularizer)
        if pre_pad is not None:
            conv = N.Sequential().add(pre_pad).add(conv)
        return self._with_activation(conv, self.activation)

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        sh, sw = self.subsample
        if self.border_mode == "same":
            oh = (h + sh - 1) // sh
            ow = (w + sw - 1) // sw
        else:
            oh = (h - self.nb_row) // sh + 1
            ow = (w - self.nb_col) // sw + 1
        return (self.nb_filter, oh, ow)


class _Pooling2D(KerasLayer):
    _op = None

    def __init__(self, pool_size=(2, 2), strides=None, border_mode: str = "valid",
                 **kw):
        super().__init__(**kw)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode!r}")
        self.border_mode = border_mode

    def build(self, input_shape):
        if self.border_mode == "same":
            # SAME = ceil(h/s) per dimension; the pooling primitive computes the exact
            # asymmetric lo/hi padding itself (pad_mode="same"), which is correct for
            # odd, even, and mixed pool sizes alike — no ceil-mode double counting.
            return self._op(self.pool_size[1], self.pool_size[0],
                            self.strides[1], self.strides[0], pad_mode="same")
        return self._op(self.pool_size[1], self.pool_size[0],
                        self.strides[1], self.strides[0], 0, 0)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        sh, sw = self.strides
        if self.border_mode == "same":
            return (c, (h + sh - 1) // sh, (w + sw - 1) // sw)
        return (c, (h - self.pool_size[0]) // sh + 1,
                (w - self.pool_size[1]) // sw + 1)


class MaxPooling2D(_Pooling2D):
    @property
    def _op(self):
        return N.SpatialMaxPooling


class AveragePooling2D(_Pooling2D):
    @property
    def _op(self):
        return N.SpatialAveragePooling


class GlobalAveragePooling2D(KerasLayer):
    def build(self, input_shape):
        c, h, w = input_shape
        return N.Sequential().add(N.SpatialAveragePooling(w, h)) \
                             .add(N.Reshape([c]))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding=(1, 1), **kw):
        super().__init__(**kw)
        self.padding = _pair(padding)

    def build(self, input_shape):
        ph, pw = self.padding
        return N.SpatialZeroPadding(pw, pw, ph, ph)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h + 2 * self.padding[0], w + 2 * self.padding[1])


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99, **kw):
        super().__init__(**kw)
        self.epsilon = epsilon
        self.momentum = momentum

    def build(self, input_shape):
        # our BatchNormalization momentum is the update fraction (Torch style),
        # Keras momentum is the retain fraction
        mom = 1.0 - self.momentum
        if len(input_shape) == 3:
            return N.SpatialBatchNormalization(input_shape[0], eps=self.epsilon,
                                               momentum=mom)
        return N.BatchNormalization(input_shape[0], eps=self.epsilon, momentum=mom)

    def compute_output_shape(self, input_shape):
        return input_shape


class Embedding(KerasLayer):
    """(batch, seq) int indices → (batch, seq, output_dim). 0-based indices."""

    def __init__(self, input_dim: int, output_dim: int, init=None, **kw):
        super().__init__(**kw)
        self.input_dim, self.output_dim = input_dim, output_dim
        self.init = init

    def build(self, input_shape):
        return N.LookupTable(self.input_dim, self.output_dim, w_init=_resolve_init(self.init),
                             zero_based=True)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class _RecurrentLayer(KerasLayer):
    _cell = None

    def __init__(self, output_dim: int, return_sequences: bool = False,
                 go_backwards: bool = False, **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    def _make_cell(self, input_size):
        return self._cell(input_size, self.output_dim)

    def _check_input_shape(self, input_shape):
        if len(input_shape) != 2:
            raise ValueError(
                f"recurrent layers expect (time, features) input, got {input_shape}")

    def build(self, input_shape):
        self._check_input_shape(input_shape)
        seq = N.Sequential()
        if self.go_backwards:
            seq.add(_ReverseTime())
        seq.add(N.Recurrent(self._make_cell(input_shape[1])))
        if not self.return_sequences:
            seq.add(N.Select(2, -1))  # last timestep (1-based dims)
        return seq

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], self.output_dim)
        return (self.output_dim,)


class _ReverseTime(N.TensorModule):
    """Flip the time axis of (batch, time, feature)."""

    def apply(self, params, state, input, *, training=False, rng=None):
        return input[:, ::-1], state


class LSTM(_RecurrentLayer):
    @property
    def _cell(self):
        return N.LSTM


class GRU(_RecurrentLayer):
    @property
    def _cell(self):
        return N.GRU


class SimpleRNN(_RecurrentLayer):
    @property
    def _cell(self):
        return N.RnnCell


class Convolution1D(KerasLayer):
    """1-D conv on (steps, features) — keras-1.2 ``Convolution1D``. Maps onto
    the native NWC TemporalConvolution (one MXU contraction)."""

    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None, border_mode: str = "valid",
                 subsample_length: int = 1, bias: bool = True, init=None, **kw):
        super().__init__(**kw)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode!r}")
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.border_mode = border_mode
        self.subsample_length = subsample_length
        self.bias = bias
        self.init = init

    def build(self, input_shape):
        steps, features = input_shape
        conv = N.TemporalConvolution(features, self.nb_filter,
                                     self.filter_length,
                                     self.subsample_length,
                                     with_bias=self.bias, w_init=_resolve_init(self.init))
        if self.border_mode == "same":
            # exact TF/keras SAME split (shared helper — pooling.py)
            from bigdl_tpu.nn.pooling import _same_pad
            k, s = self.filter_length, self.subsample_length
            left, right = _same_pad(steps, k, s)
            needed = left + right
            seq = N.Sequential()
            if left:
                seq.add(N.Padding(1, -left, num_input_dims=2))
            if needed - left:
                seq.add(N.Padding(1, needed - left, num_input_dims=2))
            conv = seq.add(conv)
        return self._with_activation(conv, self.activation)

    def compute_output_shape(self, input_shape):
        steps, _ = input_shape
        k, s = self.filter_length, self.subsample_length
        if self.border_mode == "same":
            return ((steps + s - 1) // s, self.nb_filter)
        return ((steps - k) // s + 1, self.nb_filter)


class _Pooling1D(KerasLayer):
    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 **kw):
        super().__init__(**kw)
        self.pool_length = pool_length
        self.stride = stride if stride is not None else pool_length

    def compute_output_shape(self, input_shape):
        steps, f = input_shape
        return ((steps - self.pool_length) // self.stride + 1, f)


class MaxPooling1D(_Pooling1D):
    def build(self, input_shape):
        return N.TemporalMaxPooling(self.pool_length, self.stride)


class GlobalMaxPooling1D(KerasLayer):
    def build(self, input_shape):
        return N.Sequential().add(N.TemporalMaxPooling(-1)).add(
            N.Reshape([input_shape[1]]))

    def compute_output_shape(self, input_shape):
        return (input_shape[1],)


class GlobalMaxPooling2D(KerasLayer):
    def build(self, input_shape):
        c, h, w = input_shape
        return N.Sequential().add(N.SpatialMaxPooling(w, h)) \
                             .add(N.Reshape([c]))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class LayerNormalization(KerasLayer):
    """LayerNorm over the trailing feature axis (served by the Pallas kernel
    on TPU)."""

    def __init__(self, epsilon: float = 1e-5, **kw):
        super().__init__(**kw)
        self.epsilon = epsilon

    def build(self, input_shape):
        return N.LayerNorm(input_shape[-1], eps=self.epsilon)

    def compute_output_shape(self, input_shape):
        return input_shape


# --------------------------------------------------------------- round-3 batch
class Permute(KerasLayer):
    """Permute the non-batch dims (keras 1-based ``dims``)."""

    def __init__(self, dims, **kw):
        super().__init__(**kw)
        self.dims = tuple(int(d) for d in dims)

    def build(self, input_shape):
        # nn.Transpose swaps pairs; express an arbitrary permutation as a
        # sequence of (1-based, batch-counted) swaps via cycle decomposition
        perm = [0] + [d for d in self.dims]              # with batch dim
        swaps, cur = [], list(range(len(perm)))
        for i in range(len(perm)):
            while cur[i] != perm[i]:
                j = cur.index(perm[i])
                swaps.append((i + 1, j + 1))
                cur[i], cur[j] = cur[j], cur[i]
        return N.Transpose(swaps)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[d - 1] for d in self.dims)


class RepeatVector(KerasLayer):
    """(features,) → (n, features) per sample (keras ``RepeatVector``)."""

    def __init__(self, n: int, **kw):
        super().__init__(**kw)
        self.n = n

    def build(self, input_shape):
        return N.Replicate(self.n, dim=1, n_input_dims=1)

    def compute_output_shape(self, input_shape):
        return (self.n,) + tuple(input_shape)


class Masking(KerasLayer):
    def __init__(self, mask_value: float = 0.0, **kw):
        super().__init__(**kw)
        self.mask_value = mask_value

    def build(self, input_shape):
        return N.Masking(self.mask_value)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class Highway(KerasLayer):
    def __init__(self, activation: Optional[str] = None, bias: bool = True, **kw):
        super().__init__(**kw)
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        return N.Highway(input_shape[-1], with_bias=self.bias,
                         activation=_act(self.activation))

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class MaxoutDense(KerasLayer):
    def __init__(self, output_dim: int, nb_feature: int = 4, bias: bool = True,
                 **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.bias = bias

    def build(self, input_shape):
        return N.Maxout(input_shape[-1], self.output_dim, self.nb_feature,
                        with_bias=self.bias)

    def compute_output_shape(self, input_shape):
        return (self.output_dim,)


class _UpSamplingBase(KerasLayer):
    def __init__(self, size, **kw):
        super().__init__(**kw)
        self.size = size


class UpSampling1D(_UpSamplingBase):
    def __init__(self, length: int = 2, **kw):
        super().__init__(length, **kw)

    def build(self, input_shape):
        return N.UpSampling1D(self.size)

    def compute_output_shape(self, input_shape):
        t, f = input_shape
        return (t * self.size, f)


class UpSampling2D(_UpSamplingBase):
    def __init__(self, size=(2, 2), **kw):
        super().__init__(_pair(size), **kw)

    def build(self, input_shape):
        return N.UpSampling2D(self.size)

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        return (c, h * self.size[0], w * self.size[1])


class UpSampling3D(_UpSamplingBase):
    def __init__(self, size=(2, 2, 2), **kw):
        super().__init__(tuple(size), **kw)

    def build(self, input_shape):
        return N.UpSampling3D(self.size)

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        return (c, d * self.size[0], h * self.size[1], w * self.size[2])


class ZeroPadding1D(KerasLayer):
    def __init__(self, padding: int = 1, **kw):
        super().__init__(**kw)
        self.padding = padding

    def build(self, input_shape):
        seq = N.Sequential()
        seq.add(N.Padding(1, -self.padding, num_input_dims=2))
        seq.add(N.Padding(1, self.padding, num_input_dims=2))
        return seq

    def compute_output_shape(self, input_shape):
        t, f = input_shape
        return (t + 2 * self.padding, f)


class ZeroPadding3D(KerasLayer):
    def __init__(self, padding=(1, 1, 1), **kw):
        super().__init__(**kw)
        self.padding = tuple(padding)

    def build(self, input_shape):
        pd, ph, pw = self.padding
        seq = N.Sequential()
        for dim, p in ((2, pd), (3, ph), (4, pw)):
            if p:
                seq.add(N.Padding(dim, -p, num_input_dims=4))
                seq.add(N.Padding(dim, p, num_input_dims=4))
        return seq

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        pd, ph, pw = self.padding
        return (c, d + 2 * pd, h + 2 * ph, w + 2 * pw)


class Cropping1D(KerasLayer):
    def __init__(self, cropping=(1, 1), **kw):
        super().__init__(**kw)
        self.cropping = _pair(cropping)

    def build(self, input_shape):
        t, _ = input_shape
        a, b = self.cropping
        return N.Narrow(2, a + 1, t - a - b)

    def compute_output_shape(self, input_shape):
        t, f = input_shape
        return (t - sum(self.cropping), f)


class Cropping2D(KerasLayer):
    def __init__(self, cropping=((0, 0), (0, 0)), **kw):
        super().__init__(**kw)
        self.cropping = (tuple(cropping[0]), tuple(cropping[1]))

    def build(self, input_shape):
        return N.Cropping2D(self.cropping[0], self.cropping[1])

    def compute_output_shape(self, input_shape):
        c, h, w = input_shape
        (t, b), (l, r) = self.cropping
        return (c, h - t - b, w - l - r)


class Cropping3D(KerasLayer):
    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)), **kw):
        super().__init__(**kw)
        self.cropping = tuple(tuple(c) for c in cropping)

    def build(self, input_shape):
        return N.Cropping3D(*self.cropping)

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        (a0, a1), (b0, b1), (c0, c1) = self.cropping
        return (c, d - a0 - a1, h - b0 - b1, w - c0 - c1)


class AveragePooling1D(_Pooling1D):
    def build(self, input_shape):
        return N.TemporalAveragePooling(self.pool_length, self.stride)


class GlobalAveragePooling1D(KerasLayer):
    def build(self, input_shape):
        return N.Sequential().add(N.TemporalAveragePooling(-1)).add(
            N.Reshape([input_shape[1]]))

    def compute_output_shape(self, input_shape):
        return (input_shape[1],)


class _Pooling3D(KerasLayer):
    def __init__(self, pool_size=(2, 2, 2), strides=None, **kw):
        super().__init__(**kw)
        self.pool_size = tuple(pool_size)
        self.strides = tuple(strides) if strides is not None else self.pool_size

    def compute_output_shape(self, input_shape):
        c, d, h, w = input_shape
        kd, kh, kw_ = self.pool_size
        sd, sh, sw = self.strides
        return (c, (d - kd) // sd + 1, (h - kh) // sh + 1, (w - kw_) // sw + 1)


class MaxPooling3D(_Pooling3D):
    def build(self, input_shape):
        kd, kh, kw_ = self.pool_size
        sd, sh, sw = self.strides
        return N.VolumetricMaxPooling(kd, kw_, kh, sd, sw, sh)


class AveragePooling3D(_Pooling3D):
    def build(self, input_shape):
        kd, kh, kw_ = self.pool_size
        sd, sh, sw = self.strides
        return N.VolumetricAveragePooling(kd, kw_, kh, sd, sw, sh)


class Convolution3D(KerasLayer):
    def __init__(self, nb_filter: int, kernel_dim1: int, kernel_dim2: int,
                 kernel_dim3: int, activation: Optional[str] = None,
                 subsample=(1, 1, 1), border_mode: str = "valid",
                 bias: bool = True, **kw):
        super().__init__(**kw)
        if border_mode != "valid":
            raise ValueError("Convolution3D supports border_mode='valid' only")
        self.nb_filter = nb_filter
        self.kernel = (kernel_dim1, kernel_dim2, kernel_dim3)
        self.activation = activation
        self.subsample = tuple(subsample)
        self.bias = bias

    def build(self, input_shape):
        c = input_shape[0]
        kd, kh, kw_ = self.kernel
        sd, sh, sw = self.subsample
        conv = N.VolumetricConvolution(c, self.nb_filter, kd, kw_, kh,
                                       sd, sw, sh, with_bias=self.bias)
        return self._with_activation(conv, self.activation)

    def compute_output_shape(self, input_shape):
        _, d, h, w = input_shape
        kd, kh, kw_ = self.kernel
        sd, sh, sw = self.subsample
        return (self.nb_filter, (d - kd) // sd + 1, (h - kh) // sh + 1,
                (w - kw_) // sw + 1)


class Deconvolution2D(KerasLayer):
    """Transposed conv (keras-1.2 ``Deconvolution2D``) over NCHW."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 subsample=(1, 1), activation: Optional[str] = None,
                 bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.nb_row, self.nb_col = nb_row, nb_col
        self.subsample = _pair(subsample)
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        c = input_shape[0]
        deconv = N.SpatialFullConvolution(
            c, self.nb_filter, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], no_bias=not self.bias)
        return self._with_activation(deconv, self.activation)

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        sh, sw = self.subsample
        return (self.nb_filter, (h - 1) * sh + self.nb_row,
                (w - 1) * sw + self.nb_col)


class AtrousConvolution2D(KerasLayer):
    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 atrous_rate=(1, 1), activation: Optional[str] = None,
                 bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.nb_row, self.nb_col = nb_row, nb_col
        self.atrous_rate = _pair(atrous_rate)
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        c = input_shape[0]
        conv = N.SpatialDilatedConvolution(
            c, self.nb_filter, self.nb_col, self.nb_row, 1, 1, 0, 0,
            self.atrous_rate[1], self.atrous_rate[0], with_bias=self.bias)
        return self._with_activation(conv, self.activation)

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        eff_h = self.nb_row + (self.nb_row - 1) * (self.atrous_rate[0] - 1)
        eff_w = self.nb_col + (self.nb_col - 1) * (self.atrous_rate[1] - 1)
        return (self.nb_filter, h - eff_h + 1, w - eff_w + 1)


class SeparableConvolution2D(KerasLayer):
    """Depthwise (grouped) conv + 1x1 pointwise (keras
    ``SeparableConvolution2D``) — two MXU contractions."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 depth_multiplier: int = 1, activation: Optional[str] = None,
                 subsample=(1, 1), border_mode: str = "valid",
                 bias: bool = True, **kw):
        super().__init__(**kw)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"border_mode must be valid|same, got {border_mode!r}")
        self.nb_filter = nb_filter
        self.nb_row, self.nb_col = nb_row, nb_col
        self.depth_multiplier = depth_multiplier
        self.activation = activation
        self.subsample = _pair(subsample)
        self.border_mode = border_mode
        self.bias = bias

    def build(self, input_shape):
        c = input_shape[0]
        pad = -1 if self.border_mode == "same" else 0
        depthwise = N.SpatialConvolution(
            c, c * self.depth_multiplier, self.nb_col, self.nb_row,
            self.subsample[1], self.subsample[0], pad, pad,
            n_group=c, with_bias=False)
        pointwise = N.SpatialConvolution(
            c * self.depth_multiplier, self.nb_filter, 1, 1,
            with_bias=self.bias)
        seq = N.Sequential().add(depthwise).add(pointwise)
        return self._with_activation(seq, self.activation)

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        sh, sw = self.subsample
        if self.border_mode == "same":
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            oh = (h - self.nb_row) // sh + 1
            ow = (w - self.nb_col) // sw + 1
        return (self.nb_filter, oh, ow)


class LocallyConnected1D(KerasLayer):
    def __init__(self, nb_filter: int, filter_length: int,
                 subsample_length: int = 1, activation: Optional[str] = None,
                 bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.subsample_length = subsample_length
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        t, f = input_shape
        m = N.LocallyConnected1D(t, f, self.nb_filter, self.filter_length,
                                 self.subsample_length, with_bias=self.bias)
        return self._with_activation(m, self.activation)

    def compute_output_shape(self, input_shape):
        t, _ = input_shape
        return ((t - self.filter_length) // self.subsample_length + 1,
                self.nb_filter)


class LocallyConnected2D(KerasLayer):
    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 subsample=(1, 1), activation: Optional[str] = None,
                 bias: bool = True, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.nb_row, self.nb_col = nb_row, nb_col
        self.subsample = _pair(subsample)
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        c, h, w = input_shape
        m = N.LocallyConnected2D(c, w, h, self.nb_filter, self.nb_col,
                                 self.nb_row, self.subsample[1],
                                 self.subsample[0], with_bias=self.bias)
        return self._with_activation(m, self.activation)

    def compute_output_shape(self, input_shape):
        _, h, w = input_shape
        sh, sw = self.subsample
        return (self.nb_filter, (h - self.nb_row) // sh + 1,
                (w - self.nb_col) // sw + 1)


class SpatialDropout1D(KerasLayer):
    def __init__(self, p: float = 0.5, **kw):
        super().__init__(**kw)
        self.p = p

    def build(self, input_shape):
        return N.SpatialDropout1D(self.p)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class SpatialDropout2D(SpatialDropout1D):
    def build(self, input_shape):
        return N.SpatialDropout2D(self.p)


class SpatialDropout3D(SpatialDropout1D):
    def build(self, input_shape):
        return N.SpatialDropout3D(self.p)


class GaussianDropout(KerasLayer):
    def __init__(self, p: float = 0.5, **kw):
        super().__init__(**kw)
        self.p = p

    def build(self, input_shape):
        return N.GaussianDropout(self.p)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class GaussianNoise(KerasLayer):
    def __init__(self, sigma: float = 0.1, **kw):
        super().__init__(**kw)
        self.sigma = sigma

    def build(self, input_shape):
        return N.GaussianNoise(self.sigma)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class LeakyReLU(KerasLayer):
    def __init__(self, alpha: float = 0.3, **kw):
        super().__init__(**kw)
        self.alpha = alpha

    def build(self, input_shape):
        return N.LeakyReLU(self.alpha)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class ELU(KerasLayer):
    def __init__(self, alpha: float = 1.0, **kw):
        super().__init__(**kw)
        self.alpha = alpha

    def build(self, input_shape):
        return N.ELU(self.alpha)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class ThresholdedReLU(KerasLayer):
    def __init__(self, theta: float = 1.0, **kw):
        super().__init__(**kw)
        self.theta = theta

    def build(self, input_shape):
        return N.Threshold(self.theta, 0.0)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class PReLU(KerasLayer):
    """Learnable leaky slope. Slope layout by input rank: (features,) →
    per-feature; (C, H, W[, ...]) → per-channel (nn.PReLU broadcasts on the
    channel axis); temporal (steps, features) → ONE shared slope — the native
    PReLU has no per-last-axis broadcast, and a per-timestep slope would be
    silently wrong semantics."""

    def build(self, input_shape):
        if len(input_shape) == 1:
            return N.PReLU(input_shape[0])   # (N, F): per-feature on axis -1
        if len(input_shape) >= 3:
            return N.PReLU(input_shape[0])   # NCHW-style: per-channel
        return N.PReLU(0)                    # (steps, features): shared scalar

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class TimeDistributed(KerasLayer):
    """Apply an inner keras layer at every timestep of (time, ...) input."""

    def __init__(self, layer: KerasLayer, **kw):
        super().__init__(**kw)
        self.layer = layer

    def build(self, input_shape):
        return N.TimeDistributed(self.layer.build(tuple(input_shape[1:])))

    def compute_output_shape(self, input_shape):
        inner = self.layer.compute_output_shape(tuple(input_shape[1:]))
        return (input_shape[0],) + tuple(inner)


class Bidirectional(KerasLayer):
    """Wrap a recurrent keras layer with a backward clone (merge: concat/sum)."""

    def __init__(self, layer: "_RecurrentLayer", merge_mode: str = "concat",
                 **kw):
        super().__init__(**kw)
        if merge_mode not in ("concat", "sum"):
            raise ValueError("merge_mode must be 'concat' or 'sum'")
        if not isinstance(layer, _RecurrentLayer):
            raise TypeError("Bidirectional wraps a recurrent keras layer")
        if layer.go_backwards:
            raise ValueError(
                "Bidirectional already runs both directions; go_backwards on "
                "the wrapped layer has no keras-consistent meaning here")
        self.layer = layer
        self.merge_mode = merge_mode

    def build(self, input_shape):
        cell = self.layer._make_cell(input_shape[1])
        merge = "concat" if self.merge_mode == "concat" else "add"
        if self.layer.return_sequences:
            # BiRecurrent re-reverses the backward outputs so step t aligns
            return N.Sequential().add(N.BiRecurrent(cell, merge=merge))
        # return_sequences=False: keras semantics = [fwd FULL-sequence summary,
        # bwd FULL-sequence summary]. BiRecurrent's re-reversed stream puts the
        # backward summary at t=0, so Select(-1) would grab a one-step state;
        # run the two directions explicitly and take each one's LAST output.
        bwd_cell = cell.clone()
        bwd_cell.reset()
        concat = N.ConcatTable()
        concat.add(N.Sequential().add(N.Recurrent(cell)).add(N.Select(2, -1)))
        concat.add(N.Sequential().add(_ReverseTime())
                   .add(N.Recurrent(bwd_cell)).add(N.Select(2, -1)))
        joiner = N.JoinTable(1, n_input_dims=1) if merge == "concat" \
            else N.CAddTable()
        return N.Sequential().add(concat).add(joiner)

    def compute_output_shape(self, input_shape):
        width = self.layer.output_dim * (2 if self.merge_mode == "concat" else 1)
        if self.layer.return_sequences:
            return (input_shape[0], width)
        return (width,)


class SReLU(KerasLayer):
    """S-shaped ReLU with four learnable parameter tensors (keras-1.2
    ``SReLU``); ``shared_axes`` shares parameters across those (1-based,
    non-batch) axes."""

    def __init__(self, shared_axes=None, **kw):
        super().__init__(**kw)
        self.shared_axes = shared_axes

    def build(self, input_shape):
        return N.SReLU(shape=tuple(input_shape),
                       shared_axes=self.shared_axes)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


class GlobalAveragePooling3D(KerasLayer):
    def build(self, input_shape):
        c, t, h, w = input_shape
        return N.Sequential() \
            .add(N.VolumetricAveragePooling(t, w, h)) \
            .add(N.Reshape([c]))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class GlobalMaxPooling3D(KerasLayer):
    def build(self, input_shape):
        c, t, h, w = input_shape
        return N.Sequential() \
            .add(N.VolumetricMaxPooling(t, w, h)) \
            .add(N.Reshape([c]))

    def compute_output_shape(self, input_shape):
        return (input_shape[0],)


class ConvLSTM2D(_RecurrentLayer):
    """Convolutional LSTM over (time, channels, rows, cols) input (keras-1.2
    ``ConvLSTM2D``); maps onto the native peephole ConvLSTM cell unrolled by
    ``nn.Recurrent`` (lax.scan — two MXU conv GEMMs per step). Reuses the
    shared recurrent scaffolding (go_backwards/return_sequences)."""

    def __init__(self, nb_filter: int, nb_kernel: int = 3,
                 return_sequences: bool = False, go_backwards: bool = False,
                 with_peephole: bool = True, **kw):
        super().__init__(nb_filter, return_sequences=return_sequences,
                         go_backwards=go_backwards, **kw)
        self.nb_kernel = nb_kernel
        self.with_peephole = with_peephole

    def _make_cell(self, input_size):
        return N.ConvLSTMPeephole(
            input_size, self.output_dim, self.nb_kernel, self.nb_kernel,
            with_peephole=self.with_peephole)

    def _check_input_shape(self, input_shape):
        if len(input_shape) != 4:
            raise ValueError(
                f"ConvLSTM2D expects (time, channels, rows, cols) input, "
                f"got {input_shape}")

    def compute_output_shape(self, input_shape):
        t, _, h, w = input_shape
        if self.return_sequences:
            return (t, self.output_dim, h, w)
        return (self.output_dim, h, w)


class Merge(KerasLayer):
    """The keras-1 ``Merge`` LAYER (reference ``keras.Merge``; the functional
    form is :func:`~bigdl_tpu.nn.keras.merge`): combines several inputs by
    ``mode`` (concat|sum|mul|ave|max|dot|cos).

    Two idioms:
    - functional: ``Merge(mode="sum")([node_a, node_b])``;
    - Sequential-first-layer: ``Merge(layers=[branch_a, branch_b],
      mode="concat")`` where each branch is a KerasLayer with a declared
      ``input_shape`` — the built module is a ``ParallelTable`` of the
      branches feeding the merge, consuming a Table of inputs.
    """

    @staticmethod
    def _branch_spec(i, l):
        """(input_shape, output_shape, build_thunk) for a branch — a
        KerasLayer with declared input_shape, or a built keras Sequential/
        Model (which knows its own shapes)."""
        if hasattr(l, "_module") and hasattr(l, "_input_shape"):
            shape = l._input_shape()
            if shape is None:
                raise ValueError(f"Merge branch {i}: empty Sequential")
            return shape, l.output_shape, (lambda: l._module())
        if getattr(l, "input_shape", None) is None:
            raise ValueError(
                f"Merge branch {i} needs a declared input_shape (or pass a "
                f"built keras Sequential/Model)")
        return (l.input_shape, l.compute_output_shape(l.input_shape),
                (lambda: l.build(l.input_shape)))

    def __init__(self, layers=None, mode: str = "sum", concat_axis: int = 1,
                 **kw):
        super().__init__(**kw)
        self.layers = list(layers) if layers is not None else None
        self.mode = mode
        self.concat_axis = concat_axis
        if self.layers is not None:
            if len(self.layers) < 2:
                raise ValueError(
                    f"Merge needs at least 2 branches, got {len(self.layers)}")
            specs = [self._branch_spec(i, l)
                     for i, l in enumerate(self.layers)]
            self.input_shape = tuple(s[0] for s in specs)

    def __call__(self, node):
        from bigdl_tpu.nn.keras.topology import merge_nodes
        if self.layers is not None:
            raise ValueError(
                "functional Merge takes the nodes directly — drop `layers`")
        if not isinstance(node, (list, tuple)):
            raise TypeError("Merge expects a LIST of nodes")
        return merge_nodes(list(node), self.mode, self.concat_axis)

    def build(self, input_shape):
        from bigdl_tpu.nn.keras.topology import _merge_module
        if self.layers is not None:
            specs = [self._branch_spec(i, l)
                     for i, l in enumerate(self.layers)]
            inner, _ = _merge_module(self.mode, [s[1] for s in specs],
                                     self.concat_axis)
            par = N.ParallelTable()
            for _, _, build in specs:
                par.add(build())
            return N.Sequential().add(par).add(inner)
        # bare Table input: input_shape is a tuple of per-input shapes
        if not input_shape or not isinstance(input_shape[0], (tuple, list)):
            raise ValueError(
                f"Merge without `layers` needs multiple inputs (a tuple of "
                f"shapes), got {input_shape}")
        inner, _ = _merge_module(self.mode, list(input_shape),
                                 self.concat_axis)
        return inner

    def compute_output_shape(self, input_shape):
        from bigdl_tpu.nn.keras.topology import _merge_module
        if self.layers is not None:
            shapes = [self._branch_spec(i, l)[1]
                      for i, l in enumerate(self.layers)]
        else:
            shapes = list(input_shape)
        _, shape = _merge_module(self.mode, shapes, self.concat_axis)
        return shape
