"""Keras-1.2-style user API (reference ``<dl>/nn/keras/`` + python
``bigdl.nn.keras`` — SURVEY.md §2.1, unverified)."""

from bigdl_tpu.nn.keras.layers import (
    Merge,
    Activation, AtrousConvolution2D, AveragePooling1D, AveragePooling2D,
    AveragePooling3D, BatchNormalization, Bidirectional, Convolution1D,
    Convolution2D, Convolution3D, Cropping1D, Cropping2D, Cropping3D,
    ConvLSTM2D, Deconvolution2D, Dense, Dropout, ELU, Embedding, Flatten, GRU,
    GaussianDropout, GaussianNoise, GlobalAveragePooling1D,
    GlobalAveragePooling2D, GlobalAveragePooling3D, GlobalMaxPooling1D,
    GlobalMaxPooling2D, GlobalMaxPooling3D, Highway,
    KerasLayer, LSTM, LayerNormalization, LeakyReLU, LocallyConnected1D,
    LocallyConnected2D, Masking, MaxPooling1D, MaxPooling2D, MaxPooling3D,
    MaxoutDense, PReLU, Permute, RepeatVector, Reshape, SReLU,
    SeparableConvolution2D,
    SimpleRNN, SpatialDropout1D, SpatialDropout2D, SpatialDropout3D,
    ThresholdedReLU, TimeDistributed, UpSampling1D, UpSampling2D, UpSampling3D,
    ZeroPadding1D, ZeroPadding2D, ZeroPadding3D,
)
from bigdl_tpu.nn.keras.topology import (
    Input, KerasModel, KerasNode, Model, Sequential, merge,
)

# Keras-2 style aliases
Conv2D = Convolution2D
Conv1D = Convolution1D
Conv3D = Convolution3D

__all__ = [
    "Activation", "AtrousConvolution2D", "AveragePooling1D", "AveragePooling2D",
    "AveragePooling3D", "BatchNormalization", "Bidirectional", "Conv1D",
    "Conv2D", "Conv3D", "Convolution1D", "Convolution2D", "Convolution3D",
    "Cropping1D", "Cropping2D", "Cropping3D", "Deconvolution2D", "Dense",
    "Dropout", "ELU", "Embedding", "Flatten", "GRU", "GaussianDropout",
    "GaussianNoise", "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalMaxPooling1D", "GlobalMaxPooling2D", "Highway", "Input",
    "KerasLayer", "KerasModel", "KerasNode", "LSTM", "LayerNormalization",
    "LeakyReLU", "LocallyConnected1D", "LocallyConnected2D", "Masking",
    "MaxPooling1D", "MaxPooling2D", "MaxPooling3D", "MaxoutDense", "Model",
    "PReLU", "Permute", "RepeatVector", "Reshape", "SeparableConvolution2D",
    "Sequential", "SimpleRNN", "SpatialDropout1D", "SpatialDropout2D",
    "SpatialDropout3D", "ThresholdedReLU", "TimeDistributed", "UpSampling1D",
    "UpSampling2D", "UpSampling3D", "ZeroPadding1D", "ZeroPadding2D",
    "ZeroPadding3D", "Merge", "merge",
]
